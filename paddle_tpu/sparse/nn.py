"""`paddle.sparse.nn` parity (reference `python/paddle/sparse/nn/`):
layers over sparse COO tensors (point-cloud style NDHWC actives).

TPU-first design note: XLA has no sparse-gather convolution kernel, and
at the densities these layers see in practice the MXU's dense conv
throughput wins over host-side gather orchestration — so each layer
densifies the COO input, runs the dense TPU kernel, and re-sparsifies.
Submanifold variants (SubmConv*) keep the reference semantics exactly:
outputs exist only at input active sites (the dense result is masked to
the input's activity pattern). `sparse_coo_tensor.to_dense()` and the
mask live on device, so the round-trip stays inside XLA.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..framework.core import Tensor
from ..nn.layer.layers import Layer
from . import SparseCooTensor, relu as _sp_relu, sparse_coo_tensor

__all__ = ["ReLU", "ReLU6", "LeakyReLU", "Softmax", "BatchNorm",
           "SyncBatchNorm", "Conv2D", "Conv3D", "SubmConv2D", "SubmConv3D",
           "MaxPool3D"]


def _dense(x):
    return x.to_dense() if isinstance(x, SparseCooTensor) else x


def _resparsify(dense_t, mask=None):
    """Dense Tensor -> COO. ``mask`` ([*site dims] bool) names the active
    sites explicitly (kernel-reachable sites for sparse conv — a biased
    conv makes every VALUE nonzero, so value-nonzeroness alone would
    densify); defaults to the nonzero pattern."""
    arr = dense_t._data
    if mask is None:
        mask = jnp.any(arr != 0, axis=-1) if arr.ndim > 1 else arr != 0
    nz = jnp.nonzero(mask)
    idx = jnp.stack(nz)
    vals = arr[nz]
    return sparse_coo_tensor(Tensor(idx), Tensor(vals),
                             shape=list(arr.shape))


class _ValueAct(Layer):
    """Activations act on the stored values only (zero maps to zero for
    all of these, so the activity pattern is preserved — same contract as
    the reference's sparse activations)."""

    _fn_name = None

    def forward(self, x):
        from ..nn import functional as F

        fn = getattr(F, self._fn_name)
        if isinstance(x, SparseCooTensor):
            return sparse_coo_tensor(x.indices(), fn(x.values()), x.shape)
        return fn(x)


class ReLU(_ValueAct):
    _fn_name = "relu"

    def forward(self, x):
        return _sp_relu(x)


class ReLU6(_ValueAct):
    _fn_name = "relu6"


class LeakyReLU(_ValueAct):
    _fn_name = "leaky_relu"

    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        from ..nn import functional as F

        if isinstance(x, SparseCooTensor):
            return sparse_coo_tensor(
                x.indices(), F.leaky_relu(x.values(), self.negative_slope),
                x.shape)
        return F.leaky_relu(x, self.negative_slope)


class Softmax(Layer):
    """Softmax over the last dense axis of the values (reference: softmax
    over each row's stored entries for CSR; COO here normalizes the
    trailing channel axis of the values)."""

    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        from ..nn import functional as F

        if isinstance(x, SparseCooTensor):
            return sparse_coo_tensor(x.indices(),
                                     F.softmax(x.values(), axis=self.axis),
                                     x.shape)
        return F.softmax(x, axis=self.axis)


class BatchNorm(Layer):
    """BatchNorm over the channel (last) axis of the active values
    (parity: paddle.sparse.nn.BatchNorm — statistics over actives only)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 use_global_stats=None, name=None):
        super().__init__()
        from ..nn import BatchNorm1D

        self._bn = BatchNorm1D(num_features, momentum=momentum,
                               epsilon=epsilon, weight_attr=weight_attr,
                               bias_attr=bias_attr, data_format="NLC")

    def forward(self, x):
        if isinstance(x, SparseCooTensor):
            out = self._bn(x.values().unsqueeze(0)).squeeze(0)
            return sparse_coo_tensor(x.indices(), out, x.shape)
        return self._bn(x)


class SyncBatchNorm(BatchNorm):
    """Under GSPMD the batch statistics are already global across the dp
    mesh axis (the reduction compiles to a cross-replica all-reduce), so
    Sync == BatchNorm here, like the dense SyncBatchNorm."""


def _to_channels_first(arr, nd):
    # NDHWC -> NCDHW (dense kernels are NC-first)
    perm = (0, nd + 1) + tuple(range(1, nd + 1))
    return jnp.transpose(arr, perm)


def _to_channels_last(arr, nd):
    perm = (0,) + tuple(range(2, nd + 2)) + (1,)
    return jnp.transpose(arr, perm)


class _SparseConvNd(Layer):
    _nd = 3
    _subm = False

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format=None,
                 key=None):
        super().__init__()
        from ..nn import Conv2D as DenseConv2D, Conv3D as DenseConv3D

        cls = DenseConv3D if self._nd == 3 else DenseConv2D
        ks = kernel_size if isinstance(kernel_size, (list, tuple)) \
            else [kernel_size] * self._nd
        self._ks = list(ks)
        if self._subm:
            # submanifold semantics fix the site lattice: outputs live
            # exactly at input active sites, which requires stride 1 and
            # same-padding (enforced, not silently overridden)
            strides = stride if isinstance(stride, (list, tuple)) \
                else [stride] * self._nd
            if any(s != 1 for s in strides):
                raise ValueError(
                    "SubmConv requires stride 1 (the active-site lattice "
                    "is preserved); use the non-submanifold Conv for "
                    "strided downsampling")
            pads = padding if isinstance(padding, (list, tuple)) \
                else [padding] * self._nd
            if any(p != 0 for p in pads):
                raise ValueError(
                    "SubmConv manages its own same-padding; pass "
                    "padding=0 (the default)")
            stride = 1
            padding = 0  # padded manually (even kernels need asymmetric)
        # keep the USER's forms — _reachable_mask feeds them through the
        # same functional conv as the dense path, so 'same'/pairs/ints all
        # resolve identically
        self._stride_arg = stride
        self._padding_arg = padding
        self._dilation_arg = dilation
        self._conv = cls(in_channels, out_channels, kernel_size,
                         stride=stride, padding=padding, dilation=dilation,
                         groups=groups, weight_attr=weight_attr,
                         bias_attr=bias_attr)

    def _reachable_mask(self, in_mask_cf):
        """Output active sites = sites any input active reaches through
        the kernel window (the reference's sparse-conv rulebook), computed
        as a conv of the 0/1 mask with a ones kernel at this layer's
        EXACT geometry — routed through the same functional conv as the
        dense path so every padding form ('same', pairs, ints) resolves
        identically."""
        from ..nn import functional as F

        ones = Tensor(jnp.ones((1, 1) + tuple(self._ks), jnp.float32))
        conv_fn = F.conv3d if self._nd == 3 else F.conv2d
        hit = conv_fn(Tensor(in_mask_cf.astype(jnp.float32)), ones, None,
                      stride=self._stride_arg, padding=self._padding_arg,
                      dilation=self._dilation_arg)
        return hit._data > 0.5

    def forward(self, x):
        sparse_in = isinstance(x, SparseCooTensor)
        dense = _dense(x)
        arr = dense._data
        cf_arr = _to_channels_first(arr, self._nd)
        if self._subm:
            # manual same-padding (asymmetric halves for even kernels)
            pads = [(0, 0), (0, 0)] + [((k - 1) // 2, k // 2)
                                       for k in self._ks]
            cf_arr = jnp.pad(cf_arr, pads)
        out = self._conv(Tensor(cf_arr))
        out_arr = _to_channels_last(out._data, self._nd)
        if not sparse_in:
            return Tensor(out_arr)
        in_mask = jnp.any(arr != 0, axis=-1)[:, None].astype(arr.dtype)
        if self._subm:
            mask = jnp.moveaxis(in_mask, 1, -1) > 0  # [n, *spatial, 1]
        else:
            mask = jnp.moveaxis(self._reachable_mask(in_mask), 1, -1)
        out_arr = jnp.where(mask, out_arr, 0.0)
        return _resparsify(Tensor(out_arr), mask=mask[..., 0])


class Conv3D(_SparseConvNd):
    _nd = 3
    _subm = False


class SubmConv3D(_SparseConvNd):
    _nd = 3
    _subm = True


class Conv2D(_SparseConvNd):
    _nd = 2
    _subm = False


class SubmConv2D(_SparseConvNd):
    _nd = 2
    _subm = True


class MaxPool3D(Layer):
    """Sparse max pool (parity: paddle.sparse.nn.MaxPool3D) — dense TPU
    max_pool over the densified NDHWC actives."""

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NDHWC", name=None):
        super().__init__()
        from ..nn import MaxPool3D as DenseMaxPool3D

        self._pool = DenseMaxPool3D(kernel_size, stride=stride,
                                    padding=padding)

    def forward(self, x):
        sparse_in = isinstance(x, SparseCooTensor)
        arr = _dense(x)._data
        cf = Tensor(_to_channels_first(arr, 3))
        out = self._pool(cf)
        result = Tensor(_to_channels_last(out._data, 3))
        return _resparsify(result) if sparse_in else result
