"""`paddle.sparse.nn` parity (reference `python/paddle/sparse/nn/`):
layers over sparse COO tensors (point-cloud style NDHWC actives).

TPU-first design note: XLA has no sparse-gather convolution kernel, and
at the densities these layers see in practice the MXU's dense conv
throughput wins over host-side gather orchestration — so each layer
densifies the COO input, runs the dense TPU kernel, and re-sparsifies.
Submanifold variants (SubmConv*) keep the reference semantics exactly:
outputs exist only at input active sites (the dense result is masked to
the input's activity pattern). `sparse_coo_tensor.to_dense()` and the
mask live on device, so the round-trip stays inside XLA.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..framework.core import Tensor
from ..nn.layer.layers import Layer
from . import SparseCooTensor, relu as _sp_relu, sparse_coo_tensor

__all__ = ["ReLU", "ReLU6", "LeakyReLU", "Softmax", "BatchNorm",
           "SyncBatchNorm", "Conv2D", "Conv3D", "SubmConv2D", "SubmConv3D",
           "MaxPool3D"]


def _dense(x):
    return x.to_dense() if isinstance(x, SparseCooTensor) else x


def _resparsify(dense_t):
    """Dense Tensor -> COO with the nonzero pattern of its values."""
    arr = dense_t._data
    nz = jnp.nonzero(jnp.any(arr != 0, axis=-1) if arr.ndim > 1
                     else arr != 0)
    idx = jnp.stack(nz)
    vals = arr[nz]
    return sparse_coo_tensor(Tensor(idx), Tensor(vals),
                             shape=list(arr.shape))


class _ValueAct(Layer):
    """Activations act on the stored values only (zero maps to zero for
    all of these, so the activity pattern is preserved — same contract as
    the reference's sparse activations)."""

    _fn_name = None

    def forward(self, x):
        from ..nn import functional as F

        fn = getattr(F, self._fn_name)
        if isinstance(x, SparseCooTensor):
            return sparse_coo_tensor(x.indices(), fn(x.values()), x.shape)
        return fn(x)


class ReLU(_ValueAct):
    _fn_name = "relu"

    def forward(self, x):
        return _sp_relu(x)


class ReLU6(_ValueAct):
    _fn_name = "relu6"


class LeakyReLU(_ValueAct):
    _fn_name = "leaky_relu"

    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        from ..nn import functional as F

        if isinstance(x, SparseCooTensor):
            return sparse_coo_tensor(
                x.indices(), F.leaky_relu(x.values(), self.negative_slope),
                x.shape)
        return F.leaky_relu(x, self.negative_slope)


class Softmax(Layer):
    """Softmax over the last dense axis of the values (reference: softmax
    over each row's stored entries for CSR; COO here normalizes the
    trailing channel axis of the values)."""

    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        from ..nn import functional as F

        if isinstance(x, SparseCooTensor):
            return sparse_coo_tensor(x.indices(),
                                     F.softmax(x.values(), axis=self.axis),
                                     x.shape)
        return F.softmax(x, axis=self.axis)


class BatchNorm(Layer):
    """BatchNorm over the channel (last) axis of the active values
    (parity: paddle.sparse.nn.BatchNorm — statistics over actives only)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 use_global_stats=None, name=None):
        super().__init__()
        from ..nn import BatchNorm1D

        self._bn = BatchNorm1D(num_features, momentum=momentum,
                               epsilon=epsilon, weight_attr=weight_attr,
                               bias_attr=bias_attr, data_format="NLC")

    def forward(self, x):
        if isinstance(x, SparseCooTensor):
            out = self._bn(x.values().unsqueeze(0)).squeeze(0)
            return sparse_coo_tensor(x.indices(), out, x.shape)
        return self._bn(x)


class SyncBatchNorm(BatchNorm):
    """Under GSPMD the batch statistics are already global across the dp
    mesh axis (the reduction compiles to a cross-replica all-reduce), so
    Sync == BatchNorm here, like the dense SyncBatchNorm."""


def _to_channels_first(arr, nd):
    # NDHWC -> NCDHW (dense kernels are NC-first)
    perm = (0, nd + 1) + tuple(range(1, nd + 1))
    return jnp.transpose(arr, perm)


def _to_channels_last(arr, nd):
    perm = (0,) + tuple(range(2, nd + 2)) + (1,)
    return jnp.transpose(arr, perm)


class _SparseConvNd(Layer):
    _nd = 3
    _subm = False

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format=None,
                 key=None):
        super().__init__()
        from ..nn import Conv2D as DenseConv2D, Conv3D as DenseConv3D

        cls = DenseConv3D if self._nd == 3 else DenseConv2D
        # submanifold conv preserves the active set; 'same' padding keeps
        # spatial dims so the input mask applies
        if self._subm:
            ks = kernel_size if isinstance(kernel_size, (list, tuple)) \
                else [kernel_size] * self._nd
            padding = [k // 2 for k in ks]
            stride = 1
        self._conv = cls(in_channels, out_channels, kernel_size,
                         stride=stride, padding=padding, dilation=dilation,
                         groups=groups, weight_attr=weight_attr,
                         bias_attr=bias_attr)

    def forward(self, x):
        sparse_in = isinstance(x, SparseCooTensor)
        dense = _dense(x)
        arr = dense._data
        cf = Tensor(_to_channels_first(arr, self._nd))
        out = self._conv(cf)
        out_arr = _to_channels_last(out._data, self._nd)
        if self._subm and sparse_in:
            # submanifold: only input-active sites stay active
            mask = jnp.any(arr != 0, axis=-1, keepdims=True)
            out_arr = jnp.where(mask, out_arr, 0.0)
        result = Tensor(out_arr)
        return _resparsify(result) if sparse_in else result


class Conv3D(_SparseConvNd):
    _nd = 3
    _subm = False


class SubmConv3D(_SparseConvNd):
    _nd = 3
    _subm = True


class Conv2D(_SparseConvNd):
    _nd = 2
    _subm = False


class SubmConv2D(_SparseConvNd):
    _nd = 2
    _subm = True


class MaxPool3D(Layer):
    """Sparse max pool (parity: paddle.sparse.nn.MaxPool3D) — dense TPU
    max_pool over the densified NDHWC actives."""

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NDHWC", name=None):
        super().__init__()
        from ..nn import MaxPool3D as DenseMaxPool3D

        self._pool = DenseMaxPool3D(kernel_size, stride=stride,
                                    padding=padding)

    def forward(self, x):
        sparse_in = isinstance(x, SparseCooTensor)
        arr = _dense(x)._data
        cf = Tensor(_to_channels_first(arr, 3))
        out = self._pool(cf)
        result = Tensor(_to_channels_last(out._data, 3))
        return _resparsify(result) if sparse_in else result
