"""`paddle.cost_model` parity (reference `python/paddle/cost_model/
cost_model.py` + `static_op_benchmark.json`).

The reference ships a V100-recorded static op->latency table consumed by
the auto-parallel cost estimators, plus `profile_measure` over the C++
CostModel. TPU-first redesign: latencies recorded on another vendor's
hardware are meaningless here, so `CostModel` MEASURES — it times each
recorded op of a static `Program` as its own compiled dispatch on the
live backend and returns the table. The static JSON accessors remain for
API parity, backed by the measured table.
"""
from __future__ import annotations

import time

import numpy as np

__all__ = ["CostModel"]


class CostModel:
    def __init__(self):
        self._static_cost_data = None

    def build_program(self):
        """Tiny fc program, mirroring the reference's example."""
        import paddle_tpu as pt
        from .. import static

        startup = static.Program()
        main = static.Program()
        with static.program_guard(main, startup):
            x = static.data(name="X", shape=[None, 1], dtype="float32")
            fc = pt.nn.Linear(1, 10)
            loss = fc(x).mean()  # noqa: F841 — recorded into `main`
        return startup, main

    def profile_measure(self, startup_program=None, main_program=None,
                        device=None, fetch_cost_list=("time",),
                        feed=None, repeat=10):
        """Measure per-op wall time of a recorded static Program.

        Each op's bound fn is jitted and timed standalone at the shapes the
        program recorded (inputs materialized with the recorded metadata),
        which is exactly what the reference's ProfileMeasure extracts from
        the profiler. Returns [{"op", "time_ms", "calls"}] sorted by cost.
        """
        import jax
        import jax.numpy as jnp

        from ..framework.core import Tensor

        if main_program is None:
            raise ValueError("profile_measure needs a main_program")
        # one eager replay to materialize every intermediate value
        feed = feed or self._zero_feed(main_program)
        env = {main_program.feed_vars[n]: jnp.asarray(np.asarray(v))
               for n, v in feed.items()}
        env = main_program._replay(env)

        rows = {}
        for op in main_program.ops:
            args = []
            ok = True
            for ref in op.in_refs:
                kind, val = ref[0], ref[1]
                if kind == "var":
                    v = env.get(val)
                    if v is None:
                        ok = False
                        break
                    args.append(v)
                elif kind == "tensor":
                    args.append(val._data)
                else:
                    args.append(val._data if isinstance(val, Tensor)
                                else val)
            if not ok:
                continue
            from ..utils.timing import device_sync

            try:
                fn = jax.jit(lambda *a, _f=op.fn, _s=op.static:
                             _f(*a, **_s))
                device_sync(fn(*args))
                t0 = time.perf_counter()
                for _ in range(repeat):
                    out = fn(*args)
                device_sync(out)
                dt = (time.perf_counter() - t0) / repeat
            except Exception:  # noqa: BLE001 — a non-jittable op is skipped
                continue
            r = rows.setdefault(op.op_name, {"op": op.op_name,
                                             "time_ms": 0.0, "calls": 0})
            r["time_ms"] += dt * 1e3
            r["calls"] += 1
        table = sorted(rows.values(), key=lambda r: -r["time_ms"])
        self._static_cost_data = table
        return table

    def _zero_feed(self, program):
        out = {}
        for name, (shape, dtype) in program._feed_meta.items():
            shape = tuple(1 if s in (None, -1) else s for s in shape)
            # _feed_meta stores str(dtype) which may be a class repr like
            # "<class 'numpy.float32'>" — extract the canonical name
            name_match = next(
                (c for c in ("bfloat16", "float64", "float32", "float16",
                             "int64", "int32", "int16", "int8", "bool")
                 if c in dtype), "float32")
            np_dt = np.float32 if name_match == "bfloat16" \
                else np.dtype(name_match)
            out[name] = np.zeros(shape, np_dt)
        return out

    # -- reference-shaped accessors over the measured table --
    def static_cost_data(self):
        if self._static_cost_data is None:
            raise RuntimeError(
                "no cost data: run profile_measure(main_program=...) first "
                "(this build measures on the live backend instead of "
                "shipping another vendor's latency table)")
        return self._static_cost_data

    def get_static_op_time(self, op_name, forward=True, dtype="float32"):
        if op_name is None:
            raise ValueError("op_name should not be empty")
        for row in self.static_cost_data():
            name = row["op"]
            if not forward:
                name = name.removesuffix("_grad")
                if name == row["op"]:
                    continue
            if name == op_name:
                return {"op_time": row["time_ms"], "config": {"dtype": dtype}}
        return {}
