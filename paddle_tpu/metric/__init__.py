"""`paddle.metric` parity (reference `python/paddle/metric/metrics.py`):
Metric base + Accuracy / Precision / Recall / Auc, computed host-side on
numpy (metrics are not in the compiled hot path)."""
from __future__ import annotations

import abc

import numpy as np

from ..framework.core import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def _to_np(x):
    if isinstance(x, Tensor):
        return x.numpy()
    return np.asarray(x)


class Metric(abc.ABC):
    """Base class: reset/update/accumulate/name contract
    (reference `python/paddle/metric/metrics.py:79`)."""

    def __init__(self):
        pass

    @abc.abstractmethod
    def reset(self):
        ...

    @abc.abstractmethod
    def update(self, *args):
        ...

    @abc.abstractmethod
    def accumulate(self):
        ...

    @abc.abstractmethod
    def name(self):
        ...

    def compute(self, *args):
        """Optional pre-processing on device tensors; default passthrough."""
        return args


class Accuracy(Metric):
    """Top-k accuracy (reference `metrics.py:184`)."""

    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        pred = _to_np(pred)
        label = _to_np(label)
        idx = np.argsort(-pred, axis=-1)[..., : self.maxk]
        # (N,1) integer labels are class ids, not one-hot — only argmax
        # genuine one-hot/soft labels (reference metrics.py compute)
        if label.ndim == pred.ndim and label.shape[-1] != 1:
            label = np.argmax(label, axis=-1)
        elif label.ndim == pred.ndim:
            label = label[..., 0]
        label = label.reshape(label.shape + (1,) * (idx.ndim - label.ndim))
        return (idx == label).astype(np.float32)

    def update(self, correct, *args):
        correct = _to_np(correct)
        num_samples = correct.shape[0] if correct.ndim else 1
        accs = []
        for k in self.topk:
            num_corrects = correct[..., :k].sum()
            self.total[self.topk.index(k)] += num_corrects
            self.count[self.topk.index(k)] += num_samples
            accs.append(float(num_corrects) / max(num_samples, 1))
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    """Binary precision (reference `metrics.py:332`)."""

    def __init__(self, name="precision"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = _to_np(preds).flatten()
        labels = _to_np(labels).flatten()
        pred_pos = np.rint(preds).astype(np.int64) == 1
        self.tp += int(np.sum(pred_pos & (labels == 1)))
        self.fp += int(np.sum(pred_pos & (labels != 1)))

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    """Binary recall (reference `metrics.py:421`)."""

    def __init__(self, name="recall"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = _to_np(preds).flatten()
        labels = _to_np(labels).flatten()
        pred_pos = np.rint(preds).astype(np.int64) == 1
        self.tp += int(np.sum(pred_pos & (labels == 1)))
        self.fn += int(np.sum(~pred_pos & (labels == 1)))

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """ROC-AUC via histogram buckets (reference `metrics.py:510`)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        super().__init__()
        self.num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = _to_np(preds)
        labels = _to_np(labels).flatten()
        if preds.ndim == 2 and preds.shape[1] == 2:
            pos_prob = preds[:, 1]
        else:
            pos_prob = preds.flatten()
        bins = np.clip(
            (pos_prob * self.num_thresholds).astype(np.int64),
            0,
            self.num_thresholds,
        )
        for b, l in zip(bins, labels):
            if l == 1:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1, dtype=np.int64)
        self._stat_neg = np.zeros(self.num_thresholds + 1, dtype=np.int64)

    def accumulate(self):
        tot_pos = 0.0
        tot_neg = 0.0
        auc = 0.0
        for i in range(self.num_thresholds, -1, -1):
            new_pos = tot_pos + self._stat_pos[i]
            new_neg = tot_neg + self._stat_neg[i]
            auc += (new_neg - tot_neg) * (new_pos + tot_pos) / 2.0
            tot_pos, tot_neg = new_pos, new_neg
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        return auc / (tot_pos * tot_neg)

    def name(self):
        return self._name


def accuracy(input, label, k=1):
    """Functional top-k accuracy (`paddle.metric.accuracy`)."""
    from ..ops.dispatch import apply_nondiff
    import jax.numpy as jnp

    def _acc(pred, lab):
        idx = jnp.argsort(-pred, axis=-1)[..., :k]
        lab = lab.reshape(lab.shape[0], 1)
        correct = jnp.any(idx == lab, axis=-1)
        return jnp.mean(correct.astype(jnp.float32))

    return apply_nondiff("accuracy", _acc, (input, label))
