"""Per-candidate AOT lowering: the planner's (and memory_planner's) one
candidate-evaluation code path.

For each (dp × mp × pp, batch) candidate this builds the probe model
under that mesh (pp>1: the pipeline-staged probe through
`LlamaForCausalLMPipe` at the candidate's planned microbatch count),
AOT-compiles the full train step (fwd+bwd+optimizer —
`jit/train_step.py`) and reads XLA's own executable memory accounting
(`monitor/memory.py:executable_record`; per-device for SPMD
executables). Nothing executes: host RAM materializes parameters for
lowering, the device never runs. Absorbed from
`tools/memory_planner.py:plan_one` (ISSUE 10 satellite — the OOM
preflight now calls back into this module).

With the exec cache armed (``PT_EXEC_CACHE``) every candidate compile
routes through `jit/exec_cache.py`; a repeat sweep deserializes instead
of recompiling, and the comms account (``collect_comms=True``) comes
from the cache's meta sidecar instead of re-parsing HLO — a warm sweep
pays ZERO fresh XLA compiles.
"""
from __future__ import annotations

from dataclasses import dataclass

from .candidates import candidate_label
from .hlo_costs import collective_bytes_by_axis

__all__ = ["ProbeSpec", "build_probe", "lower_candidate",
           "collect_param_specs"]


@dataclass(frozen=True)
class ProbeSpec:
    """Dimensions of the probe model the sweep lowers (defaults mirror
    memory_planner's CLI defaults; ``intermediate=0`` -> 3*hidden).
    ``layers`` is also the stage-able depth: pp candidates exist only
    where it divides over the stages. ``moe_experts > 0`` builds an
    MoE probe so the sweep's HLO account (and the analytical fallback)
    carries the expert all-to-all."""

    vocab: int = 2048
    hidden: int = 256
    intermediate: int = 0
    layers: int = 2
    heads: int = 4
    seq: int = 128
    moe_experts: int = 0

    @classmethod
    def from_args(cls, args) -> "ProbeSpec":
        """From any object with vocab/hidden/intermediate/layers/heads/
        seq attributes (e.g. an argparse namespace)."""
        return cls(vocab=args.vocab, hidden=args.hidden,
                   intermediate=args.intermediate, layers=args.layers,
                   heads=args.heads, seq=args.seq,
                   moe_experts=getattr(args, "moe_experts", 0) or 0)

    def to_dict(self) -> dict:
        return {"vocab": self.vocab, "hidden": self.hidden,
                "intermediate": self.intermediate, "layers": self.layers,
                "heads": self.heads, "seq": self.seq,
                "moe_experts": self.moe_experts}


def collect_param_specs(model) -> dict:
    """Read back the PartitionSpec every parameter actually carries —
    the propagated result of the model's seed annotations (parallel
    layers / sharding constraints), in JSON-able form (tuples ->
    lists, axis names / None as-is)."""
    from ..distributed.shard import get_sharding

    out = {}
    for name, p in model.named_parameters():
        spec = get_sharding(p)
        if spec is None:
            out[name] = []
        else:
            out[name] = [list(s) if isinstance(s, (tuple, list)) else s
                         for s in tuple(spec)]
    return out


def build_probe(cand: dict, spec: ProbeSpec):
    """Initialize the candidate's hybrid mesh and build the probe:
    ``(train_step, ids, model)`` — model + AdamW + TrainStep + a
    dp-SHARDED batch (`plan.shard_batch` — the planned run shards its
    inputs over dp; building the probe any other way would cost dp
    nothing and make its memory/comms account fiction). The ONE probe
    constructor: the lowering sweep and the bench's measured run must
    judge the SAME program. Caller owns the teardown
    (``env_mod.reset_env()``)."""
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu.distributed import fleet
    from paddle_tpu.jit.train_step import TrainStep
    from paddle_tpu.models import (LlamaConfig, LlamaForCausalLM,
                                   LlamaForCausalLMPipe)

    from .candidates import plan_microbatches
    from .plan import shard_batch

    dp, mp, batch = cand["dp"], cand["mp"], cand["batch"]
    pp = int(cand.get("pp", 1) or 1)
    n_micro = int(cand.get("n_micro") or plan_microbatches(pp, batch, dp))
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {
        "dp_degree": dp, "mp_degree": mp, "pp_degree": pp}
    if pp > 1:
        # the plan's schedule IS the probed schedule: the PipelineLayer
        # reads accumulate_steps for its default microbatch count
        strategy.pipeline_configs = {"accumulate_steps": n_micro}
    fleet.init(is_collective=True, strategy=strategy)
    cfg = LlamaConfig(
        vocab_size=spec.vocab, hidden_size=spec.hidden,
        intermediate_size=spec.intermediate or spec.hidden * 3,
        num_hidden_layers=spec.layers, num_attention_heads=spec.heads,
        max_position_embeddings=spec.seq,
        sequence_parallel=mp > 1,
        use_parallel_cross_entropy=mp > 1,
        **({"moe_num_experts": spec.moe_experts}
           if getattr(spec, "moe_experts", 0) else {}))
    pt.seed(0)
    # pp>1: the staged probe — decoder blocks stacked over the 'pp'
    # axis, the GPipe-in-XLA schedule compiled into the ONE train step
    # (fleet/meta_parallel pp_layers — the same program fit() trains)
    model = LlamaForCausalLMPipe(cfg) if pp > 1 else LlamaForCausalLM(cfg)
    opt = pt.optimizer.AdamW(learning_rate=1e-4,
                             parameters=model.parameters())
    step = TrainStep(model, opt,
                     (lambda m, i, l: m.loss_fn(m(i), l)) if pp > 1
                     else (lambda m, i, l: m(i, l)))
    # seeded: probe token VALUES never matter (nothing executes) but the
    # batch digest can reach exec-cache keys — global-RNG draws here
    # would churn the warm sweep (PTL005)
    rng = np.random.default_rng(0)
    ids = shard_batch(pt.to_tensor(rng.integers(
        0, cfg.vocab_size, (batch, spec.seq), dtype=np.int64)))
    return step, ids, model


def lower_candidate(cand: dict, spec: ProbeSpec, hbm_gb: float | None = None,
                    collect_comms: bool = False,
                    collect_specs: bool = False) -> dict:
    """One candidate: mesh init -> probe model -> AOT compile ->
    per-device memory record (-> comms account -> param specs) ->
    verdict. Tears the mesh down before returning.

    The returned row carries ``label``, the candidate axes/batch, the
    memory fields from :func:`monitor.memory.analysis_to_dict`,
    ``fits`` when ``hbm_gb`` is given, ``exec_cache: hit|miss`` when
    the cache is armed, ``collectives`` when ``collect_comms``, and
    ``param_specs`` when ``collect_specs``.
    """
    from paddle_tpu.distributed import env as env_mod
    from paddle_tpu.jit import exec_cache
    from paddle_tpu.monitor import memory as memobs

    dp, mp, pp = cand["dp"], cand["mp"], int(cand.get("pp", 1) or 1)
    label = candidate_label(cand)
    try:
        step, ids, model = build_probe(cand, spec)
        hits_before = (exec_cache.stats()["mem_hits"]
                       + exec_cache.stats()["disk_hits"])
        rec = memobs.executable_record(step, ids, ids, name=label)
        rec.update(cand)
        rec["label"] = label
        if hbm_gb is not None:
            rec["fits"] = rec["peak_bytes"] <= hbm_gb * 2**30
        if exec_cache.enabled():
            st = exec_cache.stats()
            rec["exec_cache"] = ("hit" if st["mem_hits"] + st["disk_hits"]
                                 > hits_before else "miss")
        if collect_comms:
            rec["collectives"] = _comms_for(step, (ids, ids),
                                            {"dp": dp, "mp": mp, "pp": pp})
        if collect_specs:
            rec["param_specs"] = collect_param_specs(model)
        return rec
    finally:
        env_mod.reset_env()


def _comms_for(step, batch, degrees: dict) -> dict:
    """Per-axis collective bytes of the candidate's compiled executable.

    Served from the exec cache's meta sidecar when the key is warm
    (``exec_cache.meta_get`` — no re-trace, no HLO re-parse); otherwise
    parsed from the post-SPMD optimized HLO (``compiled.as_text()``)
    and written back through ``meta_put`` under the SAME key as the
    executable, so the facts and the artifact invalidate together."""
    from paddle_tpu.jit import exec_cache

    key = step.exec_cache_key(*batch)
    meta = exec_cache.meta_get(key)
    if meta is not None and "collectives" in meta:
        return meta["collectives"]
    entry, _arrays, _nan = step._get_compiled(batch)
    try:
        hlo = entry.compiled.as_text()
    except Exception as e:  # noqa: BLE001 — a backend whose deserialized
        # executables carry no HLO still plans; the cost model falls back
        # to its analytical comms term
        return {"error": f"hlo unavailable ({type(e).__name__})"}
    comms = collective_bytes_by_axis(hlo, degrees)
    # merge, don't clobber: the program audit files its findings in the
    # same sidecar entry (analysis/program_audit.py)
    merged = dict(exec_cache.meta_get(key) or {})
    merged["collectives"] = comms
    exec_cache.meta_put(key, merged)
    return comms
