"""The planner's cost model: HBM-fit hard constraint + compute/comms
roofline over lowering-only facts.

Three ingredients per candidate (ISSUE 10 tentpole):

1. **Memory (hard constraint)** — XLA's own executable accounting
   (``args + temp`` per device, from `lowering.lower_candidate`); a
   candidate over the HBM budget is infeasible regardless of score.
2. **Comms** — per-axis wire bytes parsed from the candidate's
   post-SPMD HLO (`hlo_costs.py`), divided by the interconnect
   bandwidth. The same per-axis split the runtime's
   ``collective/bytes/<axis>`` monitor counters report, so a measured
   run can be laid against the model's prediction axis by axis.
3. **Compute** — an analytical 6·N·T flops estimate over the probe
   dimensions against peak flops × an assumed MFU, **seeded from
   `PERF_MEASUREMENTS.json`** when a hardware MFU record exists
   (`seed_from_measurements`) and falling back to documented defaults
   (PERF.md round-4: v5e bf16 peak 197 TFLOP/s, headline MFU 0.647 —
   the default assumption stays deliberately conservative at 0.40
   until the store says otherwise).

``est_step_ms = compute_ms + comms_ms`` (no-overlap conservatism: mp
collectives sit on the critical path, and assuming dp overlap without a
measurement would bias the planner toward dp — the hwbench
``shard_plan`` row records the planned-vs-measured delta that will
calibrate this). Lower is better; ties break deterministically.

Every number is rounded at the row boundary so the emitted
``shard_plan.json`` is byte-identical across repeat runs.
"""
from __future__ import annotations

import os

__all__ = ["CostSeeds", "default_seeds", "seed_from_measurements",
           "probe_param_count", "score_candidate", "rank_candidates"]

# v5e-class defaults (PERF.md): bf16 peak per chip; ICI per-direction
# bandwidth is deliberately conservative until a hardware row lands
DEFAULT_PEAK_TFLOPS = 197.0
DEFAULT_ICI_GBPS = 90.0
DEFAULT_MFU = 0.40


class CostSeeds(dict):
    """``{"peak_tflops", "ici_gbps", "mfu", "source"}`` — plain dict
    subclass so it JSON-serializes into the plan's provenance."""


def default_seeds() -> CostSeeds:
    s = CostSeeds(peak_tflops=DEFAULT_PEAK_TFLOPS,
                  ici_gbps=DEFAULT_ICI_GBPS, mfu=DEFAULT_MFU,
                  source="defaults")
    if os.environ.get("PT_AUTOSHARD_MFU"):
        s["mfu"] = float(os.environ["PT_AUTOSHARD_MFU"])
        s["source"] = "env"
    if os.environ.get("PT_AUTOSHARD_ICI_GBPS"):
        s["ici_gbps"] = float(os.environ["PT_AUTOSHARD_ICI_GBPS"])
        s["source"] = "env"
    return s


def seed_from_measurements(store_path: str | None = None) -> CostSeeds:
    """Defaults overridden by the newest real-hardware TRANSFORMER MFU
    in the measurement store (the roofline is then anchored to what
    THIS repo actually sustained, not a datasheet number). Only
    ``llama*`` metrics qualify — the probe is Llama-shaped, and a
    ResNet/BERT MFU record would misestimate the 6·N·T compute term
    several-fold. An explicit ``PT_AUTOSHARD_MFU`` env override always
    wins over the store."""
    seeds = default_seeds()
    if os.environ.get("PT_AUTOSHARD_MFU"):
        return seeds
    try:
        import json

        if store_path is None:
            from ..utils.measurements import measurements_path

            store_path = measurements_path()
        with open(store_path) as f:
            records = json.load(f).get("records", [])
        for rec in reversed(records):
            if rec.get("backend") in (None, "cpu", "unknown"):
                continue
            if not str(rec.get("metric", "")).startswith("llama"):
                continue
            mfu = (rec.get("extra") or {}).get("mfu")
            if mfu:
                seeds["mfu"] = round(float(mfu), 4)
                seeds["source"] = f"measurements:{rec.get('metric')}"
                break
    except Exception:  # noqa: BLE001 — a missing/corrupt store seeds
        pass           # the documented defaults
    return seeds


MOE_TOP_K = 2  # gshard top-2 routing (models/llama.py moe_top_k default)


def probe_param_count(spec, active_experts=None) -> int:
    """Analytical parameter count of the Llama-shaped probe
    (embedding + per-layer attention/MLP/norms + final norm + lm_head).
    An MoE probe multiplies the MLP stack by its expert count;
    ``active_experts`` caps that factor — the FLOPs term must count
    only the top-k experts routing activates per token, while memory/
    grad-traffic terms count them all."""
    h = spec.hidden
    inter = spec.intermediate or h * 3
    experts = max(int(getattr(spec, "moe_experts", 0) or 0), 1)
    if active_experts is not None:
        experts = min(experts, max(int(active_experts), 1))
    per_layer = (4 * h * h                  # q/k/v/o projections
                 + 3 * h * inter * experts  # gate/up/down (per expert)
                 + 2 * h)                   # the two RMSNorm scales
    return (spec.vocab * h            # embedding
            + spec.layers * per_layer
            + h                       # final norm
            + h * spec.vocab)         # lm_head


def score_candidate(cand: dict, row: dict, spec, seeds: CostSeeds) -> dict:
    """Roofline estimate for one FITTING candidate; returns the cost
    sub-dict merged into its plan row. Pipeline candidates (pp>1) pay
    the GPipe fill/drain bubble ``(pp−1)/n_micro`` on the compute term
    (the planned ``n_micro`` is stamped on the candidate) plus the
    per-tick ppermute handoff on the wire term."""
    dp, mp, batch = cand["dp"], cand["mp"], cand["batch"]
    pp = int(cand.get("pp", 1) or 1)
    n_micro = max(int(cand.get("n_micro", 1) or 1), 1)
    devices = dp * mp * pp
    tokens = batch * spec.seq
    # flops over the ACTIVATED params: gshard routes each token through
    # top-k experts, not the whole expert stack (grad/memory terms below
    # still count every expert)
    flops = 6.0 * probe_param_count(spec, active_experts=MOE_TOP_K) * tokens
    eff_flops = seeds["peak_tflops"] * 1e12 * seeds["mfu"] * devices
    compute_ms = flops / eff_flops * 1e3
    if pp > 1:
        # fill/drain bubble: (pp-1) of the n_micro+pp-1 schedule ticks
        # run partially empty stages — compute stretches by the ratio
        compute_ms *= 1.0 + (pp - 1) / n_micro
    comms = row.get("collectives") or {}
    per_axis = comms.get("per_axis_wire_bytes") or {}
    comms_ms = sum(per_axis.values()) / (seeds["ici_gbps"] * 1e9) * 1e3
    if not per_axis:
        # no HLO account (hlo-unavailable backends, or a sweep run with
        # collect_comms=False): the analytical terms stand in — ring
        # all-reduce of the dp-replicated grads + the Megatron f/g pair
        # per layer (two mp all-reduces of the [batch, seq, hidden]
        # activation each way) + the pipeline's per-tick ppermute of
        # the stage-state array + the MoE dispatch/combine all-to-all.
        # ALL terms must exist, and the fallback must fire whenever the
        # parsed account is absent — scoring zero comms would hand
        # comms-heavy candidates a free win
        wire = 0.0
        if dp > 1:
            grad_bytes = 4.0 * probe_param_count(spec) / (mp * pp)
            wire += 2.0 * (dp - 1) / dp * grad_bytes
        if mp > 1:
            act_bytes = 4.0 * batch * spec.seq * spec.hidden
            wire += (spec.layers * 2 * 2.0 * (mp - 1) / mp * act_bytes)
        if pp > 1:
            # one collective-permute of the stage state per schedule
            # tick, forward + backward replay (the vjp runs the ring in
            # reverse). PER-DEVICE bytes like every sibling term: the
            # state is [pp, mb, ...] with dim0 pp-sharded and the
            # microbatch dim dp-sharded, so each device ships its own
            # [mb/dp, seq, hidden] slice per tick
            mb_bytes = 4.0 * (batch // n_micro) * spec.seq * spec.hidden
            ticks = n_micro + pp - 1
            wire += 2.0 * ticks * mb_bytes / dp
        if getattr(spec, "moe_experts", 0) and dp > 1:
            # GShard dispatch + combine all-to-all per MoE layer
            # (EP rides the dp axis), forward + backward
            act_bytes = 4.0 * batch * spec.seq * spec.hidden
            wire += spec.layers * 4.0 * (dp - 1) / dp * act_bytes
        comms_ms = wire / (seeds["ici_gbps"] * 1e9) * 1e3
    est_ms = compute_ms + comms_ms
    return {
        "est_compute_ms": round(compute_ms, 4),
        "est_comms_ms": round(comms_ms, 4),
        "est_step_ms": round(est_ms, 4),
        "est_tokens_per_sec": round(tokens / est_ms * 1e3, 2)
        if est_ms > 0 else 0.0,
    }


def rank_candidates(rows: list) -> list:
    """Fitting rows best-first. The ordering key is the determinism
    contract: (rounded est_step_ms, fewer model-parallel splits, fewer
    pipeline stages, larger batch, label) — so equal-cost candidates
    prefer the simpler mesh and the bigger batch, stably."""
    fits = [r for r in rows if r.get("fits") and "error" not in r]
    return sorted(fits, key=lambda r: (r.get("est_step_ms", float("inf")),
                                       r.get("mp", 1),
                                       r.get("pp", 1),
                                       -r.get("batch", 0),
                                       r.get("label", "")))
