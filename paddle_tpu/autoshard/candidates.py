"""Candidate enumeration for the automatic sharding planner.

The legal search space is every (dp × mp) factorization of the device
count crossed with the requested global batch sizes — exactly the space
`tools/memory_planner.py` has always swept (its enumeration moved here
so the OOM preflight and the planner share ONE code path). Pure stdlib:
importable without jax, so CLI argument errors surface before any
backend initializes.
"""
from __future__ import annotations

__all__ = ["parse_mesh", "default_meshes", "enumerate_candidates",
           "candidate_label"]


def parse_mesh(token: str) -> dict:
    """``dp4xmp2`` -> {"dp": 4, "mp": 2} (either axis optional)."""
    out = {"dp": 1, "mp": 1}
    for part in token.lower().split("x"):
        part = part.strip()
        if not part:
            continue
        for axis in ("dp", "mp"):
            if part.startswith(axis):
                out[axis] = int(part[len(axis):])
                break
        else:
            raise ValueError(f"bad mesh token {part!r} "
                             f"in {token!r} (expected dpN / mpN / dpNxmpM)")
    return out


def default_meshes(n_devices: int) -> list:
    """(dp, mp) factorizations of the device count, dp-heavy first."""
    out = []
    mp = 1
    while mp <= n_devices:
        if n_devices % mp == 0:
            out.append({"dp": n_devices // mp, "mp": mp})
        mp *= 2
    return out


def candidate_label(cand: dict) -> str:
    return f"dp{cand['dp']}·mp{cand['mp']} b{cand['batch']}"


def enumerate_candidates(n_devices: int, configs=None, batches="8") -> list:
    """The planner's candidate list: ``[{"dp", "mp", "batch"}, ...]``.

    ``configs`` is a comma list of mesh tokens (or an iterable of them;
    None = all power-of-2 factorizations of ``n_devices``); ``batches``
    a comma list (or iterable) of global batch sizes. Ordering is
    deterministic — the enumeration order is part of the plan's
    byte-identity contract."""
    if configs is None:
        meshes = default_meshes(n_devices)
    else:
        tokens = (configs.split(",") if isinstance(configs, str)
                  else list(configs))
        meshes = [parse_mesh(t) for t in tokens]
    if isinstance(batches, str):
        batch_list = [int(b) for b in batches.split(",")]
    else:
        batch_list = [int(b) for b in batches]
    out = []
    for m in meshes:
        if m["dp"] * m["mp"] != n_devices:
            raise ValueError(
                f"dp{m['dp']}xmp{m['mp']} does not "
                f"factorize {n_devices} devices")
        for b in batch_list:
            out.append({**m, "batch": b})
    return out
