"""Candidate enumeration for the automatic sharding planner.

The legal search space is every (dp × mp × pp) factorization of the
device count crossed with the requested global batch sizes — exactly
the space `tools/memory_planner.py` has always swept (its enumeration
moved here so the OOM preflight and the planner share ONE code path).
The pp axis (ISSUE 15) is capped by the probe's stage-able depth: a
pipeline candidate only exists when the repeated block count divides
over its stages, so callers pass ``stage_depth`` (the probe's layer
count) and the env knob ``PT_AUTOSHARD_PP_MAX`` bounds the sweep.
Pure stdlib: importable without jax, so CLI argument errors surface
before any backend initializes.
"""
from __future__ import annotations

import os

__all__ = ["parse_mesh", "default_meshes", "enumerate_candidates",
           "candidate_label", "plan_microbatches", "pp_cap"]

_AXES = ("dp", "mp", "pp")


def parse_mesh(token: str) -> dict:
    """``dp4xmp2`` / ``dp2xpp2`` -> degree dict (every axis optional)."""
    out = {"dp": 1, "mp": 1, "pp": 1}
    for part in token.lower().split("x"):
        part = part.strip()
        if not part:
            continue
        for axis in _AXES:
            if part.startswith(axis):
                out[axis] = int(part[len(axis):])
                break
        else:
            raise ValueError(f"bad mesh token {part!r} "
                             f"in {token!r} (expected dpN / mpN / ppN, "
                             f"e.g. dpNxmpM or dpNxppK)")
    return out


def pp_cap(stage_depth=None) -> int:
    """The pp sweep bound: ``PT_AUTOSHARD_PP_MAX`` (default 8) clamped
    to the probe's stage-able depth (its repeated-block count — a
    pipeline deeper than its blocks cannot be staged)."""
    cap = int(os.environ.get("PT_AUTOSHARD_PP_MAX", "8") or 8)
    if stage_depth:
        cap = min(cap, int(stage_depth))
    return max(cap, 1)


def default_meshes(n_devices: int, pp_max: int = 1,
                   stage_depth=None) -> list:
    """(dp, mp, pp) factorizations of the device count, pp=1 rows first
    in the historical dp-heavy order (byte-identity of pre-PP plans),
    then deeper pipelines. pp values that the stage depth does not
    divide over are skipped — such a candidate could never build."""
    out = []
    pp = 1
    while pp <= min(n_devices, pp_max):
        if n_devices % pp == 0 and (
                not stage_depth or int(stage_depth) % pp == 0):
            rest = n_devices // pp
            mp = 1
            while mp <= rest:
                if rest % mp == 0:
                    out.append({"dp": rest // mp, "mp": mp, "pp": pp})
                mp *= 2
        pp *= 2
    return out


def plan_microbatches(pp: int, batch: int, dp: int = 1) -> int:
    """The planned microbatch count for a pipeline candidate: the
    largest divisor of the global batch ≤ 2·pp whose microbatch still
    dp-shards — 2·pp microbatches halve the fill/drain bubble
    ``(pp−1)/n_micro`` vs one-per-stage while keeping per-tick work
    meaningful. Deterministic (part of the plan's byte-identity);
    pp=1 pipelines nothing (n_micro=1)."""
    if pp <= 1 or batch <= 0:
        return 1
    best = 1
    for n in range(1, batch + 1):
        if n > 2 * pp:
            break
        if batch % n or (batch // n) % max(dp, 1):
            continue
        best = n
    return best


def candidate_label(cand: dict) -> str:
    pp = cand.get("pp", 1)
    tail = f"·pp{pp}" if pp > 1 else ""
    return f"dp{cand['dp']}·mp{cand['mp']}{tail} b{cand['batch']}"


def enumerate_candidates(n_devices: int, configs=None, batches="8",
                         pp_max: int = 1, stage_depth=None) -> list:
    """The planner's candidate list:
    ``[{"dp", "mp", "pp", "batch", "n_micro"}, ...]``.

    ``configs`` is a comma list of mesh tokens (or an iterable of them;
    None = all power-of-2 factorizations of ``n_devices``, pp bounded
    by ``pp_max``/``stage_depth``); ``batches`` a comma list (or
    iterable) of global batch sizes. Ordering is deterministic — the
    enumeration order is part of the plan's byte-identity contract.
    ``n_micro`` is stamped per candidate (`plan_microbatches`) so the
    lowering, the cost model, and the emitted plan all agree on the
    schedule they judged."""
    if configs is None:
        meshes = default_meshes(n_devices, pp_max=pp_max,
                                stage_depth=stage_depth)
    else:
        tokens = (configs.split(",") if isinstance(configs, str)
                  else list(configs))
        meshes = [parse_mesh(t) for t in tokens]
    if isinstance(batches, str):
        batch_list = [int(b) for b in batches.split(",")]
    else:
        batch_list = [int(b) for b in batches]
    out = []
    for m in meshes:
        m.setdefault("pp", 1)
        if m["dp"] * m["mp"] * m["pp"] != n_devices:
            raise ValueError(
                f"dp{m['dp']}xmp{m['mp']}xpp{m['pp']} does not "
                f"factorize {n_devices} devices")
        for b in batch_list:
            out.append({**m, "batch": b,
                        "n_micro": plan_microbatches(m["pp"], b, m["dp"])})
    return out
