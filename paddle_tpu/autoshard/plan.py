"""The shard plan: schema, GSPMD-style spec derivation, application.

A :class:`ShardPlan` is what the planner emits and everything else
consumes: mesh degrees + global batch + per-param PartitionSpecs +
the scored candidate table + provenance. Serialization is
deterministic by construction (sorted keys, rounded floats, no
timestamps) — the acceptance contract is *same inputs → byte-identical
``shard_plan.json``*, so reproducibility is checkable with ``cmp``.

Spec derivation (`derive_param_specs`) is the "handful of seed rules,
GSPMD-propagated" half of ISSUE 10: models built from the parallel
layers already carry their specs (the layers ARE the seed
annotations — `collect_param_specs` reads them back); a plain model
gets the Megatron conjugate pairing propagated structurally — walk the
parameters in declaration order, shard the first eligible 2-D weight's
output dim on ``mp`` (column-parallel), flip the next one's input dim
(row-parallel, XLA inserts the f/g collectives), carry column-parallel
biases on ``mp``, replicate everything else. Embedding-shaped weights
("embed" in the name) shard their vocab dim. No per-layer annotations
anywhere — XLA's SPMD partitioner completes the propagation exactly as
`distributed/shard.py` documents.
"""
from __future__ import annotations

import json
import os

__all__ = ["PLAN_VERSION", "ShardPlan", "load_plan", "derive_param_specs",
           "apply_plan", "shard_batch", "stage_model"]

PLAN_VERSION = 1


def _looks_like_embedding(name: str) -> bool:
    tail = name.lower()
    return "embed" in tail or "emb_" in tail


def derive_param_specs(model, mp_degree: int = 2,
                       mp_axis: str = "mp") -> dict:
    """Rule-derived PartitionSpecs for a model with no annotations of
    its own: ``{param_name: [spec entries]}`` (None = replicated dim).
    Dims that ``mp_degree`` does not divide stay replicated (specs are
    layout hints — correctness never depends on them)."""
    specs = {}
    stream_sharded = False
    col_out = None
    for name, p in model.named_parameters():
        shape = tuple(int(d) for d in p.shape)
        if len(shape) == 2:
            if _looks_like_embedding(name):
                specs[name] = [mp_axis, None] \
                    if shape[0] % mp_degree == 0 else [None, None]
                continue
            if not stream_sharded:
                if shape[1] % mp_degree == 0:
                    specs[name] = [None, mp_axis]  # column-parallel
                    stream_sharded, col_out = True, shape[1]
                else:
                    specs[name] = [None, None]
            else:
                specs[name] = ([mp_axis, None]      # row-parallel:
                               if shape[0] % mp_degree == 0  # the conjugate
                               else [None, None])
                stream_sharded, col_out = False, None
        elif len(shape) == 1:
            specs[name] = ([mp_axis] if stream_sharded
                           and shape[0] == col_out else [None])
        else:
            specs[name] = [None] * len(shape)
    return specs


class ShardPlan:
    """One planned hybrid configuration, loadable everywhere a mesh is
    needed (`fit(shard_plan=)`, the launcher env, the launch scripts)."""

    def __init__(self, mesh: dict, batch: int, param_specs: dict,
                 rows: list | None = None, winner: str | None = None,
                 seeds: dict | None = None, provenance: dict | None = None,
                 n_micro: int = 1, stage_assignment=None):
        self.mesh = {"dp": int(mesh.get("dp", 1)),
                     "mp": int(mesh.get("mp", 1)),
                     "pp": int(mesh.get("pp", 1))}
        self.batch = int(batch)
        self.param_specs = dict(param_specs or {})
        self.rows = list(rows or [])
        self.winner = winner
        self.seeds = dict(seeds or {})
        self.provenance = dict(provenance or {})
        # pipeline schedule the plan committed to: microbatch count per
        # step and the deterministic layer→stage map (None when pp=1)
        self.n_micro = max(int(n_micro or 1), 1)
        self.stage_assignment = (list(stage_assignment)
                                 if stage_assignment else None)

    @property
    def devices(self) -> int:
        return self.mesh["dp"] * self.mesh["mp"] * self.mesh["pp"]

    def to_dict(self) -> dict:
        return {
            "plan_version": PLAN_VERSION,
            "mesh": self.mesh,
            "batch": self.batch,
            "n_micro": self.n_micro,
            "stage_assignment": self.stage_assignment,
            "winner": self.winner,
            "param_specs": self.param_specs,
            "rows": self.rows,
            "cost_seeds": self.seeds,
            "provenance": self.provenance,
        }

    def dumps(self) -> bytes:
        """Canonical bytes — THE determinism boundary (sorted keys,
        2-space indent, trailing newline; floats were rounded at row
        construction)."""
        return (json.dumps(self.to_dict(), sort_keys=True, indent=2)
                + "\n").encode()

    def digest(self) -> str:
        import hashlib

        return hashlib.sha256(self.dumps()).hexdigest()[:16]

    def summary(self) -> dict:
        """The compact form bench lines embed (``shard_plan`` sub-object
        — what `tools/perf_guard.py --plan-drift` compares)."""
        return {"dp": self.mesh["dp"], "mp": self.mesh["mp"],
                "pp": self.mesh["pp"], "batch": self.batch,
                "devices": self.devices, "digest": self.digest()}

    def save(self, path: str) -> str:
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(self.dumps())
        os.replace(tmp, path)
        return path

    @classmethod
    def from_dict(cls, d: dict) -> "ShardPlan":
        if d.get("plan_version") != PLAN_VERSION:
            raise ValueError(
                f"shard plan version {d.get('plan_version')!r} != "
                f"{PLAN_VERSION} (replan with this tree)")
        return cls(mesh=d["mesh"], batch=d["batch"],
                   param_specs=d.get("param_specs", {}),
                   rows=d.get("rows", []), winner=d.get("winner"),
                   seeds=d.get("cost_seeds", {}),
                   provenance=d.get("provenance", {}),
                   n_micro=d.get("n_micro", 1),
                   stage_assignment=d.get("stage_assignment"))


def load_plan(path_or_plan) -> ShardPlan:
    """A ShardPlan from a path / file-ish / already-a-plan."""
    if isinstance(path_or_plan, ShardPlan):
        return path_or_plan
    with open(os.fspath(path_or_plan)) as f:
        return ShardPlan.from_dict(json.load(f))


def _spec_tuple(entries) -> tuple:
    return tuple(tuple(e) if isinstance(e, list) else e for e in entries)


def apply_plan(plan, model=None):
    """Close the loop: initialize the global mesh at the plan's degrees
    and place the model's parameters — plan-recorded specs by name
    first, the rule-derived specs for everything else; parameters that
    already carry a mesh-axis spec (parallel-layer models) keep it.
    Returns the :class:`~paddle_tpu.distributed.env.ParallelEnv`.

    A pp>1 plan initializes the full hybrid strategy (fleet.init) so
    the pipeline container reads the planned microbatch count
    (``accumulate_steps = plan.n_micro``) — wrap the model's block run
    afterwards with :func:`stage_model`. This is the
    zero-hand-written-PartitionSpecs entry point: scripts call
    ``apply_plan(load_plan(os.environ["PT_SHARD_PLAN"]), model)``
    and never name an axis.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    from ..distributed import env as env_mod
    from ..distributed import fleet as _fleet
    from ..distributed.shard import get_sharding

    plan = load_plan(plan)
    strategy = _fleet.DistributedStrategy()
    strategy.hybrid_configs = {
        "dp_degree": plan.mesh["dp"], "mp_degree": plan.mesh["mp"],
        "pp_degree": plan.mesh["pp"]}
    if plan.mesh["pp"] > 1:
        strategy.pipeline_configs = {"accumulate_steps": plan.n_micro}
    _fleet.init(is_collective=True, strategy=strategy)
    env = env_mod.get_env()
    if model is None:
        return env
    derived = None
    mesh_axes = set(env.mesh.axis_names)
    for name, p in model.named_parameters():
        cur = get_sharding(p)
        if cur is not None and any(
                a in mesh_axes for a in _flat_axes(cur)):
            continue  # the model's own seed annotations win
        entries = plan.param_specs.get(name)
        if entries is None:
            if derived is None:
                derived = derive_param_specs(
                    model, mp_degree=plan.mesh["mp"] or 1)
            entries = derived.get(name, [])
        spec = _clean_spec(_spec_tuple(entries), tuple(p.shape), env)
        p._replace_(jax.device_put(
            p._data, NamedSharding(env.mesh, PartitionSpec(*spec))))
        p._sharding_spec = PartitionSpec(*spec)
    return env


def _flat_axes(spec) -> list:
    out = []
    for e in tuple(spec):
        if isinstance(e, (tuple, list)):
            out.extend(x for x in e if x is not None)
        elif e is not None:
            out.append(e)
    return out


def _clean_spec(spec: tuple, shape: tuple, env) -> tuple:
    """Drop axis entries that do not divide their dim (same guard as
    `shard.shard_tensor`) — a plan written for one model applied to a
    near-relative degrades to replication instead of failing."""
    sizes = dict(zip(env.mesh.axis_names, env.mesh.devices.shape))
    out = []
    for i, e in enumerate(spec):
        names = e if isinstance(e, (tuple, list)) else (e,)
        n = 1
        for nm in names:
            if nm is not None:
                n *= sizes.get(nm, 1)
        ok = i < len(shape) and n and shape[i] % n == 0
        out.append(e if ok else None)
    while out and out[-1] is None:
        out.pop()
    return tuple(out)


def stage_model(model, plan):
    """Wrap ``model``'s repeated block run into the staged pipeline
    container when the plan pipelines (pp>1); identity otherwise.

    Call AFTER :func:`apply_plan` (the container reads the live 'pp'
    mesh degree and the planned ``accumulate_steps``) and build the
    optimizer from the RETURNED model's parameters — the wrapped
    blocks' parameters are re-stored stacked over the 'pp' axis
    (values unchanged, so a pp>1 run stays on the pp=1 loss curve).
    Models that are already a pipelined ``PipelineLayer`` (the *Pipe
    model classes) pass through; a model whose direct children carry
    no stage-able repeated run raises with a conversion hint.
    """
    from ..distributed.fleet.meta_parallel.parallel_layers.pp_layers \
        import PipelineLayer

    plan = load_plan(plan)
    pp = plan.mesh.get("pp", 1)
    if pp <= 1:
        return model
    kwargs = {}
    if isinstance(model, PipelineLayer):
        if getattr(model, "_pipelined", False):
            return model
        # re-staging a (pp=1-built) pipeline container: carry its
        # schedule/remat knobs over — dropping recompute_interval here
        # would train a program the plan's HBM-fit never judged
        subs = list(model._run_order)
        kwargs = {"recompute_interval": model._recompute,
                  "num_virtual_pipeline_stages": model._virtual,
                  "remat_ticks": model._remat_ticks}
    else:
        subs = [sub for _, sub in model.named_children()]
    try:
        return PipelineLayer(subs, loss_fn=getattr(model, "loss_fn", None),
                             **kwargs)
    except ValueError as e:
        raise ValueError(
            f"stage_model: cannot stage {type(model).__name__} over "
            f"pp={pp} ({e}) — express the model as repeated blocks "
            "(nn.Sequential of identical block layers) or use a "
            "pipeline-native class (LlamaForCausalLMPipe / "
            "ErnieForPretrainingPipe)") from e


def shard_batch(x, axis: str = "dp"):
    """Shard a host/global batch over the data axis (dim 0), replicating
    the rest — the one input-side placement a planned run needs.
    Scalars (0-d) replicate: there is no batch dim to split, and a
    1-entry spec on a rank-0 value is rejected by jax."""
    from ..distributed.shard import shard_tensor

    ndim = getattr(x, "ndim", None) or len(getattr(x, "shape", ()))
    spec = (axis,) + (None,) * (ndim - 1) if ndim else ()
    return shard_tensor(x, spec=spec)
