"""Automatic sharding planner (ROADMAP item 2 — docs/AUTOSHARD.md).

Plan → launch → resume hybrid runs with zero hand-written
PartitionSpecs: enumerate the legal (dp × mp × pp, batch) candidates
for a device count (pp capped by the probe's stage-able depth),
AOT-lower each on a virtual mesh (exec-cache-warm, no execution; pp>1
probes compile the GPipe-in-XLA PipelineLayer schedule), score with
XLA's memory accounting (hard HBM fit) + the per-axis collective bytes
parsed from the post-SPMD HLO (incl. the ppermute stage handoff) + an
analytical roofline seeded from `PERF_MEASUREMENTS.json` (pipeline
candidates pay the ``(pp−1)/n_micro`` bubble), and emit the winner as
a deterministic, provenance-stamped ``shard_plan.json`` carrying
``pp``/``n_micro``/the layer→stage assignment.

Driver: ``python tools/shard_plan.py plan|launch|resume|bench``.
Consumers: ``hapi.Model.fit(shard_plan=)``, launch scripts via
``apply_plan(load_plan(os.environ["PT_SHARD_PLAN"]), model)``.
"""
from .candidates import (  # noqa: F401
    candidate_label, default_meshes, enumerate_candidates, parse_mesh,
    plan_microbatches, pp_cap,
)
from .cost import (  # noqa: F401
    CostSeeds, default_seeds, rank_candidates, seed_from_measurements,
)
from .lowering import (  # noqa: F401
    ProbeSpec, build_probe, collect_param_specs, lower_candidate,
)
from .plan import (  # noqa: F401
    PLAN_VERSION, ShardPlan, apply_plan, derive_param_specs, load_plan,
    shard_batch, stage_model,
)
from .planner import make_plan, plan_sweep  # noqa: F401

__all__ = [
    "PLAN_VERSION", "ShardPlan", "ProbeSpec", "CostSeeds",
    "enumerate_candidates", "default_meshes", "parse_mesh",
    "candidate_label", "build_probe", "lower_candidate",
    "collect_param_specs",
    "derive_param_specs", "apply_plan", "load_plan", "shard_batch",
    "stage_model", "plan_microbatches", "pp_cap",
    "make_plan", "plan_sweep", "rank_candidates",
    "default_seeds", "seed_from_measurements",
]
