"""Shared CLI plumbing for the planner-family tools.

`tools/shard_plan.py` and `tools/memory_planner.py` sweep the same
probe over the same candidate space, so the probe-dimension arguments,
the smoke geometry, and the corrected-child re-exec dance (the virtual
mesh must exist BEFORE jax initializes a backend, and the host
sitecustomize pins the tunneled TPU at interpreter start) live here
once. Pure stdlib — importable before any backend decision is made.
"""
from __future__ import annotations

import os
import subprocess
import sys

__all__ = ["add_probe_args", "apply_smoke", "reexec_virtual_child",
           "SMOKE_CONFIGS"]

# the tier-1 smoke sweep: tiny probe, four mesh candidates — one per
# parallelism family incl. a pp>1 pipeline (the smoke probe's 2 layers
# stage over pp=2)
SMOKE_CONFIGS = "dp8,dp4xmp2,dp2xmp4,dp4xpp2"


def add_probe_args(ap) -> None:
    """The probe-model dimension flags (defaults shared by both tools)."""
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--intermediate", type=int, default=0,
                    help="FFN width (default 3*hidden)")
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--vocab", type=int, default=2048)
    ap.add_argument("--moe-experts", type=int, default=0,
                    help="experts per MLP (0 = dense probe; >0 builds an "
                         "MoE probe so the sweep costs the expert "
                         "all-to-all)")


def apply_smoke(args) -> None:
    """Shrink to the smoke geometry in place (CI pipeline proof)."""
    args.hidden, args.layers, args.heads = 64, 2, 4
    args.seq, args.vocab, args.batches = 32, 512, "8"
    if not getattr(args, "configs", None):
        args.configs = SMOKE_CONFIGS


def reexec_virtual_child(tool_file: str, tool_name: str, argv,
                         devices: int, child_flag: str,
                         exec_cache: str | None = None,
                         force_cpu: bool = True,
                         timeout: int = 1800) -> int:
    """Re-exec ``tool_file`` in a corrected child environment and return
    its exit code. ``child_flag`` is the env marker the tool checks to
    detect it IS the child. ``force_cpu=False`` (a bench with a live
    TPU) keeps the real backend and device count."""
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env = dict(os.environ)
    env[child_flag] = "1"
    if exec_cache:
        env["PT_EXEC_CACHE"] = os.path.abspath(exec_cache)
    if force_cpu:
        env["JAX_PLATFORMS"] = "cpu"
        flags = [f for f in env.get("XLA_FLAGS", "").split()
                 if "xla_force_host_platform_device_count" not in f]
        flags.append(f"--xla_force_host_platform_device_count={devices}")
        env["XLA_FLAGS"] = " ".join(flags)
    pin = ("import jax; jax.config.update('jax_platforms', 'cpu'); "
           if force_cpu else "")
    code = (pin
            + "import sys; sys.path.insert(0, %r); "
              "sys.path.insert(0, %r); "
              "import importlib.util; "
              "spec = importlib.util.spec_from_file_location(%r, %r); "
              "mod = importlib.util.module_from_spec(spec); "
              "spec.loader.exec_module(mod); "
              "sys.exit(mod.main(%r))"
            % (root, os.path.join(root, "tools"), tool_name,
               os.path.abspath(tool_file), list(argv)))
    try:
        proc = subprocess.run([sys.executable, "-c", code], env=env,
                              cwd=root, timeout=timeout)
    except subprocess.TimeoutExpired:
        # the documented setup-error exit code, not a traceback — a
        # timeboxed hwbench row must read a clean rc
        print(f"{tool_name}: child timed out after {timeout}s",
              file=sys.stderr, flush=True)
        return 2
    return proc.returncode
