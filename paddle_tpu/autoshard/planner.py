"""The planner loop: enumerate → lower → score → emit the plan.

Closes ROADMAP item 2's loop from candidate enumeration to a launched
run: `candidates.enumerate_candidates` names the legal
(dp × mp × pp, batch) space (pp capped by the probe's stage-able
depth — ISSUE 15), `lowering.lower_candidate` AOT-lowers each on the
virtual mesh (exec-cache-warm — a repeat sweep pays zero fresh XLA
compiles; pp>1 candidates compile the staged pipeline schedule),
`cost.score_candidate` applies the HBM-fit hard constraint + the
compute/comms roofline (incl. the pipeline bubble), and the winner
becomes a provenance-stamped
:class:`~paddle_tpu.autoshard.plan.ShardPlan` carrying its planned
``n_micro`` and layer→stage assignment.

Telemetry (``planner/*`` counters, zero-overhead off — this module is
in ``monitor.INSTRUMENTED_MODULES``): ``planner/candidates`` /
``planner/infeasible`` / ``planner/errors`` per sweep row,
``planner/plans`` per emitted plan, ``planner/winner_est_step_ms``
gauge for the winner's roofline estimate.
"""
from __future__ import annotations

import sys

from . import cost as _cost
from .candidates import candidate_label, enumerate_candidates, pp_cap
from .lowering import ProbeSpec, lower_candidate
from .plan import PLAN_VERSION, ShardPlan
from ..monitor import _register as _monitor_register

__all__ = ["plan_sweep", "make_plan"]

# Telemetry slot (paddle_tpu.monitor None-slot contract): None unless
# PT_MONITOR wired it
_monitor = None


def plan_sweep(n_devices: int, hbm_gb: float, spec: ProbeSpec | None = None,
               configs=None, batches="8", collect_comms: bool = True,
               seeds=None) -> list:
    """Lower + judge every candidate; returns the scored row list
    (errors inlined per row — one broken candidate must not hide the
    others' verdicts, same contract as memory_planner). ``seeds`` pins
    the cost seeds (make_plan passes its own so the plan's provenance
    and its scores can never come from two store reads)."""
    spec = spec or ProbeSpec()
    seeds = seeds if seeds is not None else _cost.seed_from_measurements()
    rows = []
    for cand in enumerate_candidates(n_devices, configs, batches,
                                     pp_max=pp_cap(spec.layers),
                                     stage_depth=spec.layers):
        m = _monitor
        try:
            row = lower_candidate(cand, spec, hbm_gb=hbm_gb,
                                  collect_comms=collect_comms,
                                  collect_specs=True)
        except Exception as e:  # noqa: BLE001 — per-row isolation
            row = {"label": candidate_label(cand), **cand,
                   "error": f"{type(e).__name__}: {e}"}
        if "error" not in row and row.get("fits"):
            row.update(_cost.score_candidate(cand, row, spec, seeds))
        if m is not None:
            m.on_planner_candidate(fits=bool(row.get("fits")),
                                   error="error" in row)
        rows.append(row)
    return rows


def make_plan(n_devices: int, hbm_gb: float, spec: ProbeSpec | None = None,
              configs=None, batches="8",
              collect_comms: bool = True) -> tuple:
    """The whole planning pass: ``(ShardPlan | None, rows)`` — None when
    no candidate fits the HBM budget (the caller's exit-code 3 path)."""
    import jax

    spec = spec or ProbeSpec()
    seeds = _cost.seed_from_measurements()
    rows = plan_sweep(n_devices, hbm_gb, spec, configs, batches,
                      collect_comms=collect_comms, seeds=seeds)
    ranked = _cost.rank_candidates(rows)
    if not ranked:
        return None, rows
    winner = ranked[0]
    param_specs = winner.pop("param_specs", {})
    # the losers' spec tables are bulk without information — the plan
    # records the winner's; every row keeps its verdict + cost columns.
    # exec_cache hit/miss is run state, not plan content: keeping it
    # would break cold-vs-warm byte identity
    plan_rows = []
    for r in rows:
        r = dict(r)
        r.pop("param_specs", None)
        r.pop("exec_cache", None)
        plan_rows.append(r)
    winner_pp = int(winner.get("pp", 1) or 1)
    plan = ShardPlan(
        mesh={"dp": winner["dp"], "mp": winner["mp"], "pp": winner_pp},
        batch=winner["batch"],
        param_specs=param_specs,
        rows=plan_rows,
        winner=winner["label"],
        seeds=seeds,
        provenance=_provenance(n_devices, hbm_gb, spec, configs, batches,
                               jax),
        n_micro=int(winner.get("n_micro", 1) or 1),
        stage_assignment=_stage_assignment(spec, winner_pp),
    )
    m = _monitor
    if m is not None:
        m.on_planner_plan(winner.get("est_step_ms", 0.0))
    return plan, rows


def _stage_assignment(spec, pp: int):
    """Deterministic layer→stage map for the winner (GPipe contiguous
    partition, v=1): block i runs on stage ``i // (layers/pp)``. None
    for unpipelined winners — the plan stays byte-compatible with its
    pre-PP shape there."""
    if pp <= 1 or spec.layers % pp:
        return None
    bps = spec.layers // pp
    return [i // bps for i in range(spec.layers)]


def _provenance(n_devices, hbm_gb, spec, configs, batches, jax) -> dict:
    """Same-inputs-stable provenance: everything here is a function of
    the tree, the store, and the invocation — never of the clock (a
    timestamp would break the byte-identical contract)."""
    out = {
        "plan_version": PLAN_VERSION,
        "devices": int(n_devices),
        "hbm_gb": float(hbm_gb),
        "probe": spec.to_dict(),
        "configs": configs if isinstance(configs, str) or configs is None
        else ",".join(str(c) for c in configs),
        "batches": str(batches),
        "jax": jax.__version__,
        "backend": jax.default_backend(),
        "device_kind": getattr(jax.devices()[0], "device_kind", "?"),
    }
    try:
        from ..utils.measurements import _git_commit

        out.update({k: v for k, v in _git_commit().items()
                    if k in ("commit", "dirty")})
    except Exception:  # noqa: BLE001 — no git, no commit stamp
        pass
    return out


_monitor_register(sys.modules[__name__])
