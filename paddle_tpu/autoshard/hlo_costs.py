"""Collective-traffic extraction from post-SPMD optimized HLO.

The lowering the exec cache compiles carries only sharding annotations;
the collectives that actually move bytes (gradient all-reduces, the
Megatron f/g pair, reduce-scatters) are inserted by XLA's SPMD
partitioner — so the honest per-candidate comms account reads the
*compiled* executable's HLO (``compiled.as_text()``), not the StableHLO
input (PAPERS.md: GSPMD 2105.04663 — the sharding choice determines the
collective schedule, and both are visible post-partitioning).

Attribution: every collective names its ``replica_groups``; given the
mesh degrees (AXIS_ORDER ``dp,pp,sharding,sep,mp``, outer→inner, device
id = row-major multi-index) the group structure identifies the mesh
axis (or axis combination) the bytes crossed. ``mp`` groups are
stride-1 id runs; ``dp`` groups stride by the product of the inner
axes. Wire bytes follow the standard ring factors: all-reduce moves
``2(n−1)/n`` of the payload, all-gather / reduce-scatter / all-to-all
``(n−1)/n``, collective-permute the payload itself.

Pure text parsing on stdlib + the mesh degrees — deterministic, so the
byte totals can live inside a byte-identical ``shard_plan.json``.
"""
from __future__ import annotations

import re

__all__ = ["parse_collectives", "classify_group_set",
           "collective_bytes_by_axis", "AXIS_ORDER"]

# canonical axis order, outermost (slowest) first — must match
# distributed/env.py AXIS_ORDER (kept literal: this module is jax-free)
AXIS_ORDER = ("dp", "pp", "sharding", "sep", "mp")

_DTYPE_BYTES = {
    "f64": 8, "s64": 8, "u64": 8, "c64": 8,
    "f32": 4, "s32": 4, "u32": 4,
    "bf16": 2, "f16": 2, "s16": 2, "u16": 2,
    "f8e4m3fn": 1, "f8e5m2": 1, "s8": 1, "u8": 1, "pred": 1,
}

# `-start` carries the payload type; the matching `-done` would double
# count, so only the base/start form is matched
_COLL_RE = re.compile(
    r"=\s*(?P<ty>[^=]*?)\s*"
    r"(?P<op>all-reduce|all-gather|reduce-scatter|all-to-all|"
    r"collective-permute)(?P<start>-start)?\(")
_DONE_RE = re.compile(
    r"(all-reduce|all-gather|reduce-scatter|all-to-all|"
    r"collective-permute)-done\(")
_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")
_GROUPS_RE = re.compile(r"replica_groups=\{(\{[^}]*\}(?:,\{[^}]*\})*)\}")
_IOTA_RE = re.compile(
    r"replica_groups=\[(\d+),(\d+)\]<=\[([0-9,]+)\]"
    r"(?:T\(([0-9,]+)\))?")
_PAIRS_RE = re.compile(r"source_target_pairs=\{(\{[^}]*\}(?:,\{[^}]*\})*)\}")

# ring wire factors: fraction of the payload each participant actually
# puts on the interconnect
_WIRE_FACTOR = {
    "all-reduce": lambda n: 2.0 * (n - 1) / n,
    "all-gather": lambda n: (n - 1) / n,
    "reduce-scatter": lambda n: (n - 1) / n,
    "all-to-all": lambda n: (n - 1) / n,
    "collective-permute": lambda n: 1.0,
}


def _payload_bytes(type_text: str, start_op: bool = False) -> int:
    """Bytes of an op's result type. Async ``-start`` ops are
    tuple-typed ``(operands..., results...)`` — counting every element
    would double the payload, so only the trailing (result) half is
    summed for them; sync variadic tuples ARE all results and sum
    whole."""
    shapes = _SHAPE_RE.findall(type_text)
    if start_op and len(shapes) > 1:
        shapes = shapes[len(shapes) // 2:]
    total = 0
    for dt, dims in shapes:
        n = 1
        for d in dims.split(","):
            d = d.strip()
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES.get(dt, 4)
    return total


def _parse_groups(line: str):
    """The collective's replica groups as a list of id tuples (None when
    the op carries none — e.g. a permute, handled via its pairs)."""
    m = _GROUPS_RE.search(line)
    if m:
        return [tuple(int(x) for x in g.split(",") if x.strip())
                for g in m.group(1)[1:-1].split("},{")]
    m = _IOTA_RE.search(line)
    if m:
        # iota format: reshape(transpose(iota(prod(dims)), perm), [G, S])
        g, s = int(m.group(1)), int(m.group(2))
        dims = [int(x) for x in m.group(3).split(",")]
        n = 1
        for d in dims:
            n *= d
        ids = list(range(n))
        if m.group(4):
            perm = [int(x) for x in m.group(4).split(",")]
            # transpose the multi-dim iota: rebuild ids in permuted order
            strides = [0] * len(dims)
            acc = 1
            for i in range(len(dims) - 1, -1, -1):
                strides[i] = acc
                acc *= dims[i]
            pdims = [dims[p] for p in perm]
            pstrides = [strides[p] for p in perm]
            ids = []
            idx = [0] * len(pdims)
            for _ in range(n):
                ids.append(sum(i * s for i, s in zip(idx, pstrides)))
                for ax in range(len(pdims) - 1, -1, -1):
                    idx[ax] += 1
                    if idx[ax] < pdims[ax]:
                        break
                    idx[ax] = 0
        return [tuple(ids[i * s:(i + 1) * s]) for i in range(g)]
    return None


def _coords(dev: int, degrees: dict) -> tuple:
    """Device id -> mesh multi-index (row-major over AXIS_ORDER)."""
    out = []
    rem = dev
    sizes = [degrees.get(a, 1) for a in AXIS_ORDER]
    for i, a in enumerate(AXIS_ORDER):
        inner = 1
        for s in sizes[i + 1:]:
            inner *= s
        out.append(rem // inner)
        rem %= inner
    return tuple(out)


def classify_group_set(groups, degrees: dict) -> str:
    """Which mesh axes a replica-group partition communicates over.

    Every group's members are decomposed into mesh coordinates; the
    varying coordinate positions name the axes. One axis -> ``"mp"``;
    a fused group over several -> ``"dp+mp"`` (AXIS_ORDER order);
    nothing varying (degenerate 1-groups) -> ``"none"``."""
    varying = set()
    for g in groups:
        coords = [_coords(d, degrees) for d in g]
        for i, a in enumerate(AXIS_ORDER):
            if len({c[i] for c in coords}) > 1:
                varying.add(a)
    if not varying:
        return "none"
    return "+".join(a for a in AXIS_ORDER if a in varying)


def parse_collectives(hlo_text: str, degrees: dict) -> list:
    """Every collective in an optimized-HLO module, as
    ``{"op", "axis", "group_size", "payload_bytes", "wire_bytes"}``."""
    out = []
    for line in hlo_text.splitlines():
        if _DONE_RE.search(line):
            continue
        m = _COLL_RE.search(line)
        if m is None:
            continue
        op = m.group("op")
        payload = _payload_bytes(m.group("ty"),
                                 start_op=bool(m.group("start")))
        groups = _parse_groups(line)
        if groups is None and op == "collective-permute":
            pm = _PAIRS_RE.search(line)
            if pm:
                pairs = [tuple(int(x) for x in p.split(","))
                         for p in pm.group(1)[1:-1].split("},{")]
                # each {src,tgt} pair is an independent point-to-point
                # hop: classifying pairs (not their union) attributes a
                # pp-ring shift to "pp" instead of smearing it over
                # every axis the pair set happens to span. Self-pairs
                # (identity entries XLA keeps for uninvolved devices)
                # drop out via the gsize<=1 guard below.
                groups = [tuple(sorted({a, b})) for a, b in pairs
                          if a != b]
        if not groups:
            continue
        gsize = max(len(g) for g in groups)
        if gsize <= 1:
            continue
        if op == "reduce-scatter":
            # the HLO result is the already-scattered SHARD; the ring
            # moves (n-1)/n of the pre-scatter input = result × n
            payload *= gsize
        axis = classify_group_set(groups, degrees)
        out.append({
            "op": op,
            "axis": axis,
            "group_size": gsize,
            "payload_bytes": payload,
            "wire_bytes": int(payload * _WIRE_FACTOR[op](gsize)),
        })
    return out


def collective_bytes_by_axis(hlo_text: str, degrees: dict) -> dict:
    """Aggregate per-axis comms account of one executable:
    ``{"per_axis_wire_bytes": {...}, "per_axis_payload_bytes": {...},
    "ops": {...}, "total_wire_bytes": N}`` — the cost-model input and
    the shape persisted into ``shard_plan.json`` rows."""
    per_wire: dict = {}
    per_payload: dict = {}
    ops: dict = {}
    for c in parse_collectives(hlo_text, degrees):
        a = c["axis"]
        per_wire[a] = per_wire.get(a, 0) + c["wire_bytes"]
        per_payload[a] = per_payload.get(a, 0) + c["payload_bytes"]
        ops[c["op"]] = ops.get(c["op"], 0) + 1
    return {
        "per_axis_wire_bytes": dict(sorted(per_wire.items())),
        "per_axis_payload_bytes": dict(sorted(per_payload.items())),
        "ops": dict(sorted(ops.items())),
        "total_wire_bytes": sum(per_wire.values()),
    }
