"""hapi: the Keras-like high-level Model API.

Reference parity: `paddle.Model` (`python/paddle/hapi/model.py:1050` fit,
`:1741` evaluate/predict), `Model.prepare(optimizer, loss, metrics)`,
`save/load`.

TPU-first design: `fit` drives the whole-step compiled TrainStep
(jit/train_step.py) — every batch is ONE XLA execution including the
optimizer — rather than the reference's per-op dygraph loop. Evaluation
jits the forward via a cached no-grad program. Everything else (callbacks,
metrics, DataLoader handling, save/load) keeps the reference surface.
"""
from __future__ import annotations

import os

import numpy as np

from .callbacks import config_callbacks
from ..autograd.tape import no_grad
from ..framework.core import Tensor
from ..framework.io import load as _load, save as _save
from ..io.reader import DataLoader
from ..jit.train_step import TrainStep


def _to_tensor_list(batch):
    if isinstance(batch, (list, tuple)):
        return [b if isinstance(b, Tensor) else Tensor(np.asarray(b))
                for b in batch]
    return [batch if isinstance(batch, Tensor) else Tensor(np.asarray(batch))]


class Model:
    """Parity: `paddle.Model(network, inputs=None, labels=None)`."""

    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self._train_step = None
        self.stop_training = False

    # -- setup --
    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is None:
            self._metrics = []
        elif isinstance(metrics, (list, tuple)):
            self._metrics = list(metrics)
        else:
            self._metrics = [metrics]
        if optimizer is not None and loss is not None:
            self._train_step = TrainStep(
                self.network, optimizer, self._loss_fn)
        return self

    def _loss_fn(self, net, *batch):
        n_in = len(batch) - 1 if len(batch) > 1 else 1
        inputs, labels = batch[:n_in], batch[n_in:]
        outs = net(*inputs)
        if self._loss is None:
            return outs if isinstance(outs, Tensor) else outs[0]
        loss = self._loss(outs, *labels)
        return loss.mean() if loss.ndim else loss

    # -- per-batch ops (parity: Model.train_batch / eval_batch / predict_batch) --
    def train_batch(self, inputs, labels=None, update=True):
        batch = _to_tensor_list(inputs) + (_to_tensor_list(labels) if labels is not None else [])
        loss = self._train_step(*batch)
        return [loss.numpy()]

    @no_grad()
    def eval_batch(self, inputs, labels=None):
        batch = _to_tensor_list(inputs)
        labels = _to_tensor_list(labels) if labels is not None else []
        outs = self.network(*batch)
        metrics = []
        if self._loss is not None and labels:
            loss = self._loss(outs, *labels)
            metrics.append(float(np.asarray(loss.numpy()).mean()))
        for m in self._metrics:
            m.update(*[np.asarray(x) for x in m.compute(outs, *labels)])
        return metrics

    @no_grad()
    def predict_batch(self, inputs):
        outs = self.network(*_to_tensor_list(inputs))
        if isinstance(outs, (list, tuple)):
            return [o.numpy() for o in outs]
        return [outs.numpy()]

    # -- loops --
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        assert self._train_step is not None, "call prepare() first"
        loader = train_data if isinstance(train_data, DataLoader) else \
            DataLoader(train_data, batch_size=batch_size, shuffle=shuffle,
                       drop_last=drop_last, num_workers=num_workers)
        try:
            steps = len(loader)
        except Exception:
            steps = None
        cbks = config_callbacks(
            callbacks, model=self, epochs=epochs, steps=steps,
            batch_size=batch_size, verbose=verbose, save_freq=save_freq,
            save_dir=save_dir, metrics=[m.name() for m in self._metrics])
        self.stop_training = False
        cbks.on_train_begin()
        self.network.train()
        for epoch in range(epochs):
            cbks.on_epoch_begin(epoch)
            it = 0
            for step, batch in enumerate(loader):
                cbks.on_train_batch_begin(step)
                batch = batch if isinstance(batch, (list, tuple)) else [batch]
                loss = self._train_step(*_to_tensor_list(batch))
                logs = {"loss": float(loss.numpy())}
                cbks.on_train_batch_end(step, logs)
                it += 1
                if num_iters is not None and it >= num_iters:
                    break
            cbks.on_epoch_end(epoch, logs)
            if eval_data is not None and (epoch + 1) % eval_freq == 0:
                self.evaluate(eval_data, batch_size=batch_size,
                              verbose=verbose, callbacks=callbacks)
                self.network.train()
            if self.stop_training:
                break
        cbks.on_train_end()

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None):
        loader = eval_data if isinstance(eval_data, DataLoader) else \
            DataLoader(eval_data, batch_size=batch_size, shuffle=False,
                       num_workers=num_workers)
        cbks = config_callbacks(callbacks, model=self, verbose=verbose,
                                mode="eval")
        self.network.eval()
        for m in self._metrics:
            m.reset()
        losses = []
        cbks.on_eval_begin()
        for step, batch in enumerate(loader):
            batch = batch if isinstance(batch, (list, tuple)) else [batch]
            n_in = len(batch) - 1 if len(batch) > 1 else 1
            res = self.eval_batch(batch[:n_in], batch[n_in:])
            if res:
                losses.append(res[0])
        logs = {}
        if losses:
            logs["loss"] = float(np.mean(losses))
        for m in self._metrics:
            acc = m.accumulate()
            names = m.name()  # paddle metrics return a list of names
            if isinstance(names, (list, tuple)):
                vals = acc if isinstance(acc, (list, tuple)) else [acc]
                logs.update(zip(names, vals))
            else:
                logs[names] = acc
        cbks.on_eval_end(logs)
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        loader = test_data if isinstance(test_data, DataLoader) else \
            DataLoader(test_data, batch_size=batch_size, shuffle=False,
                       num_workers=num_workers)
        self.network.eval()
        outputs = []
        for batch in loader:
            batch = batch if isinstance(batch, (list, tuple)) else [batch]
            # a (inputs, label) dataset reused for predict: drop the label
            # (reference slices by the `inputs` spec; heuristic without one)
            n_in = (len(self._inputs) if self._inputs
                    else len(batch) - 1 if len(batch) > 1 else 1)
            outputs.append(self.predict_batch(batch[:n_in]))
        if stack_outputs and outputs:
            n_out = len(outputs[0])
            return [np.concatenate([o[i] for o in outputs])
                    for i in range(n_out)]
        return outputs

    # -- persistence (parity: Model.save/load -> .pdparams/.pdopt) --
    def save(self, path, training=True):
        dirname = os.path.dirname(path)
        if dirname:
            os.makedirs(dirname, exist_ok=True)
        _save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            state = getattr(self._optimizer, "state_dict", lambda: {})()
            _save(state, path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        self.network.set_state_dict(_load(path + ".pdparams"))
        opt_path = path + ".pdopt"
        if (not reset_optimizer and self._optimizer is not None
                and os.path.exists(opt_path)):
            if hasattr(self._optimizer, "set_state_dict"):
                self._optimizer.set_state_dict(_load(opt_path))

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        from .summary import summary

        return summary(self.network, input_size, dtypes=dtype)
