"""hapi: the Keras-like high-level Model API.

Reference parity: `paddle.Model` (`python/paddle/hapi/model.py:1050` fit,
`:1741` evaluate/predict), `Model.prepare(optimizer, loss, metrics)`,
`save/load`.

TPU-first design: `fit` drives the whole-step compiled TrainStep
(jit/train_step.py) — every batch is ONE XLA execution including the
optimizer — rather than the reference's per-op dygraph loop. Evaluation
jits the forward via a cached no-grad program. Everything else (callbacks,
metrics, DataLoader handling, save/load) keeps the reference surface.
"""
from __future__ import annotations

import os
import sys
import time

import numpy as np

from .callbacks import config_callbacks
from ..autograd.tape import no_grad
from ..framework.core import Tensor
from ..framework.io import load as _load, save as _save
from ..io.reader import DataLoader
from ..jit.train_step import AsyncStepper, TrainStep
from ..monitor import _register as _monitor_register
from ..monitor import blackbox as _blackbox
from ..monitor import goodput as _gp
from ..monitor import heartbeat as _heartbeat
from ..monitor import memory as _memory
from ..monitor import watchdog as _watchdog
from ..monitor.numerics import NonFiniteError as _NonFiniteError

# Telemetry slots (see paddle_tpu.monitor): None unless PT_MONITOR wired
# them. `_spans` (monitor/spans.py) records fit/evaluate phase brackets
# and the deliberate metric materializations as `sync` attribution spans.
_monitor = None
_spans = None


def _fast_forward(src, n):
    """Yield ``src``'s batches after discarding the first ``n`` —
    host-side only (the resume fast-forward). Hand-rolled because the
    DataLoader's iterator implements ``__next__`` without ``__iter__``,
    which ``itertools.islice`` / ``yield from`` reject."""
    it = iter(src)
    for _ in range(n):
        try:
            next(it)
        except StopIteration:
            return
    while True:
        try:
            yield next(it)
        except StopIteration:
            return


def _to_tensor_list(batch):
    if isinstance(batch, (list, tuple)):
        return [b if isinstance(b, Tensor) else Tensor(np.asarray(b))
                for b in batch]
    return [batch if isinstance(batch, Tensor) else Tensor(np.asarray(batch))]


def _fetch_scalars(tensors):
    """ONE counted host transfer for a batch of lazy device scalars
    (``hapi/host_syncs`` is the guard metric for the ≤1-sync-per-window
    contract) — the single sync primitive `fit`/`evaluate` share."""
    import jax

    m = _monitor
    if m is not None:
        m.on_host_sync()
    sp = _spans
    t0 = time.perf_counter() if sp is not None else None
    out = [float(np.asarray(a).reshape(-1)[0])
           for a in jax.device_get([t._data for t in tensors])]
    if sp is not None:
        sp.record("hapi/fetch_scalars", "sync", t0, lane="sync_fences",
                  args={"n": len(tensors)})
    return out


class _LazyLoss:
    """A deferred training metric: number-like, synced on first read.

    `fit` hands these to callbacks between log windows so the loop never
    blocks on the device — but a USER callback that reads the value
    (``float(logs["loss"])``, ``np.asarray``, a comparison) must still
    get honest number semantics, and that read IS a host sync, so it is
    materialized on demand and counted via the same ``hapi/host_syncs``
    hook as the deliberate window syncs. Reading every step (e.g. a
    user-constructed ``ProgBarLogger(log_freq=1)``) therefore re-creates
    per-step syncing — visibly, in the guard counter, as the user asked.
    """

    __slots__ = ("_tensor", "_value")

    def __init__(self, tensor):
        self._tensor = tensor
        self._value = None

    def _materialize(self):
        if self._value is None:
            self._value = _fetch_scalars([self._tensor])[0]
        return self._value

    def __float__(self):
        return self._materialize()

    def __array__(self, dtype=None):
        a = np.asarray(self._materialize())
        return a.astype(dtype) if dtype is not None else a

    def item(self):
        return self._materialize()

    def __lt__(self, other):
        return self._materialize() < other

    def __le__(self, other):
        return self._materialize() <= other

    def __gt__(self, other):
        return self._materialize() > other

    def __ge__(self, other):
        return self._materialize() >= other

    def __eq__(self, other):
        return self._materialize() == other

    def __hash__(self):
        return object.__hash__(self)

    def __repr__(self):
        return (f"{self._value!r}" if self._value is not None
                else "<lazy device scalar>")


def _materialize_logs(logs):
    """Fetch every lazy scalar in ``logs`` to the host in ONE transfer,
    returning plain-float logs — everything downstream (ProgBarLogger,
    MonitorCallback, user callbacks) sees host floats and cannot
    accidentally re-sync."""
    lazy = {k: v for k, v in logs.items()
            if isinstance(v, (Tensor, _LazyLoss))}
    if not lazy:
        return dict(logs)
    out = dict(logs)
    pre = {k: v for k, v in lazy.items()
           if isinstance(v, _LazyLoss) and v._value is not None}
    todo = {k: v for k, v in lazy.items() if k not in pre}
    for k, v in pre.items():
        out[k] = v._value
    if todo:
        vals = _fetch_scalars([
            v._tensor if isinstance(v, _LazyLoss) else v
            for v in todo.values()])
        for k, f in zip(todo, vals):
            out[k] = f
    return out


class _TrainState:
    """fit's blackbox state provider: what a crash/hang postmortem sees
    of the training loop — step, last materialized loss, the goodput
    ledger snapshot, and the async pipeline's in-flight depth. Registered
    per-fit as a bound method so the recorder's WeakMethod lets it die
    with the run (monitor/blackbox.py)."""

    __slots__ = ("_stepper", "_ledger", "step", "loss", "__weakref__")

    def __init__(self, stepper, ledger):
        self._stepper = stepper
        self._ledger = ledger
        self.step = 0
        self.loss = None

    def state(self):
        out = {"step": self.step, "last_loss": self.loss,
               "in_flight": self._stepper.in_flight}
        if self._ledger is not None:
            out["goodput"] = self._ledger.snapshot()
        return out


def _input_wait_iter(ledger, it):
    """Bracket each batch fetch as goodput ``input_wait``: blocking in
    the data iterator (loader compute, prefetch starvation) lands in its
    own bucket instead of inflating the step or ``other`` residual."""
    it = iter(it)
    while True:
        ledger.enter("input_wait")
        try:
            item = next(it)
        except StopIteration:
            return
        finally:
            ledger.exit()
        yield item


class Model:
    """Parity: `paddle.Model(network, inputs=None, labels=None)`."""

    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self._train_step = None
        self.stop_training = False

    # -- setup --
    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is None:
            self._metrics = []
        elif isinstance(metrics, (list, tuple)):
            self._metrics = list(metrics)
        else:
            self._metrics = [metrics]
        if optimizer is not None and loss is not None:
            self._train_step = TrainStep(
                self.network, optimizer, self._loss_fn)
        return self

    def _loss_fn(self, net, *batch):
        n_in = len(batch) - 1 if len(batch) > 1 else 1
        inputs, labels = batch[:n_in], batch[n_in:]
        outs = net(*inputs)
        if self._loss is None:
            return outs if isinstance(outs, Tensor) else outs[0]
        loss = self._loss(outs, *labels)
        return loss.mean() if loss.ndim else loss

    # -- per-batch ops (parity: Model.train_batch / eval_batch / predict_batch) --
    def _train_batch_lazy(self, inputs, labels=None):
        """One compiled step; the loss comes back as a LAZY device scalar
        (jax dispatch is async — no host round-trip here). `fit` consumes
        this path and defers materialization to its log cadence."""
        batch = _to_tensor_list(inputs) + (
            _to_tensor_list(labels) if labels is not None else [])
        return self._train_step(*batch)

    def train_batch(self, inputs, labels=None, update=True):
        loss = self._train_batch_lazy(inputs, labels)
        # Paddle-parity return type at the PUBLIC boundary: the one-off
        # eager API hands back host numpy, and this .numpy() is the only
        # sync on the path
        return [loss.numpy()]

    @no_grad()
    def _eval_batch_lazy(self, inputs, labels=None):
        """Forward + loss with the loss left ON DEVICE; metric state still
        updates eagerly (the Metric API is numpy-facing). Returns
        (lazy mean-loss Tensor | None)."""
        batch = _to_tensor_list(inputs)
        labels = _to_tensor_list(labels) if labels is not None else []
        outs = self.network(*batch)
        loss = None
        if self._loss is not None and labels:
            loss = self._loss(outs, *labels)
            loss = loss.mean() if loss.ndim else loss
        for m in self._metrics:
            m.update(*[np.asarray(x) for x in m.compute(outs, *labels)])
        return loss

    def eval_batch(self, inputs, labels=None):
        loss = self._eval_batch_lazy(inputs, labels)
        # public boundary: materialize exactly here (Paddle-parity floats)
        return [] if loss is None else [float(np.asarray(loss.numpy()))]

    @no_grad()
    def predict_batch(self, inputs):
        outs = self.network(*_to_tensor_list(inputs))
        if isinstance(outs, (list, tuple)):
            return [o.numpy() for o in outs]
        return [outs.numpy()]

    # -- loops --
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None, max_in_flight=2,
            device_prefetch=0, nan_check=None, resume_from=None,
            checkpoint_dir=None, checkpoint_keep=None, nan_policy=None,
            shard_plan=None):
        """Parity: `paddle.Model.fit` — with an asynchronous device
        pipeline (docs/ASYNC_PIPELINE.md). Steps dispatch through an
        :class:`AsyncStepper` keeping up to ``max_in_flight`` compiled
        steps outstanding, and the per-step loss stays ON DEVICE: logs
        carry lazy scalars that are materialized (one host transfer) only
        every ``log_freq`` steps and at epoch end — not once per step,
        which through the axon tunnel costs a ~70–95 ms round-trip
        against a ~180 ms step. ``device_prefetch > 0`` additionally
        wraps the loader in a :class:`~paddle_tpu.io.DevicePrefetchIterator`
        staging that many batches ahead in device memory.

        ``nan_check=True`` arms the numerics sentinel FOR THIS FIT on
        the model's TrainStep (monitor/numerics.py): one fused
        finite-flag scalar per step; on first failure the loop dies with
        a :class:`~paddle_tpu.monitor.numerics.NonFiniteError` naming
        the step and first bad leaf, after ``Callback.on_train_error``
        fired. ``None`` (default) follows the global ``PT_NANCHECK``
        state; ``False`` forces it off for this fit. The TrainStep's
        own ``nan_check`` setting is restored when fit returns.

        Resilience (docs/RESILIENCE.md): ``checkpoint_dir`` arms a
        :class:`~paddle_tpu.resilience.CheckpointManager` — periodic
        async sharded checkpoints on a cadence planned from the measured
        save cost (``PT_CKPT_OVERHEAD_PCT``), each save quiescing the
        AsyncStepper first, plus a final checkpoint at train end.
        ``resume_from`` restores params / optimizer state / LR schedule /
        PRNG / step counters and the data-iterator position from the
        newest COMPLETE checkpoint under that directory (torn ones are
        skipped) — resharding into the current mesh placements, so the
        resumed (dp×mp) need not match the saved one. ``nan_policy=
        "skip"`` forces the sentinel on and hands its failures to a
        :class:`~paddle_tpu.resilience.NaNSkipPolicy`: the poisoned
        batch is dropped (params/LR/step untouched — the step never
        happened) and training continues, aborting only after
        ``PT_NANSKIP_MAX`` consecutive failures.

        Automatic sharding (docs/AUTOSHARD.md): ``shard_plan`` — a
        ``shard_plan.json`` path (or loaded
        :class:`~paddle_tpu.autoshard.ShardPlan`) from
        ``tools/shard_plan.py plan`` — initializes the global
        (dp×mp×pp) mesh at the plan's degrees and places every
        parameter by its planned / rule-derived PartitionSpec before
        the first step: a hybrid run with no hand-written specs. A
        pp>1 plan additionally wraps the network's repeated block run
        into the staged pipeline container (``autoshard.stage_model``
        — the planned ``n_micro`` microbatches must divide the batch)
        and re-points the optimizer at the stacked parameters; losses
        stay on the pp=1 curve. Defaults to the
        ``PT_SHARD_PLAN`` env stamp the planner's launcher sets, so a
        launched script needs no code either (``resume_from`` likewise
        defaults from the ``PT_SHARD_RESUME`` stamp `shard_plan.py
        resume` sets). Combines with ``resume_from``: the checkpoint
        reshards into the NEW plan's placements on load, so the saved
        (dp×mp) need not match."""
        assert self._train_step is not None, "call prepare() first"
        # training goodput plane (docs/OBSERVABILITY.md): one wall-clock
        # ledger per run, created before any setup so plan-apply/restore
        # time is inside the wall. PT_GOODPUT=0 opts out entirely (and
        # stands down the hang watchdog, whose deadline has no EMA
        # source without fit feeding it). Armed — slots wired, watchdog
        # started — only after setup can no longer raise outside the
        # teardown paths below.
        ledger = (_gp.Ledger()
                  if os.environ.get("PT_GOODPUT", "1") not in ("", "0")
                  else None)
        if shard_plan is None:
            shard_plan = os.environ.get("PT_SHARD_PLAN") or None
        if resume_from is None:
            # `shard_plan.py resume` stamps the checkpoint dir into the
            # workers; an hapi script relaunched that way must resume,
            # not silently retrain from step 0
            resume_from = os.environ.get("PT_SHARD_RESUME") or None
        shard_batch = None
        if shard_plan is not None:
            from ..autoshard import apply_plan, load_plan, stage_model
            from ..autoshard import shard_batch as _shard_batch

            # mesh + param placement BEFORE resume/compile: the restore
            # reshards into these placements, and the first step's
            # lowering sees them
            plan = load_plan(shard_plan)
            apply_plan(plan, self.network)
            if plan.mesh.get("pp", 1) > 1:
                # a pipelined plan: wrap the block run into the staged
                # shard_map container (param values unchanged — the
                # pp>1 run stays on the pp=1 loss curve), re-point the
                # optimizer at the stacked parameters, and rebuild the
                # compiled step around the staged network. The restore
                # below then reshards INTO the stacked placements
                # (canonical per-block checkpoint keys —
                # docs/RESILIENCE.md stage-move reshard)
                staged = stage_model(self.network, plan)
                if staged is not self.network:
                    self.network = staged
                    if self._optimizer is not None:
                        self._optimizer._parameter_list = list(
                            staged.parameters())
                    self._train_step = TrainStep(
                        self.network, self._optimizer, self._loss_fn)
            if plan.batch and batch_size != plan.batch and not isinstance(
                    train_data, DataLoader):
                import warnings

                # the plan's HBM-fit verdict and comms account were
                # computed FOR plan.batch — a different executed batch
                # voids both (a bigger one can OOM a "fits" plan)
                warnings.warn(
                    f"fit(shard_plan=): batch_size={batch_size} differs "
                    f"from the planned global batch {plan.batch}; the "
                    f"plan's HBM-fit and comms estimates assumed "
                    f"{plan.batch}", stacklevel=2)
            if plan.mesh.get("dp", 1) > 1:
                # batches must join the dp split, or XLA lowers the step
                # with the batch REPLICATED and data parallelism is
                # compiled out (the plan's memory/comms account assumed
                # dp-sharded inputs — autoshard/lowering.py lowers the
                # candidates that way)
                shard_batch = _shard_batch
        policy = None
        if nan_policy is not None:
            if nan_policy != "skip":
                raise ValueError(
                    f"fit: nan_policy must be None or 'skip' "
                    f"(got {nan_policy!r})")
            from ..resilience.numerics_policy import NaNSkipPolicy

            policy = NaNSkipPolicy()
            nan_check = True  # the policy rides the sentinel's replay
        start_epoch = 0
        skip_batches = 0
        global_step = 0
        if resume_from is not None:
            from ..resilience import resume as _resume

            crash = int(os.environ.get("PADDLE_RESTART_COUNT", "0")
                        or 0) > 0
            if ledger is not None:
                ledger.enter("restore_resume")
            try:
                scalars = _resume.restore_latest(
                    self.network, self._optimizer, resume_from,
                    train_step=self._train_step, crash_resume=crash)
            finally:
                if ledger is not None:
                    ledger.exit()
            if scalars is not None:
                start_epoch = int(scalars.get("epoch", 0))
                skip_batches = int(scalars.get("batch_in_epoch", 0))
                global_step = int(scalars.get("step", 0))
        mgr = None
        if checkpoint_dir is not None:
            from ..resilience.checkpoint_manager import CheckpointManager

            mgr = CheckpointManager(checkpoint_dir, keep=checkpoint_keep)

        def _ckpt_state(ep, batch_in_epoch, step):
            from ..resilience import resume as _resume

            return _resume.capture(
                self.network, self._optimizer, epoch=ep,
                batch_in_epoch=batch_in_epoch, step=step)
        loader = train_data if isinstance(train_data, DataLoader) else \
            DataLoader(train_data, batch_size=batch_size, shuffle=shuffle,
                       drop_last=drop_last, num_workers=num_workers)
        if skip_batches:
            # the mid-epoch fast-forward replays the loader and discards
            # the first `skip_batches` batches — that only reproduces the
            # pre-crash data under a DETERMINISTIC order. Probe the
            # actual loader (fit-built or user-supplied): an unseeded
            # RandomSampler draws from global numpy state, which the
            # checkpoint cannot capture.
            from ..io.sampler import RandomSampler

            sampler = getattr(getattr(loader, "batch_sampler", None),
                              "sampler", None)
            if isinstance(sampler, RandomSampler) and getattr(
                    sampler, "generator", None) is None:
                import warnings

                warnings.warn(
                    "fit(resume_from=...) is resuming mid-epoch over an "
                    "unseeded shuffling loader: the resumed permutation "
                    "differs from the pre-crash one, so the skipped "
                    "batches are NOT the ones already trained (some "
                    "samples repeat, others are missed this epoch). Use "
                    "shuffle=False or a seeded sampler for exact "
                    "resume.", stacklevel=2)
        try:
            steps = len(loader)
        except Exception:
            steps = None
        cbks = config_callbacks(
            callbacks, model=self, epochs=epochs, steps=steps,
            batch_size=batch_size, verbose=verbose, save_freq=save_freq,
            save_dir=save_dir, metrics=[m.name() for m in self._metrics],
            log_freq=log_freq)
        self.stop_training = False
        cbks.on_train_begin()
        self.network.train()
        stepper = AsyncStepper(self._train_step, max_in_flight=max_in_flight)
        # per-fit sentinel override, applied only now that setup can no
        # longer raise outside the restoring finally below (a failed
        # loader/callback/stepper init must not leak the override)
        prev_nan_check = self._train_step._nan_check
        if nan_check is not None:
            self._train_step._nan_check = bool(nan_check)
        notified_ckpt = None
        # loop position for the terminal checkpoint: (next epoch, next
        # batch) a resume of this run would execute
        pos = (start_epoch, skip_batches)
        # arm the goodput plane: activate the ledger (wiring the
        # module `_goodput` slots), start the hang watchdog, open the
        # fleet heartbeat when a launcher stamped PT_HEARTBEAT_DIR, and
        # join the blackbox as the training state provider. Teardown
        # runs on BOTH exits, after the MonitorCallback's run_end line
        # (which reads the still-active ledger).
        _gp.reset_run()
        tstate = _TrainState(stepper, ledger)
        _blackbox.register("training", tstate.state)
        wdog = None
        hb = None
        if ledger is not None:
            _gp.activate(ledger)
            wdog = _watchdog.Watchdog().start()
        hb_dir = os.environ.get("PT_HEARTBEAT_DIR")
        if hb_dir:
            try:
                hb = _heartbeat.HeartbeatWriter(hb_dir)
            except OSError:
                hb = None  # telemetry must never kill training

        def _goodput_teardown():
            if wdog is not None:
                wdog.stop()
            if hb is not None:
                hb.close()
            if ledger is not None:
                _gp.deactivate(ledger)
        try:
            for epoch in range(start_epoch, epochs):
                cbks.on_epoch_begin(epoch)
                sp = _spans
                t_epoch = time.perf_counter() if sp is not None else None
                it = 0
                logs = {}
                skip_now = skip_batches if epoch == start_epoch else 0
                data_src = loader
                if skip_now:
                    # resume fast-forward: the batches trained before
                    # the checkpoint are consumed from the RAW loader,
                    # host-side only (deterministic loaders replay the
                    # same order) — never staged device-ward by the
                    # prefetcher below, which would pay one useless H2D
                    # transfer per discarded batch
                    data_src = _fast_forward(loader, skip_now)
                epoch_iter = enumerate(data_src, start=skip_now)
                prefetch = None
                if device_prefetch:
                    from ..io.prefetch import DevicePrefetchIterator

                    prefetch = DevicePrefetchIterator(
                        data_src, depth=device_prefetch)
                    epoch_iter = enumerate(prefetch, start=skip_now)
                if ledger is not None:
                    epoch_iter = _input_wait_iter(ledger, epoch_iter)
                try:
                    for step, batch in epoch_iter:
                        cbks.on_train_batch_begin(step)
                        batch = batch if isinstance(batch, (list, tuple)) \
                            else [batch]
                        tensors = _to_tensor_list(batch)
                        if shard_batch is not None:
                            tensors = [shard_batch(t) for t in tensors]
                        t_step = time.perf_counter()
                        if ledger is not None:
                            ledger.enter("productive_step")
                        try:
                            loss = stepper(*tensors)
                        except _NonFiniteError as e:
                            if ledger is not None:
                                # dispatch + sentinel replay that ended
                                # in a drop: not productive wall-clock
                                ledger.exit("nan_replay_or_skip")
                            if policy is None:
                                raise
                            # skip-and-continue: the sentinel raised
                            # BEFORE the rebind, so params/opt/LR/step
                            # are exactly pre-batch — drop it and move
                            # on (record_failure raises past the budget).
                            # on_train_batch_end is deliberately NOT
                            # fired (end hooks carry training-progress
                            # semantics — LRSchedulerCallback steps the
                            # schedule there, and a skipped step must
                            # not advance it), but the batch does count
                            # toward num_iters so the loop stays bounded
                            # on a poison-heavy stream
                            policy.record_failure(e)
                            it += 1
                            if num_iters is not None and it >= num_iters:
                                break
                            continue
                        if ledger is not None:
                            ledger.exit()
                        if policy is not None:
                            policy.record_success()
                        global_step += 1
                        step_ms = (time.perf_counter() - t_step) * 1e3
                        tstate.step = global_step
                        if ledger is not None:
                            # the shared step-time EMA (watchdog deadline,
                            # ckpt cadence, monitor/step_ms_ema gauge);
                            # StepLogger feeds it when no ledger is active
                            _gp.observe_step_ms(step_ms, step=global_step)
                        # lazy between windows; number-like (counted,
                        # sync-on-read) if a user callback touches it
                        logs = {"loss": _LazyLoss(loss)}
                        if step % log_freq == 0:
                            # the window's one host sync — aligned with
                            # ProgBarLogger's print cadence
                            logs = _materialize_logs(logs)
                        lv = logs.get("loss")
                        cur_loss = (float(lv)
                                    if isinstance(lv, (int, float))
                                    else None)
                        if cur_loss is not None:
                            tstate.loss = cur_loss
                        if hb is not None:
                            # fleet heartbeat: loss only on materialized
                            # windows (never force a host sync for
                            # telemetry) — windows align across ranks,
                            # so the launcher's desync detector compares
                            # same-step losses
                            hb.beat(global_step, loss=cur_loss,
                                    step_ms=step_ms,
                                    buckets=ledger.snapshot()["buckets"]
                                    if ledger is not None else None)
                        cbks.on_train_batch_end(step, logs)
                        pos = (epoch, step + 1)
                        if mgr is not None:
                            mgr.maybe_save(
                                global_step,
                                lambda ep=epoch, s=step, g=global_step:
                                _ckpt_state(ep, s + 1, g),
                                stepper=stepper)
                            mgr.poll()
                            if (mgr.last_complete_step is not None
                                    and mgr.last_complete_step
                                    != notified_ckpt):
                                notified_ckpt = mgr.last_complete_step
                                cbks.on_checkpoint(notified_ckpt)
                        it += 1
                        if num_iters is not None and it >= num_iters:
                            break
                finally:
                    if prefetch is not None:
                        prefetch.close()
                # exact final metrics: fence the pipeline, then one sync
                t_drain = time.perf_counter()
                stepper.drain()
                if ledger is not None:
                    # the drain wait finishes already-dispatched steps —
                    # productive wall, charged without bumping the step
                    # count (charge() never increments `steps`)
                    ledger.charge("productive_step",
                                  time.perf_counter() - t_drain)
                logs = _materialize_logs(logs)
                led = _memory._ledger
                if led is not None:
                    # phase-bracket census: post-drain live buffers are
                    # the epoch's steady-state footprint
                    led.census(tag="hapi/fit_epoch")
                if sp is not None:
                    sp.record("hapi/fit_epoch", "phase", t_epoch,
                              args={"epoch": epoch})
                cbks.on_epoch_end(epoch, logs)
                pos = (epoch + 1, 0)
                if eval_data is not None and (epoch + 1) % eval_freq == 0:
                    self.evaluate(eval_data, batch_size=batch_size,
                                  verbose=verbose, callbacks=callbacks)
                    self.network.train()
                if self.stop_training:
                    break
            if mgr is not None:
                # terminal checkpoint: the finished run's final state is
                # durable, and resuming it is a no-op (epoch == epochs).
                # Skipped when this step is already durably checkpointed
                # (resume of a finished run) — rewriting a complete
                # checkpoint in place buys nothing and risks tearing it
                if (mgr.last_save_step != global_step
                        and mgr.last_complete_step != global_step):
                    mgr.save(global_step,
                             _ckpt_state(pos[0], pos[1], global_step),
                             stepper=stepper)
                mgr.finalize()
                if mgr.last_complete_step is not None \
                        and mgr.last_complete_step != notified_ckpt:
                    notified_ckpt = mgr.last_complete_step
                    cbks.on_checkpoint(notified_ckpt)
        except BaseException as e:  # noqa: BLE001 — flush sinks, re-raise
            if mgr is not None:
                # publish any save whose writer ALREADY finished (poll,
                # never join: a crashing run must not block on a stalled
                # writer before its postmortem flushes) — the run_end
                # record then names the true resume point
                try:
                    mgr.poll()
                    if mgr.last_complete_step is not None \
                            and mgr.last_complete_step != notified_ckpt:
                        cbks.on_checkpoint(mgr.last_complete_step)
                except Exception:  # noqa: BLE001 — original error wins
                    pass
            cbks.on_train_error(f"{type(e).__name__}: {e}")
            # after on_train_error: the crashed run's run_end line (and
            # its blackbox dump) read the still-active ledger above
            _goodput_teardown()
            raise
        finally:
            # per-fit override only: later fits follow the global state
            # again unless they pass their own nan_check
            self._train_step._nan_check = prev_nan_check
        cbks.on_train_end()
        # after on_train_end: MonitorCallback's run_end carries
        # `goodput` only while the ledger is still active
        _goodput_teardown()

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None):
        loader = eval_data if isinstance(eval_data, DataLoader) else \
            DataLoader(eval_data, batch_size=batch_size, shuffle=False,
                       num_workers=num_workers)
        cbks = config_callbacks(callbacks, model=self, verbose=verbose,
                                mode="eval")
        self.network.eval()
        for m in self._metrics:
            m.reset()
        losses = []
        sp = _spans
        t_eval = time.perf_counter() if sp is not None else None
        cbks.on_eval_begin()
        for step, batch in enumerate(loader):
            batch = batch if isinstance(batch, (list, tuple)) else [batch]
            n_in = len(batch) - 1 if len(batch) > 1 else 1
            res = self._eval_batch_lazy(batch[:n_in], batch[n_in:])
            if res is not None:
                losses.append(res)  # lazy device scalars
        logs = {}
        if losses:
            # one host transfer for the whole eval pass (counted as a
            # single hapi/host_syncs), instead of one per batch
            logs["loss"] = float(np.mean(_fetch_scalars(losses)))
        for m in self._metrics:
            acc = m.accumulate()
            names = m.name()  # paddle metrics return a list of names
            if isinstance(names, (list, tuple)):
                vals = acc if isinstance(acc, (list, tuple)) else [acc]
                logs.update(zip(names, vals))
            else:
                logs[names] = acc
        led = _memory._ledger
        if led is not None:
            led.census(tag="hapi/evaluate")
        if sp is not None:
            sp.record("hapi/evaluate", "phase", t_eval)
        cbks.on_eval_end(logs)
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        loader = test_data if isinstance(test_data, DataLoader) else \
            DataLoader(test_data, batch_size=batch_size, shuffle=False,
                       num_workers=num_workers)
        self.network.eval()
        outputs = []
        for batch in loader:
            batch = batch if isinstance(batch, (list, tuple)) else [batch]
            # a (inputs, label) dataset reused for predict: drop the label
            # (reference slices by the `inputs` spec; heuristic without one)
            n_in = (len(self._inputs) if self._inputs
                    else len(batch) - 1 if len(batch) > 1 else 1)
            outputs.append(self.predict_batch(batch[:n_in]))
        if stack_outputs and outputs:
            n_out = len(outputs[0])
            return [np.concatenate([o[i] for o in outputs])
                    for i in range(n_out)]
        return outputs

    # -- persistence (parity: Model.save/load -> .pdparams/.pdopt) --
    def save(self, path, training=True):
        dirname = os.path.dirname(path)
        if dirname:
            os.makedirs(dirname, exist_ok=True)
        _save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            state = getattr(self._optimizer, "state_dict", lambda: {})()
            _save(state, path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        self.network.set_state_dict(_load(path + ".pdparams"))
        opt_path = path + ".pdopt"
        if (not reset_optimizer and self._optimizer is not None
                and os.path.exists(opt_path)):
            if hasattr(self._optimizer, "set_state_dict"):
                self._optimizer.set_state_dict(_load(opt_path))

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        from .summary import summary

        return summary(self.network, input_size, dtypes=dtype)


_monitor_register(sys.modules[__name__])
