"""Model summary & flops (parity: `paddle.summary`/`paddle.flops`,
reference `python/paddle/hapi/model_summary.py`, `hapi/dynamic_flops.py`)."""
from __future__ import annotations

import numpy as np

from ..framework.core import Tensor
from ..nn.layer.layers import Layer


def _make_input(input_size, dtype="float32"):
    if isinstance(input_size, (list, tuple)) and input_size and \
            isinstance(input_size[0], (list, tuple)):
        return [_make_input(s, dtype) for s in input_size]
    shape = [d if isinstance(d, int) and d > 0 else 1 for d in input_size]
    if str(dtype).startswith("int"):
        return Tensor(np.zeros(shape, dtype))
    return Tensor(np.zeros(shape, np.dtype(dtype)))


def summary(net, input_size=None, dtypes=None, input=None):  # noqa: A002
    """Prints a per-layer table; returns {'total_params', 'trainable_params'}."""
    rows = []
    hooks = []

    def register(layer, prefix):
        def hook(l, inputs, outputs):
            n_params = sum(int(np.prod(p.shape))
                           for _, p in l.named_parameters(include_sublayers=False))
            out_shape = (list(outputs.shape)
                         if isinstance(outputs, Tensor) else "-")
            rows.append((prefix or l.__class__.__name__,
                         l.__class__.__name__, out_shape, n_params))

        hooks.append(layer.register_forward_post_hook(hook))

    for name, sub in net.named_sublayers():
        register(sub, name)

    x = input if input is not None else _make_input(
        input_size, (dtypes or ["float32"])[0] if isinstance(dtypes, list)
        else (dtypes or "float32"))
    was_training = net.training
    net.eval()
    try:
        net(*x) if isinstance(x, list) else net(x)
    finally:
        if was_training:
            net.train()
        for h in hooks:
            h.remove()

    total = sum(int(np.prod(p.shape)) for p in net.parameters())
    trainable = sum(int(np.prod(p.shape)) for p in net.parameters()
                    if not p.stop_gradient)
    width = 76
    print("-" * width)
    print(f"{'Layer (type)':<34}{'Output Shape':<26}{'Param #':<12}")
    print("=" * width)
    for name, cls, shape, n in rows:
        print(f"{name + ' (' + cls + ')':<34}{str(shape):<26}{n:<12}")
    print("=" * width)
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    print(f"Non-trainable params: {total - trainable:,}")
    print("-" * width)
    return {"total_params": total, "trainable_params": trainable}


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Rough MACs count via forward hooks on Linear/Conv layers (parity:
    `paddle.flops`)."""
    total = [0]
    hooks = []

    def hook(layer, inputs, outputs):
        from ..nn.layer.common import Linear
        from ..nn.layer.conv import Conv2D

        if custom_ops and type(layer) in custom_ops:
            total[0] += int(custom_ops[type(layer)](layer, inputs, outputs))
        elif isinstance(layer, Linear):
            total[0] += int(np.prod(outputs.shape)) * layer.weight.shape[0]
        elif isinstance(layer, Conv2D):
            w = layer.weight
            total[0] += (int(np.prod(outputs.shape))
                         * int(np.prod(w.shape[1:])))

    for _, sub in net.named_sublayers():
        hooks.append(sub.register_forward_post_hook(hook))
    try:
        net(_make_input(input_size))
    finally:
        for h in hooks:
            h.remove()
    if print_detail:
        print(f"Total Flops: {total[0]:,}")
    return total[0]
