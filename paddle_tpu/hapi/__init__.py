"""hapi: high-level Model API (parity: `python/paddle/hapi/`)."""
from .callbacks import (  # noqa: F401
    Callback, EarlyStopping, LRSchedulerCallback, ModelCheckpoint,
    ProgBarLogger,
)
from .model import Model  # noqa: F401
from .summary import flops, summary  # noqa: F401

__all__ = ["Model", "Callback", "ProgBarLogger", "ModelCheckpoint",
           "EarlyStopping", "LRSchedulerCallback", "summary", "flops"]
