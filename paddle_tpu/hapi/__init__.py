"""hapi: high-level Model API (parity: `python/paddle/hapi/`)."""
from .callbacks import (  # noqa: F401
    Callback, EarlyStopping, LRSchedulerCallback, ModelCheckpoint,
    ProgBarLogger, ReduceLROnPlateau, VisualDL, WandbCallback,
)
from .model import Model  # noqa: F401
from .summary import flops, summary  # noqa: F401

__all__ = ["Model", "Callback", "ProgBarLogger", "ModelCheckpoint",
           "EarlyStopping", "LRSchedulerCallback", "ReduceLROnPlateau",
           "VisualDL", "WandbCallback", "summary", "flops"]
