"""hapi callbacks.

Reference parity: `python/paddle/hapi/callbacks.py` — Callback base,
CallbackList dispatch, ProgBarLogger, ModelCheckpoint, EarlyStopping,
LRScheduler callback.
"""
from __future__ import annotations

import os
import time

import numpy as np


class Callback:
    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_predict_begin(self, logs=None):
        pass

    def on_predict_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def __getattr__(self, name):
        if not name.startswith("on_"):
            raise AttributeError(name)

        def call(*args, **kwargs):
            for c in self.callbacks:
                getattr(c, name)(*args, **kwargs)

        return call


class ProgBarLogger(Callback):
    """Parity: hapi ProgBarLogger (per-epoch step/loss/metric lines)."""

    def __init__(self, log_freq=1, verbose=2):
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._t0 = time.time()
        if self.verbose and self.epoch is not None:
            print(f"Epoch {epoch + 1}/{self.params.get('epochs')}")

    def on_train_batch_end(self, step, logs=None):
        logs = logs or {}
        if self.verbose > 1 and step % self.log_freq == 0:
            ips = (step + 1) / max(time.time() - self._t0, 1e-9)
            items = " - ".join(
                f"{k}: {np.asarray(v).item():.4f}"
                if np.ndim(v) == 0 or np.size(v) == 1 else f"{k}: {v}"
                for k, v in logs.items() if k != "batch_size")
            print(f"step {step + 1}/{self.steps or '?'} - {items}"
                  f" - {ips:.2f} step/s")

    def on_eval_end(self, logs=None):
        if self.verbose:
            logs = logs or {}
            items = " - ".join(
                f"{k}: {np.asarray(v).item():.4f}"
                if np.ndim(v) == 0 or np.size(v) == 1 else f"{k}: {v}"
                for k, v in logs.items() if k != "batch_size")
            print(f"Eval - {items}")


class ModelCheckpoint(Callback):
    """Parity: hapi ModelCheckpoint (save every N epochs)."""

    def __init__(self, save_freq=1, save_dir=None):
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    """Parity: hapi EarlyStopping."""

    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        self.verbose = verbose
        if mode == "auto":
            mode = "min" if "loss" in monitor or "err" in monitor else "max"
        self.mode = mode
        self.stopped_epoch = 0
        self.wait = 0
        self.best = None
        self.stop_training = False

    def _better(self, cur, best):
        if self.mode == "min":
            return cur < best - self.min_delta
        return cur > best + self.min_delta

    def on_eval_end(self, logs=None):
        logs = logs or {}
        if self.monitor not in logs:
            return
        cur = float(np.asarray(logs[self.monitor]).reshape(-1)[0])
        if self.best is None or self._better(cur, self.best):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stop_training = True
                self.model.stop_training = True


class LRSchedulerCallback(Callback):
    """Parity: hapi LRScheduler callback — steps the optimizer's scheduler."""

    def __init__(self, by_step=True, by_epoch=False):
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        from ..optimizer.lr import LRScheduler

        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if isinstance(lr, LRScheduler) else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()


def config_callbacks(callbacks=None, model=None, batch_size=None, epochs=None,
                     steps=None, verbose=2, save_freq=1, save_dir=None,
                     metrics=None, mode="train"):
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks.append(ProgBarLogger(verbose=verbose))
    if not any(isinstance(c, LRSchedulerCallback) for c in cbks):
        cbks.append(LRSchedulerCallback())
    if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks.append(ModelCheckpoint(save_freq, save_dir))
    lst = CallbackList(cbks)
    lst.set_model(model)
    lst.set_params({
        "batch_size": batch_size, "epochs": epochs, "steps": steps,
        "verbose": verbose, "metrics": metrics or [],
    })
    return lst
