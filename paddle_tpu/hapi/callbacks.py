"""hapi callbacks.

Reference parity: `python/paddle/hapi/callbacks.py` — Callback base,
CallbackList dispatch, ProgBarLogger, ModelCheckpoint, EarlyStopping,
LRScheduler callback.
"""
from __future__ import annotations

import os
import time

import numpy as np


class Callback:
    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_train_error(self, error=None):
        """Fired (before the exception re-raises) when the fit loop dies —
        the hook that lets sinks flush a terminal record instead of
        leaving a truncated artifact. ``on_train_end`` is NOT called on
        the error path (parity: the reference only ends clean runs)."""
        pass

    def on_checkpoint(self, step, logs=None):
        """Fired when a resilience checkpoint COMPLETES (manifest
        published — not when the async save starts): ``step`` is what a
        relaunch would now resume from."""
        pass

    def on_slo_breach(self, breach=None):
        """Fired when the live telemetry plane's SLO watchdog declares a
        burn-rate breach (``monitor/live.py``; docs/OBSERVABILITY.md
        "Live telemetry plane"). ``breach`` is the structured event dict
        (metric, target, fast/slow burn rates, window sizes).
        Observation-only for now — the ROADMAP 3b SLA-aware scheduler is
        the intended consumer. Only fires while live telemetry is armed
        (``PT_SLO_*`` targets set)."""
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_predict_begin(self, logs=None):
        pass

    def on_predict_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def __getattr__(self, name):
        if not name.startswith("on_"):
            raise AttributeError(name)

        def call(*args, **kwargs):
            for c in self.callbacks:
                getattr(c, name)(*args, **kwargs)

        return call


class ProgBarLogger(Callback):
    """Parity: hapi ProgBarLogger (per-epoch step/loss/metric lines)."""

    def __init__(self, log_freq=1, verbose=2):
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._t0 = time.time()
        if self.verbose and self.epoch is not None:
            print(f"Epoch {epoch + 1}/{self.params.get('epochs')}")

    def on_train_batch_end(self, step, logs=None):
        logs = logs or {}
        if self.verbose > 1 and step % self.log_freq == 0:
            ips = (step + 1) / max(time.time() - self._t0, 1e-9)
            items = " - ".join(
                f"{k}: {np.asarray(v).item():.4f}"
                if np.ndim(v) == 0 or np.size(v) == 1 else f"{k}: {v}"
                for k, v in logs.items() if k != "batch_size")
            print(f"step {step + 1}/{self.steps or '?'} - {items}"
                  f" - {ips:.2f} step/s")

    def on_eval_end(self, logs=None):
        if self.verbose:
            logs = logs or {}
            items = " - ".join(
                f"{k}: {np.asarray(v).item():.4f}"
                if np.ndim(v) == 0 or np.size(v) == 1 else f"{k}: {v}"
                for k, v in logs.items() if k != "batch_size")
            print(f"Eval - {items}")


class ModelCheckpoint(Callback):
    """Parity: hapi ModelCheckpoint (save every N epochs)."""

    def __init__(self, save_freq=1, save_dir=None):
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    """Parity: hapi EarlyStopping."""

    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        self.verbose = verbose
        if mode == "auto":
            mode = "min" if "loss" in monitor or "err" in monitor else "max"
        self.mode = mode
        self.stopped_epoch = 0
        self.wait = 0
        self.best = None
        self.stop_training = False

    def _better(self, cur, best):
        if self.mode == "min":
            return cur < best - self.min_delta
        return cur > best + self.min_delta

    def on_eval_end(self, logs=None):
        logs = logs or {}
        if self.monitor not in logs:
            return
        cur = float(np.asarray(logs[self.monitor]).reshape(-1)[0])
        if self.best is None or self._better(cur, self.best):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stop_training = True
                self.model.stop_training = True


class LRSchedulerCallback(Callback):
    """Parity: hapi LRScheduler callback — steps the optimizer's scheduler."""

    def __init__(self, by_step=True, by_epoch=False):
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        from ..optimizer.lr import LRScheduler

        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if isinstance(lr, LRScheduler) else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()


class MonitorCallback(Callback):
    """Stream per-step runtime telemetry to a JSONL sink
    (`paddle_tpu.monitor.StepLogger`): one line per train batch with loss,
    ips, and the counter diff (retraces, tunnel syncs, collective bytes...)
    attributable to that step. Auto-added by `config_callbacks` when the
    monitor is enabled (``PT_MONITOR=1``); sink path from ``path`` or
    ``PT_MONITOR_SINK``. Step ids are monotonic across epochs."""

    def __init__(self, path=None):
        self.path = path
        self._logger = None

    def on_train_begin(self, logs=None):
        from ..monitor import StepLogger

        params = getattr(self, "params", {}) or {}
        self._logger = StepLogger(self.path, meta={
            "source": "hapi.fit",
            "epochs": params.get("epochs"),
            "steps_per_epoch": params.get("steps"),
            "batch_size": params.get("batch_size"),
        })

    def on_train_batch_end(self, step, logs=None):
        if self._logger is None:
            return
        logs = logs or {}
        params = getattr(self, "params", {}) or {}
        # deferred-sync contract (docs/ASYNC_PIPELINE.md): fit leaves the
        # loss as a lazy device scalar between log windows; forcing it
        # here would re-introduce the per-step host round-trip. Log the
        # loss only on steps where fit already materialized it.
        loss = logs.get("loss")
        if not isinstance(loss, (int, float, np.floating, np.integer)):
            loss = None
        self._logger.log_step(loss=loss,
                              num_samples=params.get("batch_size"))

    def on_checkpoint(self, step, logs=None):
        # the run_end line (clean or crashed) then names the exact step a
        # relaunch will resume from (StepLogger last_checkpoint_step)
        if self._logger is not None:
            self._logger.note_checkpoint(step)

    def on_train_end(self, logs=None):
        if self._logger is not None:
            self._logger.close()
            self._logger = None

    def on_train_error(self, error=None):
        # flush the terminal run_end line with the error, so the JSONL
        # distinguishes "crashed at step N" from "file truncated at N"
        if self._logger is not None:
            self._logger.close(error=error)
            self._logger = None


class _SLOBridge(Callback):
    """Bridges live-telemetry SLO breaches (``monitor.live.subscribe``)
    into the callback chain: every callback's ``on_slo_breach`` fires
    synchronously with the breach. Subscribes only while a run is
    active and only when live telemetry is armed — with live off this
    callback is four no-op method calls per run, zero per step."""

    def __init__(self, cbks):
        self._cbks = cbks
        self._armed = False

    def on_train_begin(self, logs=None):
        from ..monitor import live

        if live.enabled():
            live.subscribe(self._dispatch)
            self._armed = True

    def _dispatch(self, breach):
        for c in self._cbks:
            if not isinstance(c, _SLOBridge):
                c.on_slo_breach(breach)

    def _unsubscribe(self):
        if self._armed:
            from ..monitor import live

            live.unsubscribe(self._dispatch)
            self._armed = False

    def on_train_end(self, logs=None):
        self._unsubscribe()

    def on_train_error(self, error=None):
        self._unsubscribe()


def config_callbacks(callbacks=None, model=None, batch_size=None, epochs=None,
                     steps=None, verbose=2, save_freq=1, save_dir=None,
                     metrics=None, mode="train", log_freq=1):
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        # cadence matches fit's loss-materialization windows, so the
        # printed values are host floats already — no extra device sync
        cbks.append(ProgBarLogger(log_freq=log_freq, verbose=verbose))
    if not any(isinstance(c, LRSchedulerCallback) for c in cbks):
        cbks.append(LRSchedulerCallback())
    if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks.append(ModelCheckpoint(save_freq, save_dir))
    if mode == "train" and not any(isinstance(c, MonitorCallback)
                                   for c in cbks):
        from ..monitor import enabled as _monitor_enabled

        if _monitor_enabled():
            cbks.append(MonitorCallback())
    if mode == "train" and not any(isinstance(c, _SLOBridge)
                                   for c in cbks):
        cbks.append(_SLOBridge(cbks))
    lst = CallbackList(cbks)
    lst.set_model(model)
    lst.set_params({
        "batch_size": batch_size, "epochs": epochs, "steps": steps,
        "verbose": verbose, "metrics": metrics or [],
    })
    return lst


class ReduceLROnPlateau(Callback):
    """Parity: hapi ReduceLROnPlateau (`hapi/callbacks.py:1172`): shrink
    the optimizer LR by ``factor`` after ``patience`` epochs without
    improvement on ``monitor``."""

    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0.0):
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        if mode == "auto":
            mode = "min" if "loss" in monitor or "err" in monitor else "max"
        self.mode = mode
        self.min_delta = abs(min_delta)
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.cooldown_counter = 0
        self.wait = 0
        self.best = None

    def _better(self, cur, best):
        if self.mode == "min":
            return cur < best - self.min_delta
        return cur > best + self.min_delta

    def _epoch_end(self, logs):
        logs = logs or {}
        if self.monitor not in logs:
            return
        cur = float(np.asarray(logs[self.monitor]).reshape(-1)[0])
        if self.cooldown_counter > 0:
            # cooldown epochs never count toward patience (Keras/paddle)
            self.cooldown_counter -= 1
            self.wait = 0
            if self.best is None or self._better(cur, self.best):
                self.best = cur
            return
        if self.best is None or self._better(cur, self.best):
            self.best = cur
            self.wait = 0
            return
        self.wait += 1
        if self.wait >= self.patience:
            opt = getattr(self.model, "_optimizer", None)
            if opt is None:
                return
            try:
                old = float(opt.get_lr())
            except Exception:
                return
            new = max(old * self.factor, self.min_lr)
            if new < old:
                opt.set_lr(new)
                if self.verbose:
                    print(f"ReduceLROnPlateau: lr {old:.3e} -> {new:.3e}")
            self.cooldown_counter = self.cooldown
            self.wait = 0

    # exactly one hook counts per epoch: train logs feed plain monitors,
    # eval logs feed 'eval_*' monitors (both fire every epoch when eval
    # data is present, so using both would double-count patience)
    def on_epoch_end(self, epoch, logs=None):
        if not self.monitor.startswith("eval_"):
            self._epoch_end(logs)

    def on_eval_end(self, logs=None):
        if not self.monitor.startswith("eval_"):
            return
        logs = logs or {}
        val = logs.get(self.monitor,
                       logs.get(self.monitor[len("eval_"):]))
        if val is not None:
            self._epoch_end({self.monitor: val})


def _scalar_logs(logs):
    out = {}
    for k, v in (logs or {}).items():
        try:
            out[k] = float(np.asarray(v).reshape(-1)[0])
        except Exception:
            continue
    return out


class VisualDL(Callback):
    """Parity: hapi VisualDL (`hapi/callbacks.py:883`) — logs epoch
    scalars to a visualdl LogWriter. Requires the external `visualdl`
    package (same optional dependency as the reference)."""

    def __init__(self, log_dir="vdl_log"):
        try:
            import visualdl
        except ImportError as e:
            from ..framework.errors import UnavailableError

            raise UnavailableError(
                "VisualDL callback needs the optional 'visualdl' package "
                "(not bundled; the reference has the same dependency). "
                "Metrics are available via ProgBarLogger / custom "
                "Callback.on_epoch_end") from e
        self.log_dir = log_dir
        self._writer = visualdl.LogWriter(logdir=log_dir)
        self._epoch = 0

    def on_epoch_end(self, epoch, logs=None):
        self._epoch = epoch
        for k, v in _scalar_logs(logs).items():
            self._writer.add_scalar(f"train/{k}", v, epoch)

    def on_eval_end(self, logs=None):
        for k, v in _scalar_logs(logs).items():
            self._writer.add_scalar(f"eval/{k}", v, self._epoch)

    def on_train_end(self, logs=None):
        self._writer.close()


class WandbCallback(Callback):
    """Parity: hapi WandbCallback (`hapi/callbacks.py:999`) — streams
    epoch scalars to a wandb run. Requires the external `wandb` package."""

    def __init__(self, project=None, **wandb_init_kwargs):
        try:
            import wandb
        except ImportError as e:
            from ..framework.errors import UnavailableError

            raise UnavailableError(
                "WandbCallback needs the optional 'wandb' package (not "
                "bundled; the reference has the same dependency)") from e
        self._wandb = wandb
        self._run = wandb.init(project=project, **wandb_init_kwargs) \
            if wandb.run is None else wandb.run

    def on_epoch_end(self, epoch, logs=None):
        self._run.log({f"train/{k}": v
                       for k, v in _scalar_logs(logs).items()},
                      step=epoch)

    def on_eval_end(self, logs=None):
        self._run.log({f"eval/{k}": v
                       for k, v in _scalar_logs(logs).items()})

    def on_train_end(self, logs=None):
        self._run.finish()
