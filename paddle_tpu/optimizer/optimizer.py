"""Optimizers.

Reference parity: `python/paddle/optimizer/` (Optimizer base, SGD, Momentum,
Adagrad, Adam, AdamW, Adamax, RMSProp, Lamb) over PHI optimizer kernels
(`phi/kernels/gpu/adam_kernel.cu` etc.).

TPU-first design: every optimizer is a *pure functional update rule*
(`_init_state` / `_update`) wrapped in a thin stateful shell. The eager path
(`opt.step()`) loops the pure rule over parameters; the compiled path (jit
train step, hapi Engine, distributed sharded states) calls the same rule
inside the traced computation — one implementation, bit-identical both ways.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..autograd.tape import no_grad
from ..framework.core import Tensor
from .lr import LRScheduler


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, multi_precision=False):
        self._learning_rate = learning_rate
        self._parameter_list = list(parameters) if parameters is not None else None
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        if weight_decay is None:
            self._weight_decay = 0.0
        elif isinstance(weight_decay, (int, float)):
            self._weight_decay = float(weight_decay)
        else:  # L2Decay-like object with _coeff
            self._weight_decay = float(getattr(weight_decay, "_coeff", 0.0))
        self._accumulators: dict[int, dict] = {}
        self._global_step = 0
        # per-param update counts: bias correction must use the number of
        # updates *this* param received (reference keeps per-param
        # beta1_pow/beta2_pow accumulators), not the global step — params
        # unfrozen mid-training otherwise get ~10x-undersized first updates
        self._step_counts: dict[int, int] = {}
        # master weights for low-precision params (multi_precision)
        self._master_weights: dict[int, jax.Array] = {}
        self._current_reg = None
        # placement hook for freshly created accumulator state (ZeRO: the
        # group_sharded wrapper sets this to shard moments over the
        # 'sharding' mesh axis — reference GroupShardedOptimizerStage2)
        self._state_placement = None
        # ASP: id(param) -> 0/1 mask reapplied after every update, keeping
        # pruned weights at zero (reference OptimizerWithSparsityGuarantee,
        # `incubate/asp/asp.py`); populated by paddle.incubate.asp.decorate
        self._param_masks: dict[int, jax.Array] = {}

    def _place_state(self, state: dict) -> dict:
        if self._state_placement is None:
            return state
        return {k: self._state_placement(v) for k, v in state.items()}

    def _place_master(self, arr):
        """fp32 master weights are optimizer state too — ZeRO shards them
        (they are the largest single saving)."""
        return arr if self._state_placement is None else self._state_placement(arr)

    # ---- overridable state accessors ----
    # The eager step goes through these so a wrapper can bracket ONE
    # param's state at a time (ZeRO offload stages host->HBM here,
    # bounding peak HBM to a single param's state instead of the whole
    # optimizer — reference offload runs per-param on CPU).
    def _get_accum(self, key):
        return self._accumulators.get(key)

    def _set_accum(self, key, state):
        self._accumulators[key] = state

    def _get_master(self, key):
        return self._master_weights.get(key)

    def _set_master(self, key, arr):
        self._master_weights[key] = arr

    # ---- lr ----
    def get_lr(self):
        if isinstance(self._learning_rate, LRScheduler):
            return self._learning_rate()
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError(
                "optimizer's learning rate is an LRScheduler; call "
                "scheduler.step()/set state instead"
            )
        self._learning_rate = float(value)

    # ---- functional rule (override in subclasses) ----
    def _init_state(self, param):
        """Pure: param array -> dict of state arrays."""
        return {}

    def _update(self, param, grad, state, lr, step):
        """Pure: (param, grad, state, lr, step) -> (new_param, new_state).
        `step` is the 1-based update count."""
        raise NotImplementedError

    # ---- weight decay helpers ----
    def _apply_decoupled_decay(self, work, lr, param):
        """Hook for decoupled (AdamW-style) decay; default no-op."""
        return work

    def _coupled_decay(self, grad, param):
        """Regularization folded into the gradient (reference: regularizer
        ops appended before the optimizer op). A per-param regularizer
        (ParamAttr(regularizer=...)) overrides the optimizer-level decay."""
        reg = self._current_reg
        if reg is not None:
            coeff = float(getattr(reg, "_coeff", 0.0))
            if type(reg).__name__ == "L1Decay":
                return grad + coeff * jnp.sign(param)
            return grad + coeff * param
        if self._weight_decay:
            return grad + self._weight_decay * param
        return grad

    # ---- eager step ----
    @no_grad()
    def step(self):
        params_grads = [
            (p, p.grad) for p in self._parameter_list
            if not p.stop_gradient and p.grad is not None
        ]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        self._global_step += 1
        lr = self.get_lr()
        for p, g in params_grads:
            if g is None:
                continue
            key = id(p)
            self._current_param = p  # per-param context for subclass rules
            self._current_reg = getattr(p, "regularizer", None)
            step = self._step_counts.get(key, 0) + 1
            self._step_counts[key] = step
            # ParamAttr(learning_rate=...) per-param multiplier
            attrs = getattr(p, "optimize_attr", None)
            lr_p = lr * float(attrs.get("learning_rate", 1.0)) if attrs else lr
            param_arr = p._data
            # multi-precision: keep an fp32 master copy for bf16/fp16 params
            if self._multi_precision and param_arr.dtype.name in ("bfloat16", "float16"):
                master = self._get_master(key)
                if master is None:
                    master = self._place_master(param_arr.astype(jnp.float32))
                work = master
                g_arr = g._data.astype(jnp.float32)
            else:
                work = param_arr
                g_arr = g._data.astype(param_arr.dtype)
            state = self._get_accum(key)
            if state is None:
                state = self._place_state(self._init_state(work))
            work = self._apply_decoupled_decay(work, lr_p, p)
            new_p, new_state = self._update(work, g_arr, state, lr_p, step)
            mask = self._param_masks.get(key)
            if mask is not None:
                new_p = new_p * mask.astype(new_p.dtype)
            self._set_accum(key, new_state)
            if self._multi_precision and param_arr.dtype.name in ("bfloat16", "float16"):
                self._set_master(key, new_p)
                p._data = new_p.astype(param_arr.dtype)
            else:
                p._data = new_p

    def clear_grad(self, set_to_zero=False):
        for p in self._parameter_list:
            p.clear_gradient(set_to_zero)

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        loss.backward()
        self.step()
        return None, None

    # ---- checkpoint ----
    def state_dict(self):
        sd = {}
        for i, p in enumerate(self._parameter_list):
            name = p.name or f"param_{i}"
            st = self._accumulators.get(id(p))
            if st:
                for k, v in st.items():
                    sd[f"{name}.{k}"] = Tensor(v)
            mw = self._master_weights.get(id(p))
            if mw is not None:
                sd[f"{name}.master_weight"] = Tensor(mw)
            sc = self._step_counts.get(id(p))
            if sc is not None:
                sd[f"{name}.step_count"] = sc
        sd["global_step"] = self._global_step
        if isinstance(self._learning_rate, LRScheduler):
            sd["LR_Scheduler"] = self._learning_rate.state_dict()
        return sd

    def set_state_dict(self, state_dict):
        self._global_step = int(state_dict.get("global_step", 0))
        if "LR_Scheduler" in state_dict and isinstance(self._learning_rate, LRScheduler):
            self._learning_rate.set_state_dict(state_dict["LR_Scheduler"])
        for i, p in enumerate(self._parameter_list):
            name = p.name or f"param_{i}"
            st = self._init_state(p._data)
            found = False
            for k in st:
                key = f"{name}.{k}"
                if key in state_dict:
                    v = state_dict[key]
                    st[k] = jnp.asarray(
                        v.numpy() if isinstance(v, Tensor) else np.asarray(v)
                    )
                    found = True
            if found:
                self._accumulators[id(p)] = st
            sk = f"{name}.step_count"
            if sk in state_dict:
                self._step_counts[id(p)] = int(state_dict[sk])
            elif found:
                # legacy checkpoints without per-param counts: fall back to
                # the global step so bias correction stays monotonic
                self._step_counts[id(p)] = self._global_step
            mk = f"{name}.master_weight"
            if mk in state_dict:
                v = state_dict[mk]
                self._master_weights[id(p)] = jnp.asarray(
                    v.numpy() if isinstance(v, Tensor) else np.asarray(v)
                )


class SGD(Optimizer):
    """Parity: paddle.optimizer.SGD (`phi/kernels/.../sgd_kernel`)."""

    def _update(self, param, grad, state, lr, step):
        grad = self._coupled_decay(grad, param)
        return param - lr * grad, state


class Momentum(Optimizer):
    """Parity: paddle.optimizer.Momentum (supports Nesterov)."""

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None, name=None,
                 multi_precision=False):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _init_state(self, param):
        return {"velocity": jnp.zeros_like(param)}

    def _update(self, param, grad, state, lr, step):
        grad = self._coupled_decay(grad, param)
        v = self._momentum * state["velocity"] + grad
        if self._nesterov:
            new_p = param - lr * (grad + self._momentum * v)
        else:
            new_p = param - lr * v
        return new_p, {"velocity": v}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, name=None,
                 initial_accumulator_value=0.0):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _init_state(self, param):
        return {"moment": jnp.full_like(param, self._init_acc)}

    def _update(self, param, grad, state, lr, step):
        grad = self._coupled_decay(grad, param)
        m = state["moment"] + grad * grad
        new_p = param - lr * grad / (jnp.sqrt(m) + self._epsilon)
        return new_p, {"moment": m}


class Adam(Optimizer):
    """Parity: paddle.optimizer.Adam (`phi/kernels/gpu/adam_kernel.cu`)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 name=None, amsgrad=False):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._amsgrad = amsgrad

    def _init_state(self, param):
        s = {
            "moment1": jnp.zeros_like(param),
            "moment2": jnp.zeros_like(param),
        }
        if self._amsgrad:
            s["moment2_max"] = jnp.zeros_like(param)
        return s

    def _update(self, param, grad, state, lr, step):
        grad = self._coupled_decay(grad, param)
        b1, b2 = self._beta1, self._beta2
        m = b1 * state["moment1"] + (1 - b1) * grad
        v = b2 * state["moment2"] + (1 - b2) * grad * grad
        m_hat = m / (1 - b1 ** step)
        if self._amsgrad:
            v_max = jnp.maximum(state["moment2_max"], v)
            v_hat = v_max / (1 - b2 ** step)
            new_state = {"moment1": m, "moment2": v, "moment2_max": v_max}
        else:
            v_hat = v / (1 - b2 ** step)
            new_state = {"moment1": m, "moment2": v}
        new_p = param - lr * m_hat / (jnp.sqrt(v_hat) + self._epsilon)
        return new_p, new_state


class AdamW(Adam):
    """Decoupled weight decay (parity: paddle.optimizer.AdamW)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None,
                 amsgrad=False):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, multi_precision, name,
                         amsgrad)
        self._decoupled_wd = float(weight_decay) if not hasattr(weight_decay, "_coeff") else float(weight_decay._coeff)
        self._apply_decay_param_fun = apply_decay_param_fun

    def _apply_decoupled_decay(self, work, lr, param):
        if not self._decoupled_wd:
            return work
        if self._apply_decay_param_fun is not None:
            if not self._apply_decay_param_fun(param.name or ""):
                return work
        return work * (1.0 - lr * self._decoupled_wd)


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _init_state(self, param):
        return {"moment": jnp.zeros_like(param), "inf_norm": jnp.zeros_like(param)}

    def _update(self, param, grad, state, lr, step):
        grad = self._coupled_decay(grad, param)
        m = self._beta1 * state["moment"] + (1 - self._beta1) * grad
        u = jnp.maximum(self._beta2 * state["inf_norm"], jnp.abs(grad))
        new_p = param - (lr / (1 - self._beta1 ** step)) * m / (u + self._epsilon)
        return new_p, {"moment": m, "inf_norm": u}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _init_state(self, param):
        s = {"mean_square": jnp.zeros_like(param), "momentum": jnp.zeros_like(param)}
        if self._centered:
            s["mean_grad"] = jnp.zeros_like(param)
        return s

    def _update(self, param, grad, state, lr, step):
        grad = self._coupled_decay(grad, param)
        ms = self._rho * state["mean_square"] + (1 - self._rho) * grad * grad
        if self._centered:
            mg = self._rho * state["mean_grad"] + (1 - self._rho) * grad
            denom = jnp.sqrt(ms - mg * mg + self._epsilon)
            new_state = {"mean_square": ms, "mean_grad": mg}
        else:
            denom = jnp.sqrt(ms + self._epsilon)
            new_state = {"mean_square": ms}
        mom = self._momentum * state["momentum"] + lr * grad / denom
        new_state["momentum"] = mom
        return param - mom, new_state


class Lamb(Optimizer):
    """Parity: paddle.optimizer.Lamb (layerwise adaptive large-batch)."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name,
                         multi_precision)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _init_state(self, param):
        return {"moment1": jnp.zeros_like(param), "moment2": jnp.zeros_like(param)}

    def _update(self, param, grad, state, lr, step):
        b1, b2 = self._beta1, self._beta2
        m = b1 * state["moment1"] + (1 - b1) * grad
        v = b2 * state["moment2"] + (1 - b2) * grad * grad
        m_hat = m / (1 - b1 ** step)
        v_hat = v / (1 - b2 ** step)
        r = m_hat / (jnp.sqrt(v_hat) + self._epsilon)
        wd = self._lamb_wd
        if self._exclude_fn is not None:
            cur = getattr(self, "_current_param", None)
            if cur is not None and self._exclude_fn(cur.name or ""):
                wd = 0.0
        update = r + wd * param
        w_norm = jnp.linalg.norm(param.astype(jnp.float32).reshape(-1))
        u_norm = jnp.linalg.norm(update.astype(jnp.float32).reshape(-1))
        trust = jnp.where(
            (w_norm > 0) & (u_norm > 0), w_norm / u_norm, 1.0
        ).astype(param.dtype)
        new_p = param - lr * trust * update
        return new_p, {"moment1": m, "moment2": v}


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho, self._epsilon = rho, epsilon

    def _init_state(self, param):
        return {
            "avg_squared_grad": jnp.zeros_like(param),
            "avg_squared_update": jnp.zeros_like(param),
        }

    def _update(self, param, grad, state, lr, step):
        grad = self._coupled_decay(grad, param)
        asg = self._rho * state["avg_squared_grad"] + (1 - self._rho) * grad * grad
        upd = (
            jnp.sqrt(state["avg_squared_update"] + self._epsilon)
            / jnp.sqrt(asg + self._epsilon)
        ) * grad
        asu = self._rho * state["avg_squared_update"] + (1 - self._rho) * upd * upd
        return param - lr * upd, {
            "avg_squared_grad": asg, "avg_squared_update": asu,
        }


class L2Decay:
    """Parity: paddle.regularizer.L2Decay."""

    def __init__(self, coeff=0.0):
        self._coeff = coeff


class L1Decay:
    def __init__(self, coeff=0.0):
        self._coeff = coeff
