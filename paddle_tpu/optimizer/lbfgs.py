"""L-BFGS (reference `python/paddle/optimizer/lbfgs.py`).

Host-driven quasi-Newton: the two-loop recursion runs over a bounded
(s, y) history of flattened parameter deltas; each inner evaluation calls
the user closure, which runs the (compiled) forward/backward. Like the
reference, `step(closure)` may evaluate the closure several times
(line search)."""
from __future__ import annotations

import numpy as np

from .optimizer import Optimizer

__all__ = ["LBFGS"]


class LBFGS(Optimizer):
    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9,
                 history_size=100, line_search_fn=None, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate=learning_rate, parameters=parameters,
                         weight_decay=weight_decay, grad_clip=grad_clip)
        self.max_iter = int(max_iter)
        self.max_eval = int(max_eval) if max_eval else self.max_iter * 5 // 4
        self.tolerance_grad = tolerance_grad
        self.tolerance_change = tolerance_change
        self.history_size = int(history_size)
        if line_search_fn not in (None, "strong_wolfe"):
            raise ValueError("line_search_fn must be None or 'strong_wolfe'")
        self.line_search_fn = line_search_fn
        self._s: list[np.ndarray] = []
        self._y: list[np.ndarray] = []
        self._prev_flat = None
        self._prev_grad = None

    # -- flat views over the parameter list --
    def _flat_params(self):
        return np.concatenate(
            [np.asarray(p._data, np.float64).ravel()
             for p in self._parameter_list])

    def _flat_grads(self):
        out = []
        for p in self._parameter_list:
            g = p.grad
            arr = (np.zeros(int(np.prod(p.shape) or 1), np.float64)
                   if g is None
                   else np.asarray(g._data, np.float64).ravel())
            out.append(arr)
        return np.concatenate(out)

    def _assign(self, flat):
        import jax.numpy as jnp

        i = 0
        for p in self._parameter_list:
            n = int(np.prod(p.shape) or 1)
            p._data = jnp.asarray(
                flat[i:i + n].reshape(p.shape or ()), p._data.dtype)
            i += n

    def _direction(self, g):
        q = g.copy()
        alphas = []
        for s, y in zip(reversed(self._s), reversed(self._y)):
            rho = 1.0 / max(float(y @ s), 1e-20)
            a = rho * (s @ q)
            alphas.append((a, rho, s, y))
            q -= a * y
        if self._y:
            y = self._y[-1]
            s = self._s[-1]
            q *= float(s @ y) / max(float(y @ y), 1e-20)
        for a, rho, s, y in reversed(alphas):
            b = rho * (y @ q)
            q += (a - b) * s
        return -q

    # -- line searches ------------------------------------------------
    # Both return (loss, step_size, evals_used, accepted). On rejection
    # the caller restores the pre-step point: leaving parameters at a
    # failed (possibly worse-loss) trial point corrupts every following
    # iteration.

    def _armijo(self, eval_closure, flat, d, t, base, gd, eval_budget):
        evals = 0
        for _bt in range(20):
            if evals >= eval_budget:
                break
            self._assign(flat + t * d)
            trial = eval_closure()
            evals += 1
            if trial <= base + 1e-4 * t * gd:
                return trial, t, evals, True
            t *= 0.5
        return base, 0.0, evals, False

    def _strong_wolfe(self, eval_closure, flat, d, t, base, gd,
                      eval_budget, c1=1e-4, c2=0.9):
        """Bracket + bisection-zoom strong-Wolfe search (reference
        `python/paddle/optimizer/lbfgs.py` `_strong_wolfe`; bisection in
        place of its cubic interpolation — same conditions, a few more
        closure calls in the worst case)."""
        evals = 0

        def phi(step_size):
            nonlocal evals
            self._assign(flat + step_size * d)
            f = eval_closure()
            evals += 1
            return f, float(self._flat_grads() @ d)

        t_prev, f_prev, g_prev = 0.0, base, gd
        bracket = None
        f_new, g_new = base, gd
        for i in range(10):
            if evals >= eval_budget:
                # budget exhausted mid-bracketing: params sit at t_prev,
                # the best descending point found — keep that progress
                # (reference _strong_wolfe keeps the last iterate on
                # max_ls exhaustion) instead of discarding the iteration
                if f_prev < base and t_prev > 0.0:
                    return f_prev, t_prev, evals, True
                return base, 0.0, evals, False
            f_new, g_new = phi(t)
            if f_new > base + c1 * t * gd or (i > 0 and f_new >= f_prev):
                bracket = (t_prev, t, f_prev, f_new, g_prev, g_new)
                break
            if abs(g_new) <= -c2 * gd:
                return f_new, t, evals, True  # both conditions hold
            if g_new >= 0:
                bracket = (t, t_prev, f_new, f_prev, g_new, g_prev)
                break
            t_prev, f_prev, g_prev = t, f_new, g_new
            t *= 2.0
        if bracket is None:  # ran out of expansion steps while descending
            return f_new, t_prev, evals, f_new < base
        lo, hi, f_lo, f_hi, g_lo, g_hi = bracket
        for _ in range(10):
            if evals >= eval_budget or abs(hi - lo) * float(
                    np.abs(d).max(initial=0.0)) <= self.tolerance_change:
                break
            mid = 0.5 * (lo + hi)
            f_mid, g_mid = phi(mid)
            if f_mid > base + c1 * mid * gd or f_mid >= f_lo:
                hi, f_hi, g_hi = mid, f_mid, g_mid
            else:
                if abs(g_mid) <= -c2 * gd:
                    return f_mid, mid, evals, True
                if g_mid * (hi - lo) >= 0:
                    hi, f_hi, g_hi = lo, f_lo, g_lo
                lo, f_lo, g_lo = mid, f_mid, g_mid
        if f_lo < base and evals < eval_budget:
            # Armijo point without curvature: still usable; the re-eval
            # leaves params+grads at the accepted point and must respect
            # the max_eval budget like every other closure call
            self._assign(flat + lo * d)
            f_lo = eval_closure()
            evals += 1
            return f_lo, lo, evals, True
        return base, 0.0, evals, False

    def step(self, closure=None):
        if closure is None:
            raise ValueError(
                "LBFGS.step needs a closure that reevaluates the model "
                "and returns the loss")

        def eval_closure():
            self.clear_grad()
            loss = closure()
            return float(np.asarray(loss.numpy(), np.float64))

        loss = eval_closure()
        evals = 1
        for _ in range(self.max_iter):
            flat = self._flat_params()
            g = self._flat_grads()
            if float(np.abs(g).max(initial=0.0)) <= self.tolerance_grad:
                break
            if self._prev_flat is not None:
                s = flat - self._prev_flat
                y = g - self._prev_grad
                if float(y @ s) > 1e-10:
                    self._s.append(s)
                    self._y.append(y)
                    if len(self._s) > self.history_size:
                        self._s.pop(0)
                        self._y.pop(0)
            self._prev_flat = flat
            self._prev_grad = g
            d = self._direction(g)
            gd = float(g @ d)
            if gd > -1e-20:  # not a descent direction: reset history
                d = -g
                gd = float(g @ d)
                self._s.clear()
                self._y.clear()
            t = float(self.get_lr())
            search = (self._strong_wolfe
                      if self.line_search_fn == "strong_wolfe"
                      else self._armijo)
            trial, t, used, ok = search(
                eval_closure, flat, d, t, loss, gd, self.max_eval - evals)
            evals += used
            if not ok:
                # restore the pre-step point; refresh its gradients if the
                # budget allows so a caller inspecting p.grad sees the
                # accepted point, not the failed trial
                self._assign(flat)
                if evals < self.max_eval:
                    eval_closure()
                    evals += 1
                break
            loss = trial
            if abs(float(np.abs(t * d).max(initial=0.0))) \
                    <= self.tolerance_change:
                break
            if evals >= self.max_eval:
                break
        from ..framework.core import Tensor
        import jax.numpy as jnp

        return Tensor(jnp.asarray(loss, jnp.float32))
