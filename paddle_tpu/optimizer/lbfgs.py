"""L-BFGS (reference `python/paddle/optimizer/lbfgs.py`).

Host-driven quasi-Newton: the two-loop recursion runs over a bounded
(s, y) history of flattened parameter deltas; each inner evaluation calls
the user closure, which runs the (compiled) forward/backward. Like the
reference, `step(closure)` may evaluate the closure several times
(line search)."""
from __future__ import annotations

import numpy as np

from .optimizer import Optimizer

__all__ = ["LBFGS"]


class LBFGS(Optimizer):
    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9,
                 history_size=100, line_search_fn=None, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate=learning_rate, parameters=parameters,
                         weight_decay=weight_decay, grad_clip=grad_clip)
        self.max_iter = int(max_iter)
        self.max_eval = int(max_eval) if max_eval else self.max_iter * 5 // 4
        self.tolerance_grad = tolerance_grad
        self.tolerance_change = tolerance_change
        self.history_size = int(history_size)
        if line_search_fn not in (None, "strong_wolfe"):
            raise ValueError("line_search_fn must be None or 'strong_wolfe'")
        self.line_search_fn = line_search_fn
        self._s: list[np.ndarray] = []
        self._y: list[np.ndarray] = []
        self._prev_flat = None
        self._prev_grad = None

    # -- flat views over the parameter list --
    def _flat_params(self):
        return np.concatenate(
            [np.asarray(p._data, np.float64).ravel()
             for p in self._parameter_list])

    def _flat_grads(self):
        out = []
        for p in self._parameter_list:
            g = p.grad
            arr = (np.zeros(int(np.prod(p.shape) or 1), np.float64)
                   if g is None
                   else np.asarray(g._data, np.float64).ravel())
            out.append(arr)
        return np.concatenate(out)

    def _assign(self, flat):
        import jax.numpy as jnp

        i = 0
        for p in self._parameter_list:
            n = int(np.prod(p.shape) or 1)
            p._data = jnp.asarray(
                flat[i:i + n].reshape(p.shape or ()), p._data.dtype)
            i += n

    def _direction(self, g):
        q = g.copy()
        alphas = []
        for s, y in zip(reversed(self._s), reversed(self._y)):
            rho = 1.0 / max(float(y @ s), 1e-20)
            a = rho * (s @ q)
            alphas.append((a, rho, s, y))
            q -= a * y
        if self._y:
            y = self._y[-1]
            s = self._s[-1]
            q *= float(s @ y) / max(float(y @ y), 1e-20)
        for a, rho, s, y in reversed(alphas):
            b = rho * (y @ q)
            q += (a - b) * s
        return -q

    def step(self, closure=None):
        if closure is None:
            raise ValueError(
                "LBFGS.step needs a closure that reevaluates the model "
                "and returns the loss")

        def eval_closure():
            self.clear_grad()
            loss = closure()
            return float(np.asarray(loss.numpy(), np.float64))

        loss = eval_closure()
        evals = 1
        for _ in range(self.max_iter):
            flat = self._flat_params()
            g = self._flat_grads()
            if float(np.abs(g).max(initial=0.0)) <= self.tolerance_grad:
                break
            if self._prev_flat is not None:
                s = flat - self._prev_flat
                y = g - self._prev_grad
                if float(y @ s) > 1e-10:
                    self._s.append(s)
                    self._y.append(y)
                    if len(self._s) > self.history_size:
                        self._s.pop(0)
                        self._y.pop(0)
            self._prev_flat = flat
            self._prev_grad = g
            d = self._direction(g)
            gd = float(g @ d)
            if gd > -1e-20:  # not a descent direction: reset history
                d = -g
                gd = float(g @ d)
                self._s.clear()
                self._y.clear()
            t = float(self.get_lr())
            # backtracking Armijo (sufficient decrease); the reference
            # uses strong-wolfe — Armijo keeps the same contract with
            # fewer closure calls and guarantees monotone loss. The
            # closure runs its own backward, so the accepted point's
            # gradients are fresh for the next iteration.
            base = loss
            trial = base
            for _bt in range(20):
                self._assign(flat + t * d)
                trial = eval_closure()
                evals += 1
                if trial <= base + 1e-4 * t * gd \
                        or evals >= self.max_eval:
                    break
                t *= 0.5
            loss = trial
            if abs(float(np.abs(t * d).max(initial=0.0))) \
                    <= self.tolerance_change:
                break
            if evals >= self.max_eval:
                break
        from ..framework.core import Tensor
        import jax.numpy as jnp

        return Tensor(jnp.asarray(loss, jnp.float32))
