"""`paddle.optimizer` (parity: `python/paddle/optimizer/__init__.py`)."""
from . import lr  # noqa: F401
from .lbfgs import LBFGS  # noqa: F401
from .optimizer import (  # noqa: F401
    Optimizer, SGD, Momentum, Adagrad, Adam, AdamW, Adamax, RMSProp, Lamb,
    Adadelta, L2Decay, L1Decay,
)
