"""paddle_tpu: a TPU-native deep learning framework with PaddlePaddle's
capabilities, built from scratch on JAX/XLA/Pallas.

Structural blueprint: SURVEY.md at the repo root. The public API mirrors
`paddle.*` (so a Paddle user can switch), while the implementation is
TPU-first: XLA compilation instead of PHI CUDA kernels, GSPMD sharding
instead of NCCL process groups, Pallas instead of hand-written CUDA.
"""
from __future__ import annotations

__version__ = "0.1.0"

# dtypes at top level (paddle.float32 ...)
from .framework.dtype import (  # noqa: F401
    bool, uint8, int8, int16, int32, int64, float16, bfloat16, float32,
    float64, complex64, complex128,
    set_default_dtype, get_default_dtype,
)
from .framework import dtype as dtype  # noqa: F401
from .framework.core import Tensor, to_tensor  # noqa: F401
from .framework.core import EagerParamBase, Parameter  # noqa: F401
from .framework.device import (  # noqa: F401
    set_device, get_device, device_count, is_compiled_with_tpu,
)
from .framework.random import seed, get_rng_state, set_rng_state  # noqa: F401
from .autograd.tape import no_grad, enable_grad, is_grad_enabled, set_grad_enabled  # noqa: F401
from .autograd.tape import grad  # noqa: F401

from . import tensor  # noqa: F401
from . import autograd  # noqa: F401
from . import ops  # noqa: F401

# hoist every tensor op to the top level: paddle_tpu.add, paddle_tpu.matmul...
for _name in dir(tensor):
    if _name.startswith("_"):
        continue
    _fn = getattr(tensor, _name)
    if callable(_fn) and getattr(_fn, "__module__", "").startswith("paddle_tpu.tensor"):
        globals().setdefault(_name, _fn)
globals()["einsum"] = tensor.einsum

# places / static-mode toggles / dtype + misc shims (reference top-level
# long tail)
from .framework.compat import (  # noqa: F401
    CPUPlace, CUDAPlace, CUDAPinnedPlace, TPUPlace, LazyGuard,
    enable_static, disable_static, in_dynamic_mode, in_static_mode,
    set_printoptions, finfo, iinfo, shape, rank, tolist,
    is_floating_point, is_integer, is_complex, create_parameter,
    get_cuda_rng_state, set_cuda_rng_state, check_shape,
    disable_signal_handler,
)
def _make_inplace(_base):
    from .tensor.manipulation import _adopt_inplace

    def g(x, *args, **kwargs):
        return _adopt_inplace(x, _base(x, *args, **kwargs))

    g.__name__ = _base.__name__ + "_"
    g.__doc__ = f"In-place variant of paddle.{_base.__name__}."
    return g


# module-level trailing-underscore inplace API (paddle convention); the
# Tensor-method variants are bound by tensor.attach
for _name in [
    "abs", "acos", "addmm", "atan", "cos", "digamma", "erf", "expm1",
    "frac", "i0", "index_add", "index_put", "lgamma", "log", "log10",
    "log2", "logit", "neg", "polygamma", "pow", "sin", "sinh", "square",
    "tan", "tanh", "tril", "triu", "trunc", "add", "subtract", "multiply",
    "divide", "clip", "scale", "exp", "sqrt", "rsqrt", "ceil", "floor",
    "round", "reciprocal", "sigmoid",
]:
    globals().setdefault(_name + "_", _make_inplace(getattr(tensor, _name)))

rand = tensor.random.rand
randn = tensor.random.randn
randint = tensor.random.randint
randperm = tensor.random.randperm
uniform = tensor.random.uniform
normal = tensor.random.normal
bernoulli = tensor.random.bernoulli
multinomial = tensor.random.multinomial
is_tensor = tensor.logic.is_tensor

# subpackages that land in later milestones are imported lazily so the core
# works standalone during bring-up
import importlib as _importlib

_LAZY = {
    "nn": ".nn",
    "optimizer": ".optimizer",
    "io": ".io",
    "amp": ".amp",
    "jit": ".jit",
    "metric": ".metric",
    "distributed": ".distributed",
    "vision": ".vision",
    "hapi": ".hapi",
    "profiler": ".profiler",
    "linalg": ".tensor.linalg",
    "incubate": ".incubate",
    "distribution": ".distribution",
    "sparse": ".sparse",
    "static": ".static",
    "models": ".models",
    "device": ".framework.device",
    "framework": ".framework",
    "utils": ".utils",
    "text": ".text",
    "quantization": ".quantization",
    "audio": ".audio",
    "onnx": ".onnx",
    "fft": ".fft",
    "inference": ".inference",
    "geometric": ".geometric",
    "signal": ".signal",
    "callbacks": ".callbacks",
    "regularizer": ".regularizer",
    "sysconfig": ".sysconfig",
    "hub": ".hub",
    "reader": ".reader",
    "dataset": ".dataset",
    "cost_model": ".cost_model",
    "monitor": ".monitor",
    "serving": ".serving",
    "resilience": ".resilience",
}


_LAZY_ATTRS = {
    "Model": (".hapi.model", "Model"),
    "DataParallel": (".distributed.parallel", "DataParallel"),
    "batch": (".batch", "batch"),
    "ParamAttr": (".nn.layer.layers", "ParamAttr"),
}


def __getattr__(name):
    if name in _LAZY:
        mod = _importlib.import_module(_LAZY[name], __name__)
        globals()[name] = mod
        return mod
    if name in _LAZY_ATTRS:
        modname, attr = _LAZY_ATTRS[name]
        val = getattr(_importlib.import_module(modname, __name__), attr)
        globals()[name] = val
        return val
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def save(obj, path, protocol=4, **kwargs):
    from .framework.io import save as _save

    return _save(obj, path, protocol=protocol, **kwargs)


def load(path, **kwargs):
    from .framework.io import load as _load

    return _load(path, **kwargs)


def summary(net, input_size=None, dtypes=None, input=None):  # noqa: A002
    from .hapi.summary import summary as _summary

    return _summary(net, input_size, dtypes=dtypes, input=input)


def flops(net, input_size, custom_ops=None, print_detail=False):
    from .hapi.summary import flops as _flops

    return _flops(net, input_size, custom_ops=custom_ops, print_detail=print_detail)


def set_flags(flags):
    from .framework.flags import set_flags as _set

    return _set(flags)


def get_flags(flags=None):
    from .framework.flags import get_flags as _get

    return _get(flags)


def set_grad_enabled_ctx(mode):  # paddle.set_grad_enabled is a context manager
    return set_grad_enabled(mode)
