"""Autocast: `paddle.amp.auto_cast` / `paddle.amp.decorate`.

Reference parity: `python/paddle/amp/auto_cast.py:271` (`amp_guard`) and
`:756` (`decorate`); the cast insertion point mirrors the generated
ad_funcs' AMP block (`paddle/fluid/eager/amp_utils.h:108`) — here it is the
single `_amp_hook` in `paddle_tpu.ops.dispatch.apply`, so every eager op and
every traced op inside `jit` sees the same policy.

Levels: O1 casts white-list op inputs to low precision and black-list op
inputs to fp32; O2 additionally keeps ("pure" low precision) everything
except black-list ops in low precision. O2 users typically `decorate` the
model so parameters themselves are stored low-precision with fp32 master
weights in the optimizer.
"""
from __future__ import annotations

import contextlib
import sys
import threading

import jax.numpy as jnp

from ..monitor import _register as _monitor_register
from ..ops import dispatch
from . import amp_lists

# Telemetry slot (see paddle_tpu.monitor): counts autocast region entries.
_monitor = None

_state = threading.local()


def _ctx():
    if not hasattr(_state, "stack"):
        _state.stack = []
    return _state.stack


def amp_state():
    stack = _ctx()
    return stack[-1] if stack else None


_LOW = {"float16": jnp.float16, "bfloat16": jnp.bfloat16}


class _AmpConfig:
    __slots__ = ("enable", "level", "dtype", "white", "black")

    def __init__(self, enable, level, dtype, custom_white, custom_black):
        self.enable = enable
        self.level = level.upper()
        self.dtype = dtype
        white = amp_lists.white_list()
        black = amp_lists.black_list()
        if custom_white:
            white |= set(custom_white)
            black -= set(custom_white)
        if custom_black:
            black |= set(custom_black)
            white -= set(custom_black)
        self.white = white
        self.black = black


def _cast_arrays(arrays, target):
    out = []
    for a in arrays:
        if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating) \
                and a.dtype != target:
            out.append(a.astype(target))
        else:
            out.append(a)
    return out


# ops that must never be blanket-cast: program containers (inner ops are
# cast individually during tracing) and explicit dtype ops
_NO_CAST = {"run_program", "cast", "clone"}


def _amp_hook(op_name, arrays):
    cfg = amp_state()
    if cfg is None or not cfg.enable or op_name in _NO_CAST:
        return arrays
    low = _LOW[cfg.dtype]
    if op_name in cfg.black:
        return _cast_arrays(arrays, jnp.float32)
    if op_name in cfg.white:
        return _cast_arrays(arrays, low)
    if cfg.level == "O2":
        return _cast_arrays(arrays, low)
    # O1 gray ops: promote to the widest floating dtype among inputs so
    # mixed fp32/low inputs don't fail (reference: GetPromoteType)
    dtypes = {a.dtype for a in arrays
              if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating)}
    if len(dtypes) > 1:
        return _cast_arrays(arrays, jnp.float32)
    return arrays


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16", use_promote=True):
    """Context manager enabling mixed precision (`paddle.amp.auto_cast`).

    TPU note: default dtype is bfloat16 — fp32 exponent range, so GradScaler
    is a no-op under bf16 (kept for API parity, enabled for fp16).
    """
    if dtype not in _LOW:
        raise ValueError(f"amp dtype must be float16|bfloat16, got {dtype!r}")
    if level.upper() not in ("O0", "O1", "O2"):
        raise ValueError(f"amp level must be O0|O1|O2, got {level!r}")
    cfg = _AmpConfig(enable and level.upper() != "O0", level, dtype,
                     custom_white_list, custom_black_list)
    if _monitor is not None and cfg.enable:
        _monitor.on_autocast_enter()
    stack = _ctx()
    stack.append(cfg)
    try:
        yield
    finally:
        stack.pop()


# the hook is installed once and permanently: it reads the *thread-local*
# config stack and no-ops when empty, so concurrent threads entering/leaving
# auto_cast cannot disable each other's casting; the active-predicate keeps
# the non-AMP fast path to a single boolean check
dispatch.set_amp_hook(_amp_hook, lambda: len(_ctx()) > 0)


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """Cast model params to the AMP dtype and enable optimizer master
    weights (`paddle.amp.decorate`, reference `auto_cast.py:756`).

    O2 stores parameters in low precision; optimizers created with
    `multi_precision=True` (forced here) keep fp32 master copies.
    """
    from ..nn.layer.layers import Layer

    if level.upper() not in ("O1", "O2"):
        raise ValueError("decorate level must be O1 or O2")
    single_model = isinstance(models, Layer)
    model_list = [models] if single_model else list(models)
    if level.upper() == "O2":
        for m in model_list:
            # parameters go low-precision; buffers (norm running stats) are
            # deliberately left fp32, matching the reference's O2 behavior
            for p in m.parameters():
                if p._data.dtype == jnp.float32:
                    p._data = p._data.astype(_LOW[dtype])
    out_opt = optimizers
    if optimizers is not None:
        single_opt = not isinstance(optimizers, (list, tuple))
        opt_list = [optimizers] if single_opt else list(optimizers)
        for opt in opt_list:
            opt._multi_precision = True
        out_opt = opt_list[0] if single_opt else opt_list
    if optimizers is None:
        return model_list[0] if single_model else model_list
    return (model_list[0] if single_model else model_list), out_opt


_monitor_register(sys.modules[__name__])
