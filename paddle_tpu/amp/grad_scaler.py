"""Dynamic loss scaling: `paddle.amp.GradScaler`.

Reference parity: `python/paddle/amp/grad_scaler.py:576` (GradScaler over
AmpScaler): scale() multiplies the loss, step/update unscale grads, skip the
step on inf/nan, and adapt the scale (x2 after `incr_every_n_steps` good
steps, /2 on a bad step).

TPU note: needed for fp16; under bfloat16 (the TPU default) overflow is as
rare as fp32, so `enable=False` scalers (identity) are common — same as the
reference's behavior when amp dtype is bf16.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor


class GradScaler:
    def __init__(self, enable=True, init_loss_scaling=2.0 ** 16,
                 incr_ratio=2.0, decr_ratio=0.5,
                 incr_every_n_steps=2000, decr_every_n_nan_or_inf=1,
                 use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling) if enable else 1.0
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._unscaled = False

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_loss_scaling(self):
        return self._scale

    def scale(self, var):
        """Multiply the loss by the scale factor."""
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        """Divide accumulated grads by the scale and detect inf/nan
        (reference `grad_scaler.py` _unscale)."""
        if not self._enable or self._unscaled:
            return
        found = False
        for p in optimizer._parameter_list:
            if p.grad is None:
                continue
            g = p.grad._data / self._scale
            if not found and not bool(jnp.all(jnp.isfinite(g))):
                found = True
            p.grad = Tensor(g, stop_gradient=True)
        self._found_inf = found
        self._unscaled = True

    def step(self, optimizer):
        """unscale + optimizer.step unless overflow was found."""
        if not self._enable:
            optimizer.step()
            return
        if not self._unscaled:
            self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self._unscaled = False

    def update(self):
        """Adapt the loss scale after a step."""
        if not self._enable or not self._dynamic:
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n_steps:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)
        self.update()

    # ---- checkpoint ----
    def state_dict(self):
        return {
            "scale": self._scale,
            "incr_ratio": self._incr_ratio,
            "decr_ratio": self._decr_ratio,
            "incr_every_n_steps": self._incr_every_n_steps,
            "decr_every_n_nan_or_inf": self._decr_every_n,
            "incr_count": self._good_steps,
            "decr_count": self._bad_steps,
            "use_dynamic_loss_scaling": self._dynamic,
        }

    def load_state_dict(self, state):
        self._scale = float(state.get("scale", self._scale))
        self._good_steps = int(state.get("incr_count", 0))
        self._bad_steps = int(state.get("decr_count", 0))
        self._dynamic = bool(
            state.get("use_dynamic_loss_scaling", self._dynamic))

    set_state_dict = load_state_dict


AmpScaler = GradScaler
