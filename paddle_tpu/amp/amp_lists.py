"""AMP op lists.

Reference parity: `python/paddle/amp/amp_lists.py` (white/black lists) and
the per-op autocast decision compiled into every generated ad_func
(`paddle/fluid/eager/amp_utils.h:108`, `eager_amp_auto_cast.h`).

TPU-first: the low-precision dtype of choice is bfloat16 (MXU-native, same
exponent range as fp32 so no loss scaling needed); fp16 is supported for
parity. White ops ride the MXU; black ops are numerically sensitive
reductions kept in fp32.
"""

# ops that benefit from low precision (matmul-class: MXU)
WHITE_LIST = {
    "matmul", "linear", "conv1d", "conv2d", "conv3d", "conv1d_transpose",
    "conv2d_transpose", "conv3d_transpose", "bmm", "mm", "mv", "einsum",
    "addmm", "flash_attention", "scaled_dot_product_attention",
}

# numerically dangerous in low precision — always fp32
BLACK_LIST = {
    "exp", "square", "log", "log2", "log10", "log1p", "mean", "sum", "prod",
    "cumsum", "softmax", "log_softmax", "softmax_with_cross_entropy",
    "cross_entropy", "binary_cross_entropy", "binary_cross_entropy_with_logits",
    "nll_loss", "kl_div", "smooth_l1_loss", "mse_loss", "l1_loss",
    "layer_norm", "batch_norm", "instance_norm", "group_norm", "rms_norm",
    "reduce_mean", "reduce_sum", "norm", "cos_sim", "pow", "rsqrt",
    "softplus", "logsumexp", "erfinv", "cholesky", "svd", "eig", "eigh",
    "inverse", "det", "sigmoid_cross_entropy_with_logits", "ctc_loss",
    "margin_cross_entropy", "dist", "renorm",
}

# everything else runs in whichever dtype its inputs already have ("gray")


def white_list():
    return set(WHITE_LIST)


def black_list():
    return set(BLACK_LIST)
