"""`paddle.amp` parity (SURVEY.md §2.2 AMP row)."""
from .auto_cast import auto_cast, amp_guard, decorate, amp_state  # noqa: F401
from .grad_scaler import GradScaler, AmpScaler  # noqa: F401
from . import amp_lists  # noqa: F401

WHITE_LIST = amp_lists.WHITE_LIST
BLACK_LIST = amp_lists.BLACK_LIST

__all__ = ["auto_cast", "amp_guard", "decorate", "GradScaler", "AmpScaler",
           "amp_lists"]


def is_bfloat16_supported(device=None):
    return True


def is_float16_supported(device=None):
    return True
from . import debugging  # noqa: F401,E402
__all__ += ["debugging"]
