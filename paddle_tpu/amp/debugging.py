"""AMP numerical debugging (parity: `python/paddle/amp/debugging.py` —
TensorChecker / check_numerics / collect_operator_stats).

The op-level NaN/Inf watchdog itself lives in framework.flags
(FLAGS_check_nan_inf, the reference's `nan_inf_utils`); this module adds the
user-facing debug API surface.
"""
from __future__ import annotations

import contextlib

import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor
from ..ops import registry

__all__ = ["enable_operator_stats_collection",
           "disable_operator_stats_collection", "collect_operator_stats",
           "enable_tensor_checker", "disable_tensor_checker",
           "check_numerics", "DebugMode", "TensorCheckerConfig"]


class DebugMode:
    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL = 2


class TensorCheckerConfig:
    def __init__(self, enable=True, debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT,
                 output_dir=None, checked_op_list=None,
                 skipped_op_list=None, debug_step=None, stack_height_limit=1):
        self.enable = enable
        self.debug_mode = debug_mode
        self.output_dir = output_dir
        self.checked_op_list = checked_op_list
        self.skipped_op_list = skipped_op_list


_baseline = None


def enable_operator_stats_collection():
    global _baseline
    _baseline = dict(registry.op_stats())


def disable_operator_stats_collection():
    global _baseline
    base = _baseline or {}
    cur = registry.op_stats()
    delta = {k: v - base.get(k, 0) for k, v in cur.items()
             if v - base.get(k, 0) > 0}
    _baseline = None
    print("<------------------------------ op list ------------------------------->")
    for name, n in sorted(delta.items(), key=lambda kv: -kv[1]):
        print(f"  {name:<40} calls: {n}")
    print("<----------------------------------- done ----------------------------->")
    return delta


@contextlib.contextmanager
def collect_operator_stats():
    enable_operator_stats_collection()
    try:
        yield
    finally:
        disable_operator_stats_collection()


def check_numerics(tensor, op_type="", var_name="", debug_mode=None):
    """Parity: `paddle.amp.debugging.check_numerics` — returns
    (num_nan, num_inf, num_zero) and raises on NaN/Inf in abort mode."""
    arr = tensor._data if isinstance(tensor, Tensor) else jnp.asarray(tensor)
    n_nan = int(jnp.isnan(arr).sum())
    n_inf = int(jnp.isinf(arr).sum())
    n_zero = int((arr == 0).sum())
    if debug_mode in (None, DebugMode.CHECK_NAN_INF_AND_ABORT) and \
            (n_nan or n_inf):
        raise FloatingPointError(
            f"[check_numerics] op={op_type} var={var_name}: "
            f"{n_nan} NaN, {n_inf} Inf")
    return (Tensor(jnp.asarray(n_nan)), Tensor(jnp.asarray(n_inf)),
            Tensor(jnp.asarray(n_zero)))


def enable_tensor_checker(checker_config: TensorCheckerConfig):
    from ..framework import flags

    flags.set_flags({"FLAGS_check_nan_inf": True})


def disable_tensor_checker():
    from ..framework import flags

    flags.set_flags({"FLAGS_check_nan_inf": False})


def compare_accuracy(dump_path, another_dump_path, output_filename,
                     loss_scale=1, dump_all_tensors=False):
    """Parity: paddle.amp.debugging.compare_accuracy — diff two
    operator-stats dumps (the workflow: run fp32 and amp with
    collect_operator_stats, dump, compare). Reads the two dumps (JSON
    lines of per-op stats), joins on op name with per-op aggregation, and
    writes an Excel-free CSV report of mismatches.

    ``loss_scale`` and ``dump_all_tensors`` are accepted for signature
    parity and ignored: this build's dumps carry op statistics only (the
    reference's full-tensor GPU dumps have no counterpart here), and no
    scale adjustment applies to count-based stats."""
    import csv
    import json
    import os

    def load(path):
        out: dict = {}
        if os.path.isdir(path):
            files = [os.path.join(path, f) for f in sorted(os.listdir(path))
                     if os.path.isfile(os.path.join(path, f))]
        else:
            files = [path]
        for fp in files:
            with open(fp) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    rec = json.loads(line)
                    op = rec.get("op", rec.get("name", "?"))
                    # AGGREGATE all records per op (dumps hold one line
                    # per call/step): numeric fields sum, so no step's
                    # NaN count is silently dropped
                    agg = out.setdefault(op, {"calls": 0})
                    agg["calls"] += 1
                    for k, v in rec.items():
                        if k in ("op", "name"):
                            continue
                        if isinstance(v, (int, float)):
                            agg[k] = agg.get(k, 0) + v
                        else:
                            agg[k] = v
        return out

    a = load(dump_path)
    b = load(another_dump_path)
    with open(output_filename, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["op", "metric", "run_a", "run_b"])
        for op in sorted(set(a) | set(b)):
            ra, rb = a.get(op, {}), b.get(op, {})
            keys = (set(ra) | set(rb)) - {"op", "name"}
            for k in sorted(keys):
                va, vb = ra.get(k), rb.get(k)
                if va != vb:
                    w.writerow([op, k, va, vb])
    return output_filename


__all__ += ["compare_accuracy"]
