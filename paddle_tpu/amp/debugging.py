"""AMP numerical debugging (parity: `python/paddle/amp/debugging.py` —
TensorChecker / check_numerics / collect_operator_stats).

The op-level NaN/Inf watchdog itself lives in framework.flags
(FLAGS_check_nan_inf, the reference's `nan_inf_utils`); this module adds the
user-facing debug API surface.
"""
from __future__ import annotations

import contextlib

import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor
from ..ops import registry

__all__ = ["enable_operator_stats_collection",
           "disable_operator_stats_collection", "collect_operator_stats",
           "enable_tensor_checker", "disable_tensor_checker",
           "check_numerics", "DebugMode", "TensorCheckerConfig"]


class DebugMode:
    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL = 2


class TensorCheckerConfig:
    def __init__(self, enable=True, debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT,
                 output_dir=None, checked_op_list=None,
                 skipped_op_list=None, debug_step=None, stack_height_limit=1):
        self.enable = enable
        self.debug_mode = debug_mode
        self.output_dir = output_dir
        self.checked_op_list = checked_op_list
        self.skipped_op_list = skipped_op_list


_baseline = None


def enable_operator_stats_collection():
    global _baseline
    _baseline = dict(registry.op_stats())


def disable_operator_stats_collection():
    global _baseline
    base = _baseline or {}
    cur = registry.op_stats()
    delta = {k: v - base.get(k, 0) for k, v in cur.items()
             if v - base.get(k, 0) > 0}
    _baseline = None
    print("<------------------------------ op list ------------------------------->")
    for name, n in sorted(delta.items(), key=lambda kv: -kv[1]):
        print(f"  {name:<40} calls: {n}")
    print("<----------------------------------- done ----------------------------->")
    return delta


@contextlib.contextmanager
def collect_operator_stats():
    enable_operator_stats_collection()
    try:
        yield
    finally:
        disable_operator_stats_collection()


def check_numerics(tensor, op_type="", var_name="", debug_mode=None):
    """Parity: `paddle.amp.debugging.check_numerics` — returns
    (num_nan, num_inf, num_zero) and raises on NaN/Inf in abort mode."""
    arr = tensor._data if isinstance(tensor, Tensor) else jnp.asarray(tensor)
    n_nan = int(jnp.isnan(arr).sum())
    n_inf = int(jnp.isinf(arr).sum())
    n_zero = int((arr == 0).sum())
    if debug_mode in (None, DebugMode.CHECK_NAN_INF_AND_ABORT) and \
            (n_nan or n_inf):
        raise FloatingPointError(
            f"[check_numerics] op={op_type} var={var_name}: "
            f"{n_nan} NaN, {n_inf} Inf")
    return (Tensor(jnp.asarray(n_nan)), Tensor(jnp.asarray(n_inf)),
            Tensor(jnp.asarray(n_zero)))


def enable_tensor_checker(checker_config: TensorCheckerConfig):
    from ..framework import flags

    flags.set_flags({"FLAGS_check_nan_inf": True})


def disable_tensor_checker():
    from ..framework import flags

    flags.set_flags({"FLAGS_check_nan_inf": False})
