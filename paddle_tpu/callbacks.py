"""`paddle.callbacks` parity (reference `python/paddle/callbacks.py`):
the hapi training callbacks re-exported at the top level."""
from .hapi.callbacks import (  # noqa: F401
    Callback, EarlyStopping, LRSchedulerCallback, ModelCheckpoint,
    ProgBarLogger, ReduceLROnPlateau, VisualDL,
)

# the reference exports the LR callback as `LRScheduler`
LRScheduler = LRSchedulerCallback

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "LRScheduler",
           "EarlyStopping", "ReduceLROnPlateau", "VisualDL"]
