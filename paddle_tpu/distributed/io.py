"""paddle.distributed.io parity (reference
`python/paddle/distributed/io.py`): persistable save/load helpers for
distributed programs. Sharded arrays are gathered/resharded by the
checkpoint layer (`distributed/checkpoint.py`), so these are thin
front-doors over the framework io with the reference's signatures."""
from __future__ import annotations

import os

__all__ = ["save_persistables", "load_persistables",
           "load_inference_model_distributed", "is_persistable"]


def is_persistable(var):
    return bool(getattr(var, "persistable", False)
                or getattr(var, "is_parameter", False))


def save_persistables(executor, dirname, main_program=None, filename=None):
    """Save every persistable parameter the program references."""
    from ..framework.io import save

    if main_program is None:
        from ..static import default_main_program

        main_program = default_main_program()
    params = {p.name or f"param_{i}": p
              for i, p in enumerate(main_program.all_parameters())}
    os.makedirs(dirname, exist_ok=True)
    save({k: v for k, v in params.items()},
         os.path.join(dirname, filename or "__model__.pdparams"))


def load_persistables(executor, dirname, main_program=None, filename=None):
    from ..framework.io import load

    if main_program is None:
        from ..static import default_main_program

        main_program = default_main_program()
    state = load(os.path.join(dirname, filename or "__model__.pdparams"))
    by_name = {p.name or f"param_{i}": p
               for i, p in enumerate(main_program.all_parameters())}
    for k, v in state.items():
        if k in by_name:
            by_name[k].set_value(v)
    return state


def load_inference_model_distributed(dirname, executor, **kwargs):
    from ..static import load_inference_model

    return load_inference_model(dirname, executor, **kwargs)
