"""Slot data generators (reference
`fleet/data_generator/data_generator.py`): user subclasses implement
`generate_sample(line)`; the generator formats samples into the slot text
protocol. The format itself is runtime-agnostic (plain text lines), so it
works here even though the PS training tier is excluded — use it to
produce files any slot-format consumer reads.
"""
from __future__ import annotations

import sys

__all__ = ["DataGenerator", "MultiSlotDataGenerator",
           "MultiSlotStringDataGenerator"]


class DataGenerator:
    def __init__(self):
        self._proto_info = None
        self.batch_size_ = 32

    def set_batch(self, batch_size):
        self.batch_size_ = int(batch_size)

    def generate_sample(self, line):
        raise NotImplementedError(
            "subclasses implement generate_sample(line) returning a "
            "no-arg iterator over (slot_name, values) tuples")

    def generate_batch(self, samples):
        def local_iter():
            for s in samples:
                yield s

        return local_iter

    def _gen_str(self, line):
        raise NotImplementedError

    def run_from_stdin(self):
        for line in sys.stdin:
            line_iter = self.generate_sample(line)
            for parsed in line_iter():
                if parsed is None:
                    continue
                sys.stdout.write(self._gen_str(parsed))

    def run_from_memory(self):
        batch_samples = []
        line_iter = self.generate_sample(None)
        for parsed in line_iter():
            if parsed is None:
                continue
            batch_samples.append(parsed)
            if len(batch_samples) == self.batch_size_:
                batch_iter = self.generate_batch(batch_samples)
                for sample in batch_iter():
                    sys.stdout.write(self._gen_str(sample))
                batch_samples = []
        if batch_samples:
            batch_iter = self.generate_batch(batch_samples)
            for sample in batch_iter():
                sys.stdout.write(self._gen_str(sample))


class MultiSlotDataGenerator(DataGenerator):
    """Output line: `slot_count v v ... slot_count v v ...` per sample
    (ints/floats), the reference's MultiSlot proto text form."""

    def _gen_str(self, line):
        if not isinstance(line, (list, tuple)):
            raise ValueError(
                "generate_sample must yield a list/tuple of "
                "(slot_name, values) pairs")
        out = []
        for name, values in line:
            del name
            out.append(str(len(values)))
            out.extend(str(v) for v in values)
        return " ".join(out) + "\n"


class MultiSlotStringDataGenerator(MultiSlotDataGenerator):
    """String-slot variant: values pass through as raw strings (the text
    form is identical — numbers are stringified the same way)."""
