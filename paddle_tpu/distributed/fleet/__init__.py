"""fleet: the distributed-training facade.

Reference parity: `paddle.distributed.fleet` — `fleet.init`
(`fleet/fleet.py:169`), `fleet.distributed_model` (`fleet/model.py:30`),
`fleet.distributed_optimizer` (`fleet/fleet.py:1053`), plus the worker/server
role queries PS mode uses.

TPU-first design: `init` builds the global device mesh from the strategy's
hybrid degrees (instead of splitting NCCL comm rings per axis) and installs
the HybridCommunicateGroup view over it. `distributed_model` wraps by
strategy exactly like the reference's meta-parallel dispatch
(`fleet/model.py:126-149`): pure-DP -> DataParallel annotations, pp>1 ->
PipelineParallel schedule wrapper, otherwise the layer already carries its
TP shardings and passes through.
"""
from __future__ import annotations

from .base.distributed_strategy import DistributedStrategy
from .base.topology import (
    CommunicateTopology, HybridCommunicateGroup, ensure_hcg, get_hcg, set_hcg,
)
from .. import env as env_mod

__all__ = [
    "init", "DistributedStrategy", "HybridCommunicateGroup",
    "CommunicateTopology", "distributed_model", "distributed_optimizer",
    "get_hybrid_communicate_group", "worker_index", "worker_num",
    "is_first_worker", "barrier_worker",
]

_fleet_strategy: DistributedStrategy | None = None


def init(role_maker=None, is_collective=False, strategy=None, log_level="INFO"):
    """Parity: `fleet.init` (`fleet/fleet.py:169`)."""
    global _fleet_strategy
    strategy = strategy or DistributedStrategy()
    _fleet_strategy = strategy
    hc = strategy.hybrid_configs
    env_mod.init_mesh(
        dp=hc.get("dp_degree", 1) or 1,
        mp=hc.get("mp_degree", 1) or 1,
        pp=hc.get("pp_degree", 1) or 1,
        sharding=hc.get("sharding_degree", 1) or 1,
        sep=hc.get("sep_degree", 1) or 1,
    )
    set_hcg(HybridCommunicateGroup())
    return None


def get_strategy() -> DistributedStrategy | None:
    return _fleet_strategy


def get_hybrid_communicate_group() -> HybridCommunicateGroup:
    return ensure_hcg()


def distributed_model(model):
    """Parity: `fleet.distributed_model` (`fleet/model.py:30`)."""
    from ..parallel import DataParallel
    from .meta_parallel.pipeline_parallel import PipelineParallel
    from .meta_parallel.parallel_layers.pp_layers import PipelineLayer

    hcg = ensure_hcg()
    if hcg.get_pipe_parallel_world_size() > 1 and isinstance(model, PipelineLayer):
        return PipelineParallel(model, hcg, _fleet_strategy)
    if (hcg.get_data_parallel_world_size() > 1
            and hcg.get_model_parallel_world_size() == 1
            and hcg.get_pipe_parallel_world_size() == 1):
        return DataParallel(model)
    # TP / hybrid: shardings already live on the parameters (GSPMD)
    return model


def distributed_optimizer(optimizer, strategy=None):
    """Parity: `fleet.distributed_optimizer` (`fleet/fleet.py:1053`). Under
    GSPMD the optimizer update inherits parameter shardings, so no wrapping
    is needed; returned as-is (HybridParallelOptimizer's grad-clip-across-
    groups behavior is automatic because grads are global arrays)."""
    return optimizer


# -- worker/server role queries (PS-mode parity; collective mode: trivial) --

def worker_index():
    e = env_mod.get_env()
    return e.rank if e else 0


def worker_num():
    import jax

    return jax.process_count()


def is_first_worker():
    return worker_index() == 0


def barrier_worker():
    from ..collective import barrier

    barrier()


# -- reference-shaped class surface (`fleet.Fleet`, role makers, util) --

from . import utils  # noqa: F401,E402
from .base.role_maker import (  # noqa: F401,E402
    PaddleCloudRoleMaker, Role, UserDefinedRoleMaker,
)
from .data_generator import (  # noqa: F401,E402
    MultiSlotDataGenerator, MultiSlotStringDataGenerator,
)


class UtilBase:
    """Parity: `fleet.UtilBase` (`fleet/base/util_factory.py`) — host-side
    helpers over the collective layer."""

    def all_reduce(self, input, mode="sum", comm_world="worker"):  # noqa: A002
        import numpy as np

        from ..collective import ReduceOp, all_reduce
        from ...framework.core import Tensor

        op = {"sum": ReduceOp.SUM, "max": ReduceOp.MAX,
              "min": ReduceOp.MIN}[mode]
        out = all_reduce(Tensor(np.asarray(input)), op=op)
        return np.asarray(out.numpy())

    def barrier(self, comm_world="worker"):
        from ..collective import barrier

        barrier()

    def all_gather(self, input, comm_world="worker"):  # noqa: A002
        import numpy as np

        from ..collective import all_gather
        from ...framework.core import Tensor

        outs: list = []
        all_gather(outs, Tensor(np.asarray(input)))
        return [np.asarray(o.numpy()) for o in outs]

    def get_file_shard(self, files):
        """Split a file list evenly over workers (reference semantics:
        earlier workers take the remainder)."""
        n = worker_num()
        i = worker_index()
        base, rem = divmod(len(files), n)
        start = i * base + min(i, rem)
        return files[start:start + base + (1 if i < rem else 0)]

    def print_on_rank(self, message, rank_id=0):
        if worker_index() == rank_id:
            print(message)


class Fleet:
    """Parity: the `fleet.Fleet` facade class — the module-level functions
    bound as methods (the reference instantiates one global `fleet`; this
    module IS that singleton, and `Fleet()` returns a view of it)."""

    init = staticmethod(init)
    distributed_model = staticmethod(distributed_model)
    distributed_optimizer = staticmethod(distributed_optimizer)
    worker_index = staticmethod(worker_index)
    worker_num = staticmethod(worker_num)
    is_first_worker = staticmethod(is_first_worker)
    barrier_worker = staticmethod(barrier_worker)
    get_hybrid_communicate_group = staticmethod(
        get_hybrid_communicate_group)

    @property
    def util(self):
        return UtilBase()

    def is_worker(self):
        return True

    def is_server(self):
        return False


__all__ += ["utils", "Fleet", "Role", "PaddleCloudRoleMaker", "UserDefinedRoleMaker",
            "UtilBase", "MultiSlotDataGenerator",
            "MultiSlotStringDataGenerator"]
