"""Hybrid-parallel topology.

Reference parity: `CommunicateTopology` and `HybridCommunicateGroup`
(`python/paddle/distributed/fleet/base/topology.py:58,144-240`) — the 4-D
cartesian rank grid and the per-axis communicator groups every meta-parallel
layer consults.

TPU-first design: the topology IS the mesh (env.AXIS_ORDER). Groups are mesh
axes, so "get_model_parallel_group" returns the 'mp' axis group; there is no
rank-list arithmetic because XLA addresses devices by mesh coordinates.
"""
from __future__ import annotations

from ... import env as env_mod
from ...collective import Group


class CommunicateTopology:
    """Parity: `topology.py:58`. Maps hybrid axis names to mesh axes."""

    # reference axis vocabulary -> mesh axis
    _ALIAS = {"data": "dp", "pipe": "pp", "sharding": "sharding",
              "sep": "sep", "model": "mp"}

    def __init__(self, hybrid_group_names=("data", "pipe", "sharding", "sep", "model"),
                 dims=None):
        self._names = list(hybrid_group_names)
        e = env_mod.ensure_env()
        self._dims = list(dims) if dims is not None else [
            e.degree(self._ALIAS[n]) for n in self._names
        ]

    def get_hybrid_group_names(self):
        return self._names

    def get_dim(self, axis_name):
        return self._dims[self._names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        n = 1
        for d in self._dims:
            n *= d
        return n


class HybridCommunicateGroup:
    """Parity: `topology.py:144`. The object `fleet.init` hangs the per-axis
    groups on; meta-parallel layers query world sizes/ranks/groups here."""

    def __init__(self, topology: CommunicateTopology | None = None):
        self._topo = topology or CommunicateTopology()
        e = env_mod.ensure_env()
        self._env = e
        self._dp_group = Group(("dp",), "dp_group")
        self._mp_group = Group(("mp",), "mp_group")
        self._pp_group = Group(("pp",), "pp_group")
        self._sharding_group = Group(("sharding",), "sharding_group")
        self._sep_group = Group(("sep",), "sep_group")
        # dp+sharding fused group (reference: check_group for pure-dp params)
        self._dp_sharding_group = Group(("dp", "sharding"), "dp_sharding")

    def get_hybrid_communicate_group(self):
        return self

    @property
    def topology(self):
        return self._topo

    def topology_obj(self):
        return self._topo

    # -- global --
    def get_global_rank(self):
        return self._env.rank

    def get_world_size(self):
        return self._env.world_size

    # -- data parallel --
    def get_data_parallel_world_size(self):
        return self._env.degree("dp")

    def get_data_parallel_rank(self):
        return 0

    def get_data_parallel_group(self):
        return self._dp_group

    def get_data_parallel_group_src_rank(self):
        return 0

    # -- model (tensor) parallel --
    def get_model_parallel_world_size(self):
        return self._env.degree("mp")

    def get_model_parallel_rank(self):
        return 0

    def get_model_parallel_group(self):
        return self._mp_group

    def get_model_parallel_group_src_rank(self):
        return 0

    # -- pipeline parallel --
    def get_pipe_parallel_world_size(self):
        return self._env.degree("pp")

    def get_stage_id(self):
        return 0

    def get_pipe_parallel_group(self):
        return self._pp_group

    def is_first_stage(self):
        return True

    def is_last_stage(self):
        return True

    # -- sharding --
    def get_sharding_parallel_world_size(self):
        return self._env.degree("sharding")

    def get_sharding_parallel_rank(self):
        return 0

    def get_sharding_parallel_group(self):
        return self._sharding_group

    # -- sep --
    def get_sep_parallel_world_size(self):
        return self._env.degree("sep")

    def get_sep_parallel_rank(self):
        return 0

    def get_sep_parallel_group(self):
        return self._sep_group

    def get_check_parallel_group(self, sharding=False):
        return self._dp_sharding_group


_hcg: HybridCommunicateGroup | None = None


def set_hcg(hcg: HybridCommunicateGroup):
    global _hcg
    _hcg = hcg


def get_hcg() -> HybridCommunicateGroup | None:
    return _hcg


def ensure_hcg() -> HybridCommunicateGroup:
    global _hcg
    if _hcg is None:
        _hcg = HybridCommunicateGroup()
    return _hcg
