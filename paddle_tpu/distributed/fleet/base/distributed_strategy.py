"""DistributedStrategy: the configuration object for every distributed feature.

Reference parity: `paddle.distributed.fleet.DistributedStrategy` backed by a
228-field protobuf (`paddle/fluid/framework/distributed_strategy.proto:333`).

TPU-first design: plain attributes (no protobuf — nothing crosses a process
boundary in single-controller SPMD). The surface keeps the reference's knob
names so fleet-configured training scripts port unchanged; knobs that have no
TPU meaning (nccl_comm_num, fuse_grad_size_in_MB...) are accepted and ignored
— XLA owns fusion and overlap.
"""
from __future__ import annotations


class DistributedStrategy:
    def __init__(self):
        # hybrid parallel degrees (the load-bearing config)
        self.hybrid_configs = {
            "dp_degree": 1,
            "mp_degree": 1,
            "pp_degree": 1,
            "sharding_degree": 1,
            "sep_degree": 1,
            "mp_configs": {},
            "pp_configs": {},
        }
        # feature switches (reference proto field names)
        self.amp = False
        self.amp_configs = {}
        self.recompute = False
        self.recompute_configs = {}
        self.sharding = False
        self.sharding_configs = {}
        self.pipeline = False
        self.pipeline_configs = {"accumulate_steps": 1, "micro_batch_size": 1}
        self.tensor_parallel = False
        self.tensor_parallel_configs = {}
        self.gradient_merge = False
        self.gradient_merge_configs = {}
        self.lamb = False
        self.lamb_configs = {}
        self.dgc = False
        self.heter_ccl_mode = False
        self.find_unused_parameters = False
        self.fuse_grad_size_in_MB = 32
        self.nccl_comm_num = 1
        self.gradient_scale_configs = {"scale_strategy": "avg"}
        self.without_graph_optimization = True
        self.asp = False
        self.qat = False
        self.qat_configs = {}
        self.fuse_all_reduce_ops = True
        self.last_comm_group_size_MB = 1

    def __repr__(self):
        degrees = {k: v for k, v in self.hybrid_configs.items()
                   if k.endswith("_degree")}
        return f"DistributedStrategy(hybrid={degrees})"
