"""Role makers (reference `fleet/base/role_maker.py`): who am I in the
job — worker index, world size, endpoints.

TPU-first: roles come from the launcher environment
(`distributed/launch`), the same variables the reference's
PaddleCloudRoleMaker reads; there is no PS "server" role (see README
exclusions), so every process is a collective worker.
"""
from __future__ import annotations

import os

__all__ = ["Role", "RoleMakerBase", "PaddleCloudRoleMaker",
           "UserDefinedRoleMaker"]


class Role:
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4
    COORDINATOR = 5


class RoleMakerBase:
    def __init__(self):
        self._role = Role.WORKER

    def is_worker(self):
        return self._role == Role.WORKER

    def is_server(self):
        return False  # no PS tier in this build

    def is_first_worker(self):
        return self.worker_index() == 0

    def worker_index(self):
        raise NotImplementedError

    def worker_num(self):
        raise NotImplementedError

    def role_id(self):
        return self.worker_index()


class PaddleCloudRoleMaker(RoleMakerBase):
    """Environment-driven role maker (the launcher exports the same
    variables the reference's cloud runtime does)."""

    def __init__(self, is_collective=True, **kwargs):
        super().__init__()
        self._is_collective = is_collective

    def worker_index(self):
        return int(os.environ.get("PADDLE_TRAINER_ID", 0))

    def worker_num(self):
        return int(os.environ.get("PADDLE_TRAINERS_NUM", 1))

    def worker_endpoints(self, to_string=False):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        lst = [e for e in eps.split(",") if e]
        return ",".join(lst) if to_string else lst


class UserDefinedRoleMaker(RoleMakerBase):
    def __init__(self, is_collective=True, current_id=0, role=Role.WORKER,
                 worker_num=1, worker_endpoints=None, **kwargs):
        super().__init__()
        self._role = role
        self._current_id = int(current_id)
        self._worker_num = int(worker_num)
        self._endpoints = list(worker_endpoints or [])

    def worker_index(self):
        return self._current_id

    def worker_num(self):
        return self._worker_num

    def worker_endpoints(self, to_string=False):
        return (",".join(self._endpoints) if to_string
                else list(self._endpoints))
