"""Elastic training: membership, failure detection, scale up/down.

Reference parity: `ElasticManager` (`fleet/elastic/manager.py:126`) — etcd3
node registration, heartbeat + watch on the member set, scale decision,
kill-and-relaunch of local trainers with rewritten env.

TPU-first design: membership rides our own C++ TCPStore
(`distributed/store.py`) instead of etcd — heartbeat keys with host ids,
the master watches the key-set; on membership change the decision is
relaunch-and-re-pjit: checkpoints are reshard-on-load
(`distributed/checkpoint.py`), so a job restarted on a different mesh shape
resumes exactly (SURVEY §5.3 "elastic = re-pjit on new mesh after relaunch").
Slice health itself comes from the TPU runtime via jax device health.
"""
from __future__ import annotations

import os
import threading
import time

__all__ = ["ElasticManager", "ElasticStatus"]


class ElasticStatus:
    COMPLETED = 1
    ERROR = 2
    HOLD = 3
    RESTART = 4
    EXIT = 5


class ElasticManager:
    """Heartbeat-based membership over TCPStore.

    master: `ElasticManager(job_id, rank=0, is_master=True)` — starts the
    store server and the watcher. workers: connect with the master address.
    `watch()` returns an ElasticStatus when membership changes or the
    job completes.
    """

    def __init__(self, job_id="default", rank=0, hosts=None, is_master=None,
                 host=None, port=0, np=1, heartbeat_interval=2.0,
                 timeout=10.0):
        from ...store import TCPStore

        self.job_id = job_id
        self.rank = rank
        self.np = int(np)
        self.heartbeat_interval = heartbeat_interval
        self.timeout = timeout
        is_master = (rank == 0) if is_master is None else is_master
        addr = host or os.environ.get("PADDLE_ELASTIC_SERVER",
                                      "127.0.0.1")
        self.store = TCPStore(host=addr, port=port, is_master=is_master,
                              timeout=timeout)
        self.port = self.store.port
        self._stop = threading.Event()
        self._node_key = f"{job_id}/nodes/{rank}"
        self._members_at_start = None
        self._hb = threading.Thread(target=self._heartbeat_loop, daemon=True)
        self._hb.start()

    # -- membership --
    def _heartbeat_loop(self):
        while not self._stop.is_set():
            self.store.set(self._node_key, str(time.time()))
            self._stop.wait(self.heartbeat_interval)

    def alive_nodes(self):
        now = time.time()
        alive = []
        for r in range(self.np):
            try:
                ts = float(self.store.get(f"{self.job_id}/nodes/{r}"))
            except (KeyError, ValueError):
                continue
            if now - ts <= self.timeout:
                alive.append(r)
        return alive

    def wait_for_np(self, np=None, timeout=60.0):  # noqa: A002
        want = np or self.np
        deadline = time.time() + timeout
        while time.time() < deadline:
            if len(self.alive_nodes()) >= want:
                return True
            time.sleep(self.heartbeat_interval / 2)
        return False

    def watch(self):
        """Blocks until membership changes (RESTART) or completion (EXIT)."""
        if self._members_at_start is None:
            self._members_at_start = set(self.alive_nodes())
        while not self._stop.is_set():
            try:
                self.store.get(f"{self.job_id}/completed")
                return ElasticStatus.COMPLETED
            except KeyError:
                pass
            cur = set(self.alive_nodes())
            if cur != self._members_at_start:
                self._members_at_start = cur
                return ElasticStatus.RESTART
            time.sleep(self.heartbeat_interval)
        return ElasticStatus.EXIT

    def mark_completed(self):
        self.store.set(f"{self.job_id}/completed", "1")

    def exit(self, completed=False):
        if completed:
            self.mark_completed()
        self._stop.set()
        self._hb.join(timeout=5)
