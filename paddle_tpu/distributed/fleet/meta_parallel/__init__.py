"""Meta-parallel layers and schedules (parity:
`python/paddle/distributed/fleet/meta_parallel/`)."""
from .parallel_layers.mp_layers import (  # noqa: F401
    ColumnParallelLinear, ParallelCrossEntropy, RowParallelLinear,
    masked_token_mean,
    VocabParallelEmbedding,
)
from .parallel_layers.pp_layers import (  # noqa: F401
    LayerDesc, PipelineLayer, SharedLayerDesc,
)
from .parallel_layers.random import (  # noqa: F401
    RNGStatesTracker, get_rng_state_tracker, model_parallel_random_seed,
)
from .pipeline_parallel import PipelineParallel  # noqa: F401

__all__ = [
    "ColumnParallelLinear", "RowParallelLinear", "VocabParallelEmbedding",
    "ParallelCrossEntropy", "masked_token_mean", "LayerDesc", "SharedLayerDesc", "PipelineLayer",
    "PipelineParallel", "RNGStatesTracker", "get_rng_state_tracker",
    "model_parallel_random_seed",
]
