"""Pipeline-parallel training driver.

Reference parity: `PipelineParallel` / `PipelineParallelWithInterleave`
(`fleet/meta_parallel/pipeline_parallel.py:130,383,815`) — the host-side
F-then-B / 1F1B micro-batch scheduler with p2p activation exchange.

TPU-first design: the schedule is compiled INTO the XLA program by
`PipelineLayer._pipeline_blocks` (shard_map + ppermute GPipe loop), so this
class only keeps the reference's `train_batch`/`eval_batch` driver API:
forward the full batch (micro-batching happens inside the op), compute loss,
one backward, one optimizer step. 1F1B's memory benefit is delivered by
`recompute_interval` (jax.checkpoint) instead of host-side scheduling;
interleaved virtual stages are a schedule variant of the same shard_map loop
(future work tracked in SURVEY §7 hard-part (b)).
"""
from __future__ import annotations

from .parallel_layers.pp_layers import PipelineLayer


class PipelineParallel:
    def __init__(self, layers, hcg, strategy=None):
        if not isinstance(layers, PipelineLayer):
            raise TypeError("PipelineParallel expects a PipelineLayer")
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        cfg = (strategy.pipeline_configs if strategy is not None else {}) or {}
        self.accumulate_steps = int(cfg.get("accumulate_steps", 1) or 1)
        self.micro_batch_size = int(cfg.get("micro_batch_size", 1) or 1)
        self.total_loss = None

    def __getattr__(self, name):
        return getattr(self._layers, name)

    def __call__(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def _n_micro(self):
        return max(self.accumulate_steps,
                   self._hcg.get_pipe_parallel_world_size())

    def forward_backward_pipeline(self, data, scaler=None):
        """Parity: `pipeline_parallel.py:383`. Runs fwd+bwd for one global
        batch; returns the (averaged) loss tensor."""
        inputs, labels = data
        out = self._layers(inputs, n_microbatches=self._n_micro())
        if self._layers.loss_fn is None:
            raise ValueError("PipelineLayer needs loss_fn for train_batch")
        loss = self._layers.loss_fn(out, labels)
        if loss.ndim:
            loss = loss.mean()
        scaled = scaler.scale(loss) if scaler is not None else loss
        scaled.backward()
        self.total_loss = loss
        return loss

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """Parity: `PipelineParallel.train_batch`."""
        loss = self.forward_backward_pipeline(data, scaler)
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    def eval_batch(self, data, compute_loss=True):
        from ....autograd.tape import no_grad

        inputs, labels = data
        with no_grad():
            out = self._layers(inputs, n_microbatches=self._n_micro())
            if compute_loss and self._layers.loss_fn is not None:
                loss = self._layers.loss_fn(out, labels)
                return loss.mean() if loss.ndim else loss
        return out
