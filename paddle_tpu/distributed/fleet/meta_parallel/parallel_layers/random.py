"""Per-parallel-axis RNG state tracking for deterministic dropout.

Reference parity: `RNGStatesTracker` (`fleet/layers/mpu/random.py`) — under
TP, dropout inside the parallel region must use a *different* seed per mp
rank (masks on different weight shards must differ) while dropout outside
must be *identical* across mp ranks.

TPU-first design: JAX PRNG keys are functional, so a "state per name" is a
dict of keys; `rng_state(name)` routes `framework.random.next_key()` through
the named key via `rng_scope`. Under GSPMD the mask tensor is one global
array, so mp ranks are automatically consistent — the tracker exists for API
parity and for explicitly-partitioned (shard_map) regions where per-shard
determinism is needed; there we fold the axis index into the key.
"""
from __future__ import annotations

import contextlib

import jax

from .....framework import random as rng

MODEL_PARALLEL_RNG = "model_parallel_rng"


class RNGStatesTracker:
    def __init__(self):
        self.states_ = {}
        self.seeds_ = set()

    def reset(self):
        self.states_ = {}
        self.seeds_ = set()

    def add(self, name, seed):
        if seed in self.seeds_:
            raise ValueError(f"seed {seed} already exists")
        if name in self.states_:
            raise ValueError(f"state {name} already exists")
        self.seeds_.add(seed)
        self.states_[name] = jax.random.key(seed)

    def get_states_tracker(self):
        return dict(self.states_)

    def set_states_tracker(self, states):
        self.states_ = dict(states)

    @contextlib.contextmanager
    def rng_state(self, name=MODEL_PARALLEL_RNG):
        if name not in self.states_:
            raise ValueError(f"state {name} does not exist")
        with rng.rng_scope(self.states_[name]) as cell:
            try:
                yield
            finally:
                self.states_[name] = cell[0]


_TRACKER = RNGStatesTracker()


def get_rng_state_tracker() -> RNGStatesTracker:
    return _TRACKER


def model_parallel_random_seed(seed=None):
    """Parity: `fleet/layers/mpu/random.py` model_parallel_random_seed."""
    import random as pyrandom

    seed = seed if seed is not None else pyrandom.randint(0, 2**31 - 1)
    global_seed = seed
    local_seed = seed + 1024
    _TRACKER.reset()
    rng.seed(global_seed)
    _TRACKER.add(MODEL_PARALLEL_RNG, local_seed)


def dropout(x, p=0.5, axis=None, rng_name=MODEL_PARALLEL_RNG, training=True,
            mode="upscale_in_train", name=None):
    """Dropout drawing its mask key from the named tracker state (parity:
    `paddle.distributed.fleet.meta_parallel.parallel_layers.random.dropout`)."""
    from .....nn import functional as F

    if not training or p == 0.0:
        return x
    with _TRACKER.rng_state(rng_name):
        return F.dropout(x, p, axis=axis, training=training, mode=mode)
