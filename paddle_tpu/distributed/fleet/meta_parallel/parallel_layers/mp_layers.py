"""Megatron-style tensor-parallel layers.

Reference parity: `fleet/layers/mpu/mp_layers.py:35,173,343,524`
(`VocabParallelEmbedding`, `ColumnParallelLinear`, `RowParallelLinear`,
`ParallelCrossEntropy`) and the identity/allreduce autograd ops in
`mp_ops.py:26,90,218`.

TPU-first design: the reference manually splits each weight per rank and
inserts `_c_identity`/`_mp_allreduce` autograd ops around the matmuls. Here
each weight stays ONE global array physically sharded over the 'mp' mesh axis
(`NamedSharding`), and the forward drops sharding *constraints* on the
activations; XLA's SPMD partitioner derives the identity/allreduce pattern —
including the transposed collectives in the backward — from those layouts.
Column-parallel output is sharded on the feature dim; feeding it to a
row-parallel input (sharded on its contraction dim) produces exactly
Megatron's f/g conjugate pair with zero communication between the two
matmuls, on ICI, without a single explicit collective in the model code.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...base.topology import ensure_hcg
from .... import shard
from .....framework.core import Tensor
from .....nn import functional as F
from .....nn import initializer as I
from .....nn.layer.layers import Layer
from .....ops.dispatch import apply


def _mp_degree():
    return ensure_hcg().get_model_parallel_world_size()


class VocabParallelEmbedding(Layer):
    """Embedding with the vocab dim sharded over 'mp'
    (parity: `mp_layers.py:35`)."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.Normal(0.0, 1.0),
        )
        shard.shard_parameter(self.weight, "mp", None)

    def forward(self, x):
        out = F.embedding(x, self.weight)
        # token activations come out replicated over 'mp' (XLA: gather
        # over the sharded vocab dim → one all-reduce, Megatron's
        # masked-lookup+psum); the batch dim KEEPS its dp split — naming
        # only None dims would force XLA to gather the dp shards at
        # every boundary now that traced constraints are honored
        # (distributed/shard.py)
        return shard.sharding_constraint(
            out, "dp", *(None,) * (out.ndim - 1))


class ColumnParallelLinear(Layer):
    """Linear with W [in, out] sharded on out ('column'); parity:
    `mp_layers.py:173`. gather_output=False leaves the activation sharded on
    its last dim for a following RowParallelLinear."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=None, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.gather_output = gather_output
        self.is_mp = _mp_degree() > 1
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal(),
        )
        shard.shard_parameter(self.weight, None, "mp")
        has_bias = True if has_bias is None else has_bias
        self.bias = self.create_parameter(
            [out_features], is_bias=True) if has_bias else None
        if self.bias is not None:
            shard.shard_parameter(self.bias, "mp")

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        nd = out.ndim
        # batch dim keeps its dp split through both layouts (see
        # VocabParallelEmbedding.forward)
        if self.gather_output:
            return shard.sharding_constraint(out, "dp", *(None,) * (nd - 1))
        return shard.sharding_constraint(
            out, "dp", *(None,) * (nd - 2), "mp")


class RowParallelLinear(Layer):
    """Linear with W [in, out] sharded on in ('row'); parity:
    `mp_layers.py:343`. With input_is_parallel the incoming activation is
    already sharded on its last (contraction) dim and the matmul's partial
    sums reduce over 'mp' (XLA inserts the all-reduce)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.is_mp = _mp_degree() > 1
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal(),
        )
        shard.shard_parameter(self.weight, "mp", None)
        # bias is applied after the reduce → replicated (reference keeps it
        # unsharded on rank0 for the same reason)
        self.bias = self.create_parameter(
            [out_features], is_bias=True) if has_bias else None

    def forward(self, x):
        nd = x.ndim
        if self.input_is_parallel:
            x = shard.sharding_constraint(
                x, "dp", *(None,) * (nd - 2), "mp")
        out = F.linear(x, self.weight, None)
        out = shard.sharding_constraint(
            out, "dp", *(None,) * (out.ndim - 1))
        if self.bias is not None:
            out = out + self.bias
        return out


class ParallelCrossEntropy(Layer):
    """Softmax cross-entropy over an 'mp'-sharded vocab logit
    (parity: `mp_layers.py:524` / `c_softmax_with_cross_entropy` op).

    The logits stay sharded on the class dim end-to-end; the log-sum-exp
    reduction over classes is a sharded-dim reduction XLA lowers to an
    all-reduce over 'mp' — the reference op's exact algorithm
    (max-psum / sum-psum / masked gather) emerges from the layout.
    """

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        logits = shard.sharding_constraint(
            input, "dp", *(None,) * (input.ndim - 2), "mp")
        ignore = self.ignore_index

        def ce(lg, lb):
            lg32 = lg.astype(jnp.float32)
            lse = jnp.log(jnp.sum(jnp.exp(
                lg32 - jnp.max(lg32, -1, keepdims=True)), -1, keepdims=True)
            ) + jnp.max(lg32, -1, keepdims=True)
            lb2 = lb if lb.ndim == lg.ndim - 1 else lb.squeeze(-1)
            picked = jnp.take_along_axis(
                lg32, jnp.where(lb2 < 0, 0, lb2)[..., None], axis=-1)
            loss = (lse - picked)[..., 0]
            return jnp.where(lb2 == ignore, jnp.zeros((), loss.dtype), loss)[..., None]

        return apply("parallel_cross_entropy", ce, (logits, label))


def masked_token_mean(loss, labels, ignore_index=-100):
    """Mean of per-token loss over NON-ignored tokens — the reference
    cross-entropy 'mean' reduction divides by the count of valid labels,
    not the total token count (round-1 ADVICE: padded batches were
    under-weighted)."""

    def f(l, lb):
        valid = lb != ignore_index
        cnt = jnp.maximum(jnp.sum(valid), 1).astype(jnp.float32)
        return (jnp.sum(l.astype(jnp.float32)) / cnt).astype(l.dtype)

    return apply("masked_token_mean", f, (loss, labels))
