"""Pipeline-parallel layer container.

Reference parity: `LayerDesc`/`SharedLayerDesc`/`PipelineLayer`
(`fleet/meta_parallel/parallel_layers/pp_layers.py:56,239`) — a model
expressed as a flat list of layer descriptors, partitioned into stages.

TPU-first design (SURVEY §2.6 "PP ⇒ GPipe-style jax pipeline"): the reference
materializes only this rank's stage layers and exchanges activations over
brpc/NCCL p2p. Here ALL stages live in one SPMD program: the repeated block's
parameters are stacked along a leading axis sharded over the 'pp' mesh axis
(each pp group holds n_layers/pp blocks in HBM — same memory scaling as the
reference), and execution is a circular GPipe schedule inside `shard_map`
with `jax.lax.ppermute` moving activations stage-to-stage over ICI. The
whole schedule is ONE XLA program: no host-driven 1F1B loop, no p2p meta
negotiation (`p2p_communication.py:47` SendRecvMeta), no interceptor actor
mesh (`fleet_executor/`) — the compiler overlaps compute and permutes.

Non-repeated head/tail layers (embedding, final norm, lm head) run
replicated on every stage — redundant FLOPs on a small fraction of the model
in exchange for zero extra communication, the standard TPU trade. Their
*parameters*, however, are ZeRO-style sharded over the 'pp' axis (gathered
on use by XLA), so replicated compute does not cost replicated HBM.

Schedules (all compiled, tick loop is a `lax.scan` so compile time is
independent of the microbatch count):
- GPipe (default): microbatches stream through the stage ring once.
- Interleaved virtual stages (`num_virtual_pipeline_stages=v`, parity:
  `PipelineParallelWithInterleave`, `pipeline_parallel.py:815,960`): each
  device holds v non-contiguous block chunks (chunk c of device d = blocks
  [(c·pp+d)·bpc, ...)); microbatches lap the ring v times, cutting the
  fill/drain bubble from (pp-1)·W to (pp-1)·W/v.
- 1F1B memory mode (`pipeline_configs={'schedule': '1F1B'}` or
  `remat_ticks=True`): each tick is wrapped in `jax.checkpoint`, so the
  backward holds only stage-boundary states per tick instead of every
  intra-block activation — the memory profile 1F1B host scheduling buys in
  the reference (`pipeline_parallel.py:383`), delivered by rematerialization.
"""
from __future__ import annotations

import collections
import re
import sys

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from .... import env as env_mod
from .....autograd.tape import no_grad
from .....framework import random as rng
from .....framework.core import EagerParamBase, Tensor
from .....monitor import _register as _monitor_register
from .....nn.layer.layers import Layer
from .....ops.dispatch import apply

# Telemetry slot (paddle_tpu.monitor None-slot contract): None unless
# PT_MONITOR wired it. The compiled ppermute handoff is invisible to
# the eager collective counters (it lives inside the one XLA program),
# so the pipeline forward reports its schedule analytically here —
# ticks, microbatches, and the per-tick stage-state bytes that ride
# `collective/bytes/pp`.
_monitor = None


class LayerDesc:
    """Parity: `pp_layers.py:56`."""

    def __init__(self, layer_func, *inputs, **kwargs):
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_func, Layer):
            raise TypeError("LayerDesc expects a Layer subclass")

    def build_layer(self):
        return self.layer_func(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_func.__name__})"


class SharedLayerDesc(LayerDesc):
    """Parity: `pp_layers.py` SharedLayerDesc (tied embeddings). The first
    occurrence within ONE PipelineLayer builds the layer; later occurrences
    reuse it — trivially correct in SPMD because every stage sees every
    parameter. Sharing is scoped to the constructing PipelineLayer (the
    registry dict is passed in), so independent models never alias."""

    def __init__(self, key, layer_func, forward_func=None, shared_weight_attr="weight",
                 *inputs, **kwargs):
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr

    def build_layer(self, registry=None):
        if registry is None:
            return super().build_layer()
        if self.layer_name not in registry:
            registry[self.layer_name] = super().build_layer()
        return registry[self.layer_name]


def _pp_degree():
    e = env_mod.ensure_env()
    return e.degree("pp")


def _param_spec(p):
    s = getattr(p._data, "sharding", None)
    if isinstance(s, NamedSharding):
        spec = tuple(s.spec) + (None,) * (p.ndim - len(s.spec))
        return spec
    return (None,) * p.ndim


class PipelineLayer(Layer):
    """Parity: `pp_layers.py:239`.

    With pp degree 1 this is a Sequential. With pp degree N, the maximal
    contiguous run of same-class descriptors (the transformer blocks) is
    stage-partitioned; its parameters are stored STACKED: one Parameter per
    block-param-name with leading dim n_blocks, sharded PartitionSpec('pp',
    *block_spec). `forward` runs head layers, then the GPipe schedule over
    microbatches, then tail layers.
    """

    def __init__(self, layers, num_stages=None, topology=None, loss_fn=None,
                 seg_method="uniform", recompute_interval=0,
                 num_virtual_pipeline_stages=None, remat_ticks=None,
                 shard_head_tail_over_pp=True, **kwargs):
        super().__init__()
        self._loss_fn = loss_fn
        self._recompute = recompute_interval
        self._remat_ticks = remat_ticks
        self._num_stages = num_stages or _pp_degree()
        self._virtual = max(int(num_virtual_pipeline_stages or 1), 1)
        descs = list(layers)
        shared_registry: dict = {}
        built = [
            d.build_layer(shared_registry) if isinstance(d, SharedLayerDesc)
            else d.build_layer() if isinstance(d, LayerDesc)
            else d
            for d in descs
        ]

        pp = _pp_degree()
        if pp <= 1:
            # degenerate: plain sequential container. The repeated-run
            # bounds are still recorded: they define the CANONICAL
            # per-block checkpoint keys ("<flat index>.<param>") that a
            # pipelined relaunch of the same model assembles its stacks
            # from (stage-move reshard-on-load, docs/RESILIENCE.md)
            self._pipelined = False
            for i, sub in enumerate(built):
                self.add_sublayer(str(i), sub)
            self._run_order = built
            start, length = self._repeated_run(descs, built)
            self._flat_start, self._n_blocks = start, length
            return

        start, length = self._repeated_run(descs, built)
        n_blocks = length
        v = self._virtual
        if n_blocks % (pp * v):
            raise ValueError(
                f"pipeline blocks ({n_blocks}) must divide evenly over pp "
                f"stages ({pp}) x virtual stages ({v})"
            )
        self._pipelined = True
        self._blocks_per_stage = n_blocks // pp
        self._blocks_per_chunk = n_blocks // (pp * v)
        self._n_blocks = n_blocks
        self._flat_start = start

        self._head = built[:start]
        blocks = built[start:start + length]
        self._tail = built[start + length:]
        for i, sub in enumerate(self._head):
            self.add_sublayer(f"head_{i}", sub)
        for i, sub in enumerate(self._tail):
            self.add_sublayer(f"tail_{i}", sub)
        # the template block: its shells get rebound to traced slices
        self._template = blocks[0]
        self.add_sublayer("block_template", self._template)
        self._template_params = [p for _, p in self._template.named_parameters()]
        # exclude template's own params from this container's param list —
        # the stacked tensors are the real trainable state
        self._template_param_ids = {id(p) for p in self._template_params}

        e = env_mod.ensure_env()
        # storage order (d, c, i): device d's contiguous 'pp' shard holds
        # its v interleaved chunks — chunk c of device d = blocks
        # [(c*pp + d)*bpc : +bpc]. Identity when v == 1.
        bpc = self._blocks_per_chunk
        self._block_order = [
            (c * pp + d) * bpc + i
            for d in range(pp) for c in range(v) for i in range(bpc)
        ]
        self._stacked = []
        for name, p in self._template.named_parameters():
            arrs = []
            for bi in self._block_order:
                q = dict(blocks[bi].named_parameters())[name]
                if tuple(q.shape) != tuple(p.shape):
                    raise ValueError(
                        "pipeline blocks must be structurally identical: "
                        f"param {name} shapes differ")
                arrs.append(q._data)
            stacked = jnp.stack(arrs)
            spec = ("pp",) + _param_spec(p)
            stacked = jax.device_put(
                stacked, NamedSharding(e.mesh, PartitionSpec(*spec)))
            sp = EagerParamBase(stacked,
                                name=f"blocks.{name}", trainable=not p.stop_gradient)
            sp._sharding_spec = PartitionSpec(*spec)
            pname = "stack__" + re.sub(r"[^0-9a-zA-Z_]", "_", name)
            self.add_parameter(pname, sp)
            self._stacked.append(sp)

        if shard_head_tail_over_pp:
            self._shard_head_tail(e, pp)

    def _shard_head_tail(self, e, pp):
        """Store head/tail params sharded over the (otherwise replicating)
        'pp' mesh axis — XLA gathers them on use, so the replicated
        embedding/lm-head *compute* does not cost replicated *HBM* (ZeRO-3
        for the non-pipelined layers). Tiny params stay replicated."""
        for sub in (*self._head, *self._tail):
            for _, p in sub.named_parameters():
                if p.ndim == 0 or p._data.size < (1 << 16):
                    continue
                spec = list(_param_spec(p))
                d0 = spec[0]
                if d0 is None:
                    axes = ("pp",)
                elif isinstance(d0, tuple):
                    axes = tuple(d0) + ("pp",)
                else:
                    axes = (d0, "pp")
                if "pp" in (d0 if isinstance(d0, tuple) else (d0,)):
                    continue
                div = 1
                for a in axes:
                    div *= e.degree(a)
                if p.shape[0] % div:
                    continue
                spec[0] = axes if len(axes) > 1 else axes[0]
                p._data = jax.device_put(
                    p._data, NamedSharding(e.mesh, PartitionSpec(*spec)))
                p._sharding_spec = PartitionSpec(*spec)

    @staticmethod
    def _repeated_run(descs, built):
        """Longest contiguous run of descriptors with the same class."""
        best = (0, 1)
        i = 0
        n = len(built)
        while i < n:
            j = i
            cls = type(built[i])
            while j < n and type(built[j]) is cls:
                j += 1
            if j - i > best[1]:
                best = (i, j - i)
            i = j
        return best

    # -- parameters: hide the template's (they are represented stacked) --
    def named_parameters(self, prefix="", include_sublayers=True):
        for name, p in super().named_parameters(prefix, include_sublayers):
            if getattr(self, "_pipelined", False) and id(p) in self._template_param_ids:
                continue
            yield name, p

    # -- canonical (stage-layout-free) checkpoint surface ------------------
    #
    # Checkpoints must survive stage moves (pp1 ↔ pp2 ↔ pp4, any v):
    # state_dict always speaks CANONICAL per-block keys — the flat
    # "<index>.<param>" names the pp=1 sequential container produces —
    # regardless of how the parameters are stored. In pipelined mode the
    # stacked tensors are exposed as per-block slices on save and
    # reassembled (with the stacked sharding) on load, so a checkpoint
    # written at any topology restores at any other by construction
    # (resilience/resume.py rides this for the model AND the optimizer
    # moments). docs/RESILIENCE.md "stage-move reshard".

    def _canonical_prefix_items(self):
        """Head/tail sublayers with their canonical flat-index prefix."""
        items = [(str(i), sub) for i, sub in enumerate(self._head)]
        base = self._flat_start + self._n_blocks
        items += [(str(base + i), sub) for i, sub in enumerate(self._tail)]
        return items

    def _stacked_layout(self):
        """``[(stacked_param, template_key, canonical_keys)]`` — the
        canonical per-block key list is in STORAGE order (slice j of the
        stack is flat block ``_block_order[j]``, so interleaved virtual
        stages canonicalize too)."""
        out = []
        for (name, _p), sp in zip(self._template.named_parameters(),
                                  self._stacked):
            keys = [f"{self._flat_start + bi}.{name}"
                    for bi in self._block_order]
            out.append((sp, name, keys))
        return out

    def _template_buffers(self):
        """The template block's persistable buffers (relative key →
        live Tensor). Staging SHARES one buffer across every block
        (blocks[1:]'s copies are discarded at construction — the
        container cannot represent per-block buffer state), so the
        canonical surface writes the shared value under every block's
        key and reads it back from whichever loads last."""
        param_keys = {k for k, _ in self._template.named_parameters()}
        return {k: v for k, v in self._template.state_dict().items()
                if k not in param_keys}

    def state_dict(self, destination=None, include_sublayers=True,
                   use_hook=True):
        if not getattr(self, "_pipelined", False):
            return super().state_dict(destination, include_sublayers,
                                      use_hook)
        dest = destination if destination is not None \
            else collections.OrderedDict()
        for prefix, sub in self._canonical_prefix_items():
            for k, v in sub.state_dict().items():
                dest[f"{prefix}.{k}"] = v
        for sp, _name, keys in self._stacked_layout():
            for j, key in enumerate(keys):
                dest[key] = Tensor(sp._data[j], stop_gradient=True)
        for bname, buf in self._template_buffers().items():
            for bi in range(self._n_blocks):
                dest[f"{self._flat_start + bi}.{bname}"] = buf
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        if not getattr(self, "_pipelined", False):
            return super().set_state_dict(state_dict, use_structured_name)
        missing, own = [], set()
        for prefix, sub in self._canonical_prefix_items():
            pre = prefix + "."
            sub_sd = {k[len(pre):]: v for k, v in state_dict.items()
                      if k.startswith(pre)}
            m, _ = sub.set_state_dict(sub_sd)
            missing += [pre + k for k in m]
            own.update(pre + k for k in sub.state_dict())
        for bname, buf in self._template_buffers().items():
            bkeys = [f"{self._flat_start + bi}.{bname}"
                     for bi in range(self._n_blocks)]
            own.update(bkeys)
            present = [k for k in bkeys if k in state_dict]
            if not present:
                missing += bkeys
            else:
                v = state_dict[present[-1]]
                buf._data = jax.device_put(np.asarray(
                    v.numpy() if isinstance(v, Tensor) else v))
        for sp, _name, keys in self._stacked_layout():
            own.update(keys)
            if any(k not in state_dict for k in keys):
                missing += [k for k in keys if k not in state_dict]
                continue
            vals = [np.asarray(state_dict[k].numpy()
                               if isinstance(state_dict[k], Tensor)
                               else state_dict[k]) for k in keys]
            arr = np.stack(vals)
            if tuple(arr.shape) != tuple(sp.shape):
                raise ValueError(
                    f"shape mismatch for stacked {_name}: loaded "
                    f"{arr.shape} vs expected {tuple(sp.shape)}")
            sp._data = jax.device_put(
                jnp.asarray(arr, dtype=sp._data.dtype), sp._data.sharding)
        unexpected = [k for k in state_dict if k not in own]
        return missing, unexpected

    load_dict = set_state_dict

    def get_num_stages(self):
        return self._num_stages

    @property
    def loss_fn(self):
        return self._loss_fn

    # -- forward --
    def forward(self, x, n_microbatches=None):
        if not self._pipelined:
            for sub in self._run_order:
                x = sub(x)
            return x
        for sub in self._head:
            x = sub(x)
        x = self._pipeline_blocks(x, n_microbatches)
        for sub in self._tail:
            x = sub(x)
        return x

    def _block_apply(self, param_arrays, x_array):
        """Run the template block's python with shells rebound onto traced
        per-block parameter slices (the TensorWrapper rebinding trick the
        tracing JIT uses — see jit/program.py raw_program)."""
        saved = [(t, t._data) for t in self._template_params]
        for t, a in zip(self._template_params, param_arrays):
            t._data = a
        try:
            with no_grad():
                out = self._template(Tensor(x_array, stop_gradient=True))
        finally:
            for t, a in saved:
                t._data = a
        return out._data

    @staticmethod
    def _make_schedule(n_micro, pp, v):
        """Host-side simulation of the ring schedule: per tick, which chunk
        each stage slot applies, which microbatch (if any) enters slot 0,
        and which finished microbatch (if any) exits slot pp-1. Fully
        deterministic, so it compiles into the program as constant scan
        inputs. GPipe is the v == 1 special case (T = n_micro + pp - 1);
        v > 1 microbatches lap the ring v times (T ~= v*n_micro + pp - 1,
        per-tick work 1/v, fill/drain bubble shrunk by v)."""
        lap = [-1] * pp
        mbid = [-1] * pp
        next_in = exited = 0
        chunks, enters, exits = [], [], []
        while exited < n_micro:
            enter = -1
            if mbid[0] < 0 and next_in < n_micro:
                mbid[0], lap[0], enter = next_in, 0, next_in
                next_in += 1
            chunks.append([max(l, 0) for l in lap])
            enters.append(enter)
            exit_id = -1
            if mbid[pp - 1] >= 0 and lap[pp - 1] == v - 1:
                exit_id = mbid[pp - 1]
                exited += 1
                mbid[pp - 1] = lap[pp - 1] = -1
            exits.append(exit_id)
            mbid = [mbid[-1]] + mbid[:-1]
            lap = [lap[-1]] + lap[:-1]
            if mbid[0] >= 0:
                lap[0] += 1
        return chunks, enters, exits

    def _pipeline_blocks(self, x, n_microbatches):
        """The GSPMD *shifted pipeline* (GSPMD paper §3.3): stage states are
        one array [pp, mb, ...] sharded on 'pp'; each tick vmaps the block
        stack over the stage dim (each device computes its stage) and
        `jnp.roll`s the state one slot — a shift on a sharded dim that XLA
        lowers to CollectivePermute over ICI. The tick loop is a `lax.scan`
        over a precomputed schedule, so compile time is O(1) in both the
        microbatch count and pp (VERDICT round 1: the unrolled loop blew up
        compile time). The whole schedule is ONE differentiable XLA program
        (vjp replays it in reverse — fwd/bwd overlap comes from XLA
        scheduling, not host code)."""
        e = env_mod.ensure_env()
        pp = _pp_degree()
        v = self._virtual
        n_micro = n_microbatches or self._default_microbatches()
        bpc = self._blocks_per_chunk
        block_apply = self._block_apply
        remat = self._recompute and self._recompute > 0
        remat_ticks = self._remat_ticks
        if remat_ticks is None:
            remat_ticks = self._default_schedule_1f1b()
        stage_sharding = NamedSharding(e.mesh, PartitionSpec("pp"))

        chunks, enters, exits = self._make_schedule(n_micro, pp, v)
        m = _monitor
        if m is not None:
            # the compiled ppermute handoff never reaches the eager
            # collective counters — account it analytically from the
            # schedule: one permute of the [pp, mb, ...] state per tick
            mb = x.shape[0] // n_micro if n_micro else int(x.shape[0])
            elems = pp * mb
            for d in x.shape[1:]:
                elems *= int(d)
            itemsize = np.dtype(x._data.dtype).itemsize
            m.on_pipeline_forward(
                pp=pp, n_micro=n_micro, ticks=len(chunks),
                p2p_bytes=len(chunks) * elems * itemsize,
                bubble=(len(chunks) - v * n_micro) / max(len(chunks), 1))
        sched = (jnp.asarray(chunks, jnp.int32),
                 jnp.asarray(enters, jnp.int32),
                 jnp.asarray(exits, jnp.int32),
                 jnp.arange(len(chunks), dtype=jnp.int32))

        def kernel(xa, key_data, *stacked):
            B = xa.shape[0]
            if B % n_micro:
                raise ValueError(
                    f"batch {B} not divisible into {n_micro} microbatches")
            mb = B // n_micro
            xs = xa.reshape(n_micro, mb, *xa.shape[1:])
            base_key = jax.random.wrap_key_data(key_data)
            # [n_blocks, ...] -> [pp, v, bpc, ...] (storage order is
            # (device, chunk, intra) — see __init__); dim0 stays 'pp'-sharded
            staged = [s.reshape(pp, v, bpc, *s.shape[1:]) for s in stacked]

            def stage_fn(params_stage, chunk_idx, state, stage_key):
                chunk = [
                    jax.lax.dynamic_index_in_dim(p, chunk_idx, 0,
                                                 keepdims=False)
                    for p in params_stage
                ]
                block_keys = jax.random.split(
                    jax.random.fold_in(stage_key, chunk_idx), bpc)

                def body(carry, inp):
                    params_i, k = inp
                    fn = block_apply
                    if remat:
                        fn = jax.checkpoint(fn)
                    # block dropout etc. draws from the per-block key so
                    # masks are independent across blocks/stages/ticks and
                    # reproducible under remat
                    with rng.rng_scope(k):
                        out = fn(list(params_i), carry)
                    return out, None

                out, _ = jax.lax.scan(body, state,
                                      (tuple(chunk), block_keys))
                return out

            vstage = jax.vmap(stage_fn, in_axes=(0, 0, 0, 0))

            def tick(carry, sch):
                states, outputs = carry
                chunk_idx, enter_id, exit_id, t = sch
                stage_keys = jax.random.split(
                    jax.random.fold_in(base_key, t), pp)
                x_in = jax.lax.dynamic_index_in_dim(
                    xs, jnp.maximum(enter_id, 0), 0, keepdims=False)
                states = states.at[0].set(
                    jnp.where(enter_id >= 0, x_in, states[0]))
                states = jax.lax.with_sharding_constraint(
                    states, stage_sharding)
                states = vstage(staged, chunk_idx, states, stage_keys)
                oi = jnp.maximum(exit_id, 0)
                cur = jax.lax.dynamic_index_in_dim(
                    outputs, oi, 0, keepdims=False)
                outputs = jax.lax.dynamic_update_index_in_dim(
                    outputs, jnp.where(exit_id >= 0, states[pp - 1], cur),
                    oi, 0)
                if pp > 1:
                    states = jnp.roll(states, 1, axis=0)
                return (states, outputs), None

            states = jnp.zeros((pp, mb) + tuple(xa.shape[1:]), xa.dtype)
            outputs = jnp.zeros((n_micro, mb) + tuple(xa.shape[1:]), xa.dtype)
            body = jax.checkpoint(tick) if remat_ticks else tick
            (states, outputs), _ = jax.lax.scan(
                body, (states, outputs), sched)
            return outputs.reshape(B, *outputs.shape[2:])

        key_data = Tensor(
            jax.random.key_data(rng.next_key()), stop_gradient=True)
        return apply("pipeline", kernel, (x, key_data, *self._stacked))

    def _default_schedule_1f1b(self):
        from ... import get_strategy

        s = get_strategy()
        if s is None:
            return False
        sched = (s.pipeline_configs or {}).get("schedule", "")
        return str(sched).upper() == "1F1B"

    def _default_microbatches(self):
        from ... import get_strategy

        s = get_strategy()
        if s is not None and s.pipeline_configs.get("accumulate_steps"):
            return int(s.pipeline_configs["accumulate_steps"])
        return _pp_degree()


_monitor_register(sys.modules[__name__])
