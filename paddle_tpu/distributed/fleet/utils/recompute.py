"""User-facing activation recomputation (gradient checkpointing).

Reference parity: `paddle.distributed.fleet.utils.recompute` /
`recompute_sequential` (`fleet/recompute/recompute.py:69,334`) — a PyLayer
that stashes inputs + RNG state in forward and re-runs the forward inside
backward.

TPU-first design: the segment becomes ONE taped op whose pure function is
wrapped in `jax.checkpoint`. `jax.vjp` of a checkpointed function stores
only the segment *inputs*; the pullback rematerializes the forward — the
same storage contract as the reference's PyLayer, but it composes with jit
(`TrainStep` whole-step compilation sees the remat annotation and XLA
schedules the recompute). RNG determinism needs no state save/restore: the
PRNG key is threaded as an operand, so the rematerialized forward replays
the identical dropout masks by construction (the reference must snapshot
and restore CUDA RNG state — `recompute.py:113` `swith_rng_state_tracker`).
"""
from __future__ import annotations

import jax

from ....autograd import tape
from ....autograd.tape import no_grad
from ....framework import random as rng
from ....framework.core import Tensor
from ....jit.program import _flatten, _unflatten
from ....nn.layer.layers import Layer
from ....ops.dispatch import apply


def _collect_state(function):
    """Differentiable params + aux buffers of the Layer behind ``function``
    (the Layer itself, or a bound method of one)."""
    layer = None
    if isinstance(function, Layer):
        layer = function
    else:
        owner = getattr(function, "__self__", None)
        if isinstance(owner, Layer):
            layer = owner
    if layer is None:
        return [], []
    diff, aux = [], []
    seen = set()
    for _, p in layer.named_parameters():
        if id(p) not in seen:
            seen.add(id(p))
            (aux if p.stop_gradient else diff).append(p)
    for _, b in layer.named_buffers():
        if id(b) not in seen:
            seen.add(id(b))
            aux.append(b)
    return diff, aux


def recompute(function, *args, **kwargs):
    """Run ``function(*args, **kwargs)`` without storing its intermediate
    activations; the backward pass recomputes them. Gradients flow to the
    tensor arguments and to the parameters of ``function``'s Layer (pass a
    Layer or a Layer's bound method, e.g. ``recompute(self.block, x)``).
    """
    kwargs.pop("preserve_rng_state", True)   # always preserved (see module doc)
    kwargs.pop("use_reentrant", None)        # accepted for API parity
    if not tape.is_grad_enabled():
        return function(*args, **kwargs)

    diff, aux = _collect_state(function)
    leaves: list[Tensor] = []
    in_spec = _flatten((args, kwargs), leaves)
    stop_flags = [t.stop_gradient for t in leaves]
    n_diff, n_aux = len(diff), len(aux)
    prng = rng.next_key()
    entry = {}

    def pure(*arrays):
        param_arrays = arrays[:n_diff]
        aux_arrays = arrays[n_diff:n_diff + n_aux]
        key = arrays[n_diff + n_aux]
        input_arrays = arrays[n_diff + n_aux + 1:]
        for t, a in zip(diff, param_arrays):
            t._data = a
        for t, a in zip(aux, aux_arrays):
            t._data = a
        input_tensors = [
            Tensor(a, stop_gradient=sg)
            for a, sg in zip(input_arrays, stop_flags)
        ]
        call_args, call_kwargs = _unflatten(in_spec, input_tensors, pos=[0])
        with no_grad(), rng.rng_scope(key):
            out = function(*call_args, **call_kwargs)
        out_leaves: list[Tensor] = []
        entry["out_spec"] = _flatten(out, out_leaves)
        entry["n_user_out"] = len(out_leaves)
        return tuple(t._data for t in out_leaves) + tuple(
            t._data for t in aux)

    ckpt = jax.checkpoint(pure)
    saved = [(t, t._data) for t in diff + aux]
    try:
        outs = apply("recompute", ckpt, (*diff, *aux, prng, *leaves))
    finally:
        for t, a in saved:
            t._data = a
    outs = outs if isinstance(outs, tuple) else (outs,)
    user_outs = list(outs[: entry["n_user_out"]])
    new_aux = outs[entry["n_user_out"]:]
    with no_grad():
        for t, new in zip(aux, new_aux):
            t._data = new._data
    return _unflatten(entry["out_spec"], user_outs, pos=[0])


def recompute_sequential(ctx, functions, *args, **kwargs):
    """Parity: `recompute.py:334` — split a Sequential/LayerList into
    ``segments`` chunks and recompute each chunk.

    ``ctx``: dict with optional ``segments`` (default 1) and
    ``preserve_rng_state``.
    """
    segments = int((ctx or {}).get("segments", 1) or 1)
    preserve = (ctx or {}).get("preserve_rng_state", True)
    if isinstance(functions, Layer):
        layers = list(functions)     # Sequential / LayerList iterate children
    else:
        layers = list(functions)

    class _Segment(Layer):
        def __init__(self, subs):
            super().__init__()
            for i, s in enumerate(subs):
                self.add_sublayer(str(i), s)
            self._subs = subs

        def forward(self, *xs, **kw):
            out = xs
            for s in self._subs:
                out = s(*out, **kw) if isinstance(out, tuple) else s(out, **kw)
                if not isinstance(out, tuple):
                    out = (out,)
                kw = {}
            return out[0] if len(out) == 1 else out

    n = len(layers)
    seg_size = max(1, (n + segments - 1) // segments)
    out = args
    for start in range(0, n, seg_size):
        seg = _Segment(layers[start:start + seg_size])
        if not isinstance(out, tuple):
            out = (out,)
        out = recompute(seg, *out, preserve_rng_state=preserve, **kwargs)
        kwargs = {}
    return out
