"""Megatron-style sequence parallelism (SP) utilities.

Reference parity: `fleet/utils/sequence_parallel_utils.py:36-122` (the
Scatter/Gather/AllGather/ReduceScatter PyLayers), `:228`
(`ColumnSequenceParallelLinear`), `:340` (`RowSequenceParallelLinear`),
`:190` (SP-param allreduce hooks).

TPU-first design: SP shards the *sequence* dim of activations over the 'mp'
axis in the regions between the TP linears (layernorm/dropout/residual), so
the memory-heavy elementwise region holds seq/mp per device. The reference
implements this with explicit allgather/reduce-scatter PyLayers; here each
op is a sharding constraint and XLA emits the all-gather (entering a column
linear) and reduce-scatter (leaving a row linear) — including their
transposes in backward. The SP-parameter allreduce hook (`:190`) has no
equivalent: layernorm params are global replicated arrays, their grads are
reduced by GSPMD automatically.

Layout note: paddle's SP utils assume activations [s, b, h]; ours follow the
framework-wide [b, s, h] and shard dim 1.
"""
from __future__ import annotations

from ... import shard
from ...fleet.base.topology import ensure_hcg
from ...fleet.meta_parallel.parallel_layers.mp_layers import (
    ColumnParallelLinear, RowParallelLinear,
)


def _seq_spec(ndim, axis="mp"):
    # batch keeps its dp split: a constraint naming only the seq axis
    # would force XLA to DROP the dp sharding at every SP boundary (a
    # full remat copy per layer now that traced constraints are honored
    # — distributed/shard.py)
    parts = [None] * ndim
    parts[0] = "dp"
    parts[1] = axis
    return parts


def _batch_spec(ndim):
    return ["dp"] + [None] * (ndim - 1)


class ScatterOp:
    """Split the sequence dim over 'mp' (parity `:85`)."""

    @staticmethod
    def apply(x):
        return shard.sharding_constraint(x, *_seq_spec(x.ndim))


class GatherOp:
    """Re-replicate the sequence dim (parity `:99`)."""

    @staticmethod
    def apply(x):
        return shard.sharding_constraint(x, *_batch_spec(x.ndim))


class AllGatherOp:
    """Gather seq shards before a column-parallel matmul (parity `:108`)."""

    @staticmethod
    def apply(x):
        return shard.sharding_constraint(x, *_batch_spec(x.ndim))


class ReduceScatterOp:
    """Reduce partial sums and scatter the seq dim (parity `:122`) —
    the exit of a row-parallel matmul in SP mode."""

    @staticmethod
    def apply(x):
        return shard.sharding_constraint(x, *_seq_spec(x.ndim))


def scatter(x):
    return ScatterOp.apply(x)


def all_gather(x):
    return AllGatherOp.apply(x)


def reduce_scatter(x):
    return ReduceScatterOp.apply(x)


def mark_as_sequence_parallel_parameter(parameter):
    """Parity `:170`: tag consulted by `register_sequence_parallel_allreduce_hooks`;
    grads of global arrays are already correct under GSPMD, so the tag is
    informational."""
    parameter.sequence_parallel = True


def is_sequence_parallel_parameter(parameter):
    return getattr(parameter, "sequence_parallel", False)


def register_sequence_parallel_allreduce_hooks(model, accumulation_steps=1,
                                               fuse_sequence_parallel_allreduce=False):
    """No-op under GSPMD (see module docstring); kept for script parity."""
    return None


class ColumnSequenceParallelLinear(ColumnParallelLinear):
    """Column-parallel linear whose input arrives sequence-sharded
    (parity `:228`): all-gather seq → matmul → output feature-sharded."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=None, gather_output=False, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__(in_features, out_features, weight_attr=weight_attr,
                         has_bias=has_bias, gather_output=gather_output,
                         fuse_matmul_bias=fuse_matmul_bias,
                         mp_group=mp_group, name=name)

    def forward(self, x):
        x = AllGatherOp.apply(x)
        return super().forward(x)


class RowSequenceParallelLinear(RowParallelLinear):
    """Row-parallel linear whose output leaves sequence-sharded
    (parity `:340`): matmul partial sums → reduce-scatter over seq."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=True,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__(in_features, out_features, weight_attr=weight_attr,
                         has_bias=has_bias,
                         input_is_parallel=input_is_parallel,
                         fuse_matmul_bias=fuse_matmul_bias,
                         mp_group=mp_group, name=name)

    def forward(self, x):
        out = super().forward(x)
        return ReduceScatterOp.apply(out)


def create_fused_allreduce_gradient_hook(*a, **k):  # parity stub
    return None
