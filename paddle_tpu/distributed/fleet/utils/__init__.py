from .recompute import recompute, recompute_sequential  # noqa: F401

__all__ = ["recompute", "recompute_sequential"]
