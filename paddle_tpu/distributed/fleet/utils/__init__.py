from .recompute import recompute, recompute_sequential  # noqa: F401

__all__ = ["recompute", "recompute_sequential"]


class LocalFS:
    """Local filesystem client (parity: fleet.utils.LocalFS — the
    reference's fs abstraction over local disk)."""

    def ls_dir(self, path):
        import os

        dirs, files = [], []
        for name in sorted(os.listdir(path)):
            (dirs if os.path.isdir(os.path.join(path, name))
             else files).append(name)
        return dirs, files

    def mkdirs(self, path):
        import os

        os.makedirs(path, exist_ok=True)

    def is_dir(self, path):
        import os

        return os.path.isdir(path)

    def is_file(self, path):
        import os

        return os.path.isfile(path)

    def is_exist(self, path):
        import os

        return os.path.exists(path)

    def delete(self, path):
        import os
        import shutil

        if os.path.isdir(path):
            shutil.rmtree(path)
        elif os.path.exists(path):
            os.remove(path)

    def rename(self, src, dst):
        import os

        os.rename(src, dst)

    def mv(self, src, dst, overwrite=False):
        import os

        if os.path.exists(dst):
            if not overwrite:
                raise FileExistsError(
                    f"mv destination exists: {dst!r} (reference "
                    "FSFileExistsError semantics; pass overwrite=True)")
            self.delete(dst)
        os.rename(src, dst)

    def upload(self, local_path, fs_path):
        import shutil

        shutil.copy(local_path, fs_path)

    def download(self, fs_path, local_path):
        import shutil

        shutil.copy(fs_path, local_path)

    def touch(self, path, exist_ok=True):
        import os

        if os.path.exists(path) and not exist_ok:
            raise FileExistsError(path)
        open(path, "a").close()

    def cat(self, path):
        with open(path, "rb") as f:
            return f.read()

    def list_dirs(self, path):
        return self.ls_dir(path)[0]


class HDFSClient:
    """Parity: fleet.utils.HDFSClient. HDFS needs the hadoop CLI, which
    this image does not bundle; the constructor verifies the binary and
    raises with that rationale otherwise (silent absence would hide the
    gap)."""

    def __init__(self, hadoop_home=None, configs=None, **kwargs):
        import os
        import shutil

        cand = (os.path.join(hadoop_home, "bin", "hadoop")
                if hadoop_home else shutil.which("hadoop"))
        if not cand or not os.path.exists(cand):
            raise RuntimeError(
                "HDFSClient requires the hadoop CLI, which is not present "
                "in this TPU image; mount it and pass hadoop_home, or use "
                "LocalFS / gcsfuse-style mounts for TPU-pod storage")
        self._hadoop = cand
        self._configs = configs or {}


class DistributedInfer:
    """Parity shim: fleet.utils.DistributedInfer rebuilds a PS program for
    distributed inference; the PS tier is excluded (README 'Scope'), and
    GSPMD inference needs no program rewrite — `inference.Predictor` runs
    the sharded program directly."""

    def __init__(self, main_program=None, startup_program=None):
        raise RuntimeError(
            "DistributedInfer is part of the excluded parameter-server "
            "stack (README 'Scope'); use paddle_tpu.inference.Predictor — "
            "GSPMD-sharded programs serve without a rewrite pass")


__all__ += ["LocalFS", "HDFSClient", "DistributedInfer"]
