"""Distributed checkpoint: sharded per-region save + reshard-on-load.

Reference parity: auto-parallel `dist_saver.py:53` (per-rank shard files) +
`converter.py:25` (cross-config conversion) and the PP/TP checkpoint
adaptors (`fleet/utils/pp_parallel_adaptor.py`); SURVEY §5.4 asks for the
tensorstore/OCDBT-style contract: async sharded checkpoint keyed by global
shape + sharding, with reshard-on-load.

TPU-first design: tensors are GLOBAL arrays (sharding is placement, not
identity), so the reference's shard-merging converter collapses into
layout metadata. Format (v2):

  index.json                       {"format": 2, "tensors": {key: meta}}
  <key>.r<start>x<start>....npy    one .npy PER SHARD REGION

Save never materializes a tensor's global value: each unique shard region
(deduped by ``replica_id == 0``) is fetched device->host on its own and
streamed to its own file; single-device / host arrays stream in
row-chunks through a memmap. Load never materializes the global value
either: `jax.make_array_from_callback` asks for exactly the regions the
*destination* sharding needs, and each region is assembled by slicing the
overlapping shard files (mmap reads). Mesh-shape changes (tp4->tp8, pp
on/off, ZeRO on/off) are therefore reshard-on-load by construction, at
per-device memory cost.

Async save bounds host memory: shard snapshots are produced into a
byte-bounded queue (default 1 GiB in flight) and written by one writer
thread — the full checkpoint is never resident on the host at once
(the v1 design held a complete host copy per pending save).

Multi-host: every process writes only its addressable ``replica_id == 0``
shards (disjoint across processes by construction); the coordinator
writes the index, enumerating all regions from the global sharding via
``devices_indices_map`` — so a shared filesystem assembles the checkpoint
with no cross-host gathers.
"""
from __future__ import annotations

import collections
import json
import os
import re
import threading

import jax
import numpy as np

from ..framework.core import Tensor

_INDEX = "index.json"
_CHUNK_BYTES = 64 << 20  # streaming-chunk size for unsharded tensors


def _safe_name(key):
    return re.sub(r"[^0-9A-Za-z_.\-]", "_", key)


def atomic_write_json(path, obj):
    """Crash-safe JSON publish: tmp + fsync + rename + parent-dir fsync.
    A crash mid-write can only leave the .tmp (never a truncated final
    file), and the rename itself is durable once the directory entry is
    synced. Shared by the index write here and the resilience layer's
    MANIFEST.json (checkpoint_manager.py) — completeness markers must
    all be torn-proof the same way."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    try:
        dfd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:  # platform without directory fsync
        pass


def _spec_of(arr):
    s = getattr(arr, "sharding", None)
    spec = getattr(s, "spec", None)
    if spec is None:
        return None
    return [list(p) if isinstance(p, tuple) else p for p in spec]


def _norm_index(idx, shape):
    """Tuple of slices (possibly with None endpoints) -> [[start, stop]]."""
    out = []
    for sl, dim in zip(idx, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


def _region_tag(bounds):
    if not bounds:
        return "r0"
    return "r" + "x".join(str(b[0]) for b in bounds)


def _unique_regions(arr):
    """All shard regions of a jax.Array's GLOBAL sharding, deduped, as
    normalized bounds lists. Enumerated from devices_indices_map so the
    index is complete even when some shards live on other hosts."""
    seen = {}
    for idx in arr.sharding.devices_indices_map(arr.shape).values():
        bounds = _norm_index(idx, arr.shape)
        seen[_region_tag(bounds)] = bounds
    return seen


def _dtype_str(arr):
    return str(arr.dtype)


class _ByteQueue:
    """Bounded-byte producer/consumer queue for async checkpoint writes.
    A writer failure unblocks and re-raises in the producer (`put`)
    rather than deadlocking it against a dead consumer."""

    def __init__(self, max_bytes):
        self.max = max_bytes
        self._q = collections.deque()
        self._bytes = 0
        self._cv = threading.Condition()
        self._closed = False
        self.error = None

    def put(self, item, nbytes):
        with self._cv:
            while (self.error is None and self._bytes
                   and self._bytes + nbytes > self.max):
                self._cv.wait()
            if self.error is not None:
                raise RuntimeError(
                    "async checkpoint writer failed") from self.error
            self._q.append((item, nbytes))
            self._bytes += nbytes
            self._cv.notify_all()

    def get(self):
        with self._cv:
            while not self._q and not self._closed:
                self._cv.wait()
            if not self._q:
                return None
            item, nbytes = self._q.popleft()
            self._bytes -= nbytes
            self._cv.notify_all()
            return item

    def fail(self, exc):
        with self._cv:
            self.error = exc
            self._q.clear()
            self._bytes = 0
            self._cv.notify_all()

    def close(self):
        with self._cv:
            self._closed = True
            self._cv.notify_all()


class _WriterThread(threading.Thread):
    """Async-save writer whose failure actually surfaces: `join()`
    re-raises the writer's exception in the joining thread (a bare
    `Thread.join` returns normally over a dead thread, which would let
    a failed checkpoint pass for a written one). `join()` then runs the
    save's `finalize` (cross-process barrier + index write) ON THE
    CALLER THREAD — a device collective issued from a background thread
    could interleave with the training step's collectives in different
    orders on different hosts and deadlock."""

    def __init__(self, target, finalize=None):
        super().__init__(daemon=True)
        self._target_fn = target
        self._finalize = finalize
        self._finalized = False
        self._lock = threading.Lock()
        self.error = None

    def run(self):
        try:
            self._target_fn()
        except BaseException as e:  # noqa: BLE001 — re-raised in join()
            self.error = e

    def join(self, timeout=None):
        super().join(timeout)
        if self.is_alive():  # timeout expired
            return
        if self.error is not None:
            raise RuntimeError(
                "async checkpoint writer failed") from self.error
        with self._lock:
            if self._finalized or self._finalize is None:
                return
            self._finalized = True
        self._finalize()


def _barrier():
    """Cross-process fence: every process's shard writes are on disk
    before the coordinator writes index.json (whose presence is the
    checkpoint-complete marker). No-op single-process."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("paddle_tpu_ckpt_save")


def _seal_memmaps(path, open_memmaps):
    """Flush chunk-streamed shard files and move them from .tmp to their
    final names. ``open_memmap`` allocates the FULL file up front, so a
    size check can never see a torn chunk write — the rename is the
    write-complete marker ``is_complete`` relies on (a writer killed
    mid-stream leaves only the .tmp; the final name is absent)."""
    for fname, mm in open_memmaps.items():
        mm.flush()
        os.replace(os.path.join(path, fname + ".tmp"),
                   os.path.join(path, fname))
    open_memmaps.clear()


def _write_item(path, item, open_memmaps):
    kind = item[0]
    if kind == "barrier":
        _seal_memmaps(path, open_memmaps)
        _barrier()
    elif kind == "npy":
        _, fname, arr = item
        np.save(os.path.join(path, fname), arr)
    elif kind == "chunk":
        _, fname, shape, dtype, row0, arr = item
        mm = open_memmaps.get(fname)
        if mm is None:
            mm = np.lib.format.open_memmap(
                os.path.join(path, fname + ".tmp"), mode="w+",
                dtype=np.dtype(dtype), shape=tuple(shape))
            open_memmaps[fname] = mm
        mm[row0:row0 + arr.shape[0]] = arr
    elif kind == "index":
        _, meta = item
        _seal_memmaps(path, open_memmaps)
        # index last: its presence marks the checkpoint complete. Written
        # atomically so a crash mid-write can only leave NO index (torn
        # checkpoint, never selected for resume) — a truncated
        # index.json would otherwise read as a checkpoint with fewer
        # tensors, which is worse than none at all.
        atomic_write_json(os.path.join(path, _INDEX), meta)


def _emit_tensor(key, arr, entries, sink, snapshot=False,
                 write_unsharded=True):
    """Stream one tensor's addressable shards into `sink` and record its
    index entry. Never touches the global value. `snapshot=True` forces
    an owned copy of every piece (async saves: np.asarray of a host
    ndarray — or of a CPU-backend jax buffer — is a zero-copy VIEW the
    caller may mutate or donate before the writer drains it).
    `write_unsharded=False` records the entry but skips the data write
    for tensors with no shard ownership (host ndarrays, 0-d arrays) —
    multi-host saves gate those on the coordinator so N processes don't
    race truncate/write on the same file."""
    fbase = _safe_name(key)
    if isinstance(arr, Tensor):
        arr = arr._data
    is_jax = isinstance(arr, jax.Array)
    if is_jax and getattr(arr, "sharding", None) is not None and arr.ndim:
        regions = _unique_regions(arr)
        shards = {
            _region_tag(_norm_index(s.index, arr.shape)): s
            for s in arr.addressable_shards if s.replica_id == 0
        }
    else:
        arr = np.asarray(arr)
        regions = {_region_tag([[0, d] for d in arr.shape]):
                   [[0, d] for d in arr.shape]}
        shards = None
    itemsize = np.dtype(_dtype_str(arr)).itemsize
    entry = {
        "shape": list(arr.shape),
        "dtype": _dtype_str(arr),
        "spec": _spec_of(arr),
        # per-shard payload bytes: lets is_complete() detect a shard file
        # truncated by a mid-save crash (a complete .npy is header + data,
        # so its on-disk size is strictly greater than the data bytes)
        "shards": [{"file": f"{fbase}.{tag}.npy", "index": bounds,
                    "bytes": int(np.prod(
                        [b[1] - b[0] for b in bounds],
                        dtype=np.int64)) * itemsize if bounds
                    else itemsize}
                   for tag, bounds in sorted(regions.items())],
    }
    entries[key] = entry
    for tag, bounds in sorted(regions.items()):
        fname = f"{fbase}.{tag}.npy"
        if shards is not None:
            shard = shards.get(tag)
            if shard is None:
                continue  # owned by another host's process
            data = shard.data
        else:
            if not write_unsharded:
                continue  # coordinator writes ownerless tensors
            data = arr
        shape = tuple(b[1] - b[0] for b in bounds) if bounds else ()
        nbytes = int(np.prod(shape, dtype=np.int64)) * np.dtype(
            _dtype_str(data)).itemsize if shape else np.dtype(
            _dtype_str(data)).itemsize
        snap = (lambda a: np.array(a, copy=True)) if snapshot \
            else np.asarray
        if not shape or nbytes <= _CHUNK_BYTES:
            sink(("npy", fname, snap(data)), max(nbytes, 1))
        else:
            # stream row-chunks: bounds the host high-water mark for
            # huge single-region tensors (embedding tables etc.)
            rows = max(1, _CHUNK_BYTES // max(1, nbytes // shape[0]))
            for r0 in range(0, shape[0], rows):
                piece = snap(data[r0:r0 + rows])
                sink(("chunk", fname, shape, _dtype_str(data), r0, piece),
                     piece.nbytes)


def save_state_dict(state_dict, path, process_group=None, coordinator_rank=0,
                    async_save=False, max_inflight_bytes=1 << 30):
    """Save {name: Tensor} to a sharded checkpoint directory.

    Returns None, or a started writer thread if async_save — join it (or
    call wait_all()) before relying on the files; join RAISES if the
    writer failed (ENOSPC, permissions), so a failed checkpoint cannot
    pass for a written one. Async saves hold at most ~max_inflight_bytes
    of host snapshots at a time; the producer (caller) blocks when the
    writer falls that far behind, which keeps memory bounded instead of
    buffering the whole model. `process_group` is accepted for API parity
    but unused: shard ownership comes from the arrays' global shardings.
    """
    os.makedirs(path, exist_ok=True)
    entries = {}
    is_coordinator = jax.process_index() == coordinator_rank

    if not async_save:
        open_memmaps = {}

        def sink(item, nbytes):
            _write_item(path, item, open_memmaps)

        for key, val in state_dict.items():
            _emit_tensor(key, val, entries, sink,
                         write_unsharded=is_coordinator
                         or jax.process_count() == 1)
        sink(("barrier",), 0)  # all hosts' shards durable before index
        if is_coordinator:
            sink(("index", {"format": 2, "tensors": entries}), 0)
        # post-index barrier: no process returns before the completeness
        # marker exists — otherwise a non-coordinator that reads the
        # checkpoint right after save races the coordinator's write
        sink(("barrier",), 0)
        return None

    q = _ByteQueue(max_inflight_bytes)

    def writer():
        open_memmaps = {}
        try:
            while True:
                item = q.get()
                if item is None:
                    break
                _write_item(path, item, open_memmaps)
            _seal_memmaps(path, open_memmaps)
        except BaseException as e:
            q.fail(e)  # unblock + fail the producer
            raise

    def finalize():
        # runs in join(), on the CALLER thread: cross-process barrier,
        # then the coordinator publishes the completeness marker, then a
        # second barrier so no process's join() returns pre-index
        _barrier()
        if is_coordinator:
            _write_item(path, ("index", {"format": 2, "tensors": entries}),
                        {})
        _barrier()

    t = _WriterThread(writer, finalize)
    t.start()
    # snapshots are produced SYNCHRONOUSLY with respect to the live
    # jax.Arrays (the next train step may donate/rebind their buffers;
    # round-1 ADVICE), and as OWNED copies (snapshot=True) so the writer
    # never reads a buffer the caller can mutate — only file I/O
    # overlaps with the caller.
    try:
        for key, val in state_dict.items():
            _emit_tensor(key, val, entries, q.put, snapshot=True,
                         write_unsharded=is_coordinator
                         or jax.process_count() == 1)
    finally:
        q.close()
    _pending.append(t)
    return t


_pending: list = []


def wait_all():
    """Block until every async save has finished. Raises if any writer
    failed (the checkpoint on disk is then incomplete)."""
    while _pending:
        _pending.pop().join()


def _np_from_file(fpath, dtype):
    """mmap a shard .npy; re-view exotic dtypes (bfloat16 round-trips
    through .npy as raw 'V2' bytes)."""
    data = np.load(fpath, mmap_mode="r")
    want = np.dtype(dtype)
    if data.dtype != want and data.dtype.itemsize == want.itemsize:
        data = data.view(want)
    return data


def _read_region(path, meta, bounds):
    """Assemble one region [[start, stop], ...] of a tensor from the shard
    files that overlap it. Reads only overlapping byte ranges (mmap)."""
    shape = tuple(b[1] - b[0] for b in bounds)
    out = np.empty(shape, np.dtype(meta["dtype"]))
    for sh in meta["shards"]:
        s_bounds = sh["index"]
        lo = [max(b[0], s[0]) for b, s in zip(bounds, s_bounds)]
        hi = [min(b[1], s[1]) for b, s in zip(bounds, s_bounds)]
        if any(l >= h for l, h in zip(lo, hi)):
            continue
        src = tuple(slice(l - s[0], h - s[0])
                    for l, h, s in zip(lo, hi, s_bounds))
        dst = tuple(slice(l - b[0], h - b[0])
                    for l, h, b in zip(lo, hi, bounds))
        data = _np_from_file(os.path.join(path, sh["file"]), meta["dtype"])
        out[dst] = data[src]
    return out


def _meta_v1_to_v2(meta):
    """v1 entries ({'file': ...}) read as a single whole-tensor shard."""
    if "shards" in meta:
        return meta
    meta = dict(meta)
    meta["shards"] = [{"file": meta.pop("file"),
                       "index": [[0, d] for d in meta["shape"]]}]
    return meta


def _load_index(path):
    with open(os.path.join(path, _INDEX)) as f:
        raw = json.load(f)
    tensors = raw["tensors"]
    return {k: _meta_v1_to_v2(m) for k, m in tensors.items()}


def is_complete(path):
    """True iff ``path`` holds a complete, untorn checkpoint: the index
    exists and parses, and every shard file it references mmaps with its
    full header-declared payload on disk (``np.memmap`` refuses a file
    shorter than header + data, so a shard truncated by a mid-save crash
    fails here) AND matches the payload size the index recorded for its
    region (``shards[].bytes``, absent on older checkpoints).
    Chunk-streamed shards (tensors over the streaming threshold) are
    covered by a different mechanism: they are written to ``.tmp`` and
    renamed only once fully streamed (``_seal_memmaps``), because their
    memmap is allocated at full size up front — a writer killed
    mid-stream leaves no file at the final name. The resume selector
    (``resilience/checkpoint_manager.py``) calls this so a checkpoint
    killed mid-write is never resumed from."""
    try:
        index = _load_index(path)
    except (OSError, ValueError, KeyError):
        return False
    for meta in index.values():
        for sh in meta.get("shards", []):
            fpath = os.path.join(path, sh["file"])
            try:
                data = np.load(fpath, mmap_mode="r")
            except Exception:  # noqa: BLE001 — torn/missing/corrupt
                return False
            want = sh.get("bytes")
            if want is not None and data.nbytes != want:
                return False
    return True


def load_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, offload=False):
    """Load a checkpoint INTO the given {name: Tensor} dict, placing each
    value with the destination tensor's current sharding (reshard-on-load).
    Each device's region is assembled from only the shard files overlapping
    it — the global value is never materialized for sharded destinations.
    Missing keys raise; extra checkpoint keys are ignored."""
    index = _load_index(path)
    for key, dest in state_dict.items():
        if key not in index:
            raise KeyError(f"checkpoint at {path} has no tensor {key!r}")
        meta = index[key]
        if not isinstance(dest, Tensor):
            continue
        if tuple(meta["shape"]) != tuple(dest.shape):
            raise ValueError(
                f"{key}: checkpoint shape {tuple(meta['shape'])} != dest "
                f"{tuple(dest.shape)} (shape-changing conversion is not a "
                "reshard)")
        sharding = getattr(dest._data, "sharding", None)
        dtype = dest._data.dtype

        if sharding is not None and dest._data.ndim:
            def cb(idx, _m=meta, _d=dtype):
                bounds = _norm_index(idx, _m["shape"])
                return _read_region(path, _m, bounds).astype(_d)

            dest._data = jax.make_array_from_callback(
                tuple(meta["shape"]), sharding, cb)
        else:
            full = _read_region(path, meta,
                                [[0, d] for d in meta["shape"]])
            if sharding is not None:  # 0-d: keep the mesh placement
                dest._data = jax.device_put(full.astype(dtype), sharding)
            else:
                dest._data = jax.device_put(full.astype(dtype))
    return state_dict


def load_checkpoint(path):
    """Load to host: {name: np.ndarray} without placement."""
    index = _load_index(path)
    return {k: _read_region(path, m, [[0, d] for d in m["shape"]])
            for k, m in index.items()}
