"""Distributed checkpoint: sharded save + reshard-on-load.

Reference parity: auto-parallel `dist_saver.py` + `converter.py` (SURVEY
§5.4 — "re-shard checkpoints across different parallel configs (the
converter.py capability is the important contract)") and the PP/TP
checkpoint adaptors (`fleet/utils/pp_parallel_adaptor.py`).

TPU-first design: tensors are GLOBAL arrays (sharding is placement, not
identity), so the reference's shard-merging converter collapses: save writes
each tensor's global value plus its layout metadata; load places the global
value into whatever sharding the *destination* parameter currently has.
Mesh-shape changes (tp4->tp8, pp on/off, ZeRO on/off) are therefore
reshard-on-load by construction. Layout: one .npy per tensor + index.json —
streamable per-tensor (no giant pickle), async-saveable.
"""
from __future__ import annotations

import json
import os
import re
import threading

import jax
import numpy as np

from ..framework.core import Tensor

_INDEX = "index.json"


def _safe_name(key):
    return re.sub(r"[^0-9A-Za-z_.\-]", "_", key)


def _spec_of(arr):
    s = getattr(arr, "sharding", None)
    spec = getattr(s, "spec", None)
    if spec is None:
        return None
    return [list(p) if isinstance(p, tuple) else p for p in spec]


def save_state_dict(state_dict, path, process_group=None, coordinator_rank=0,
                    async_save=False):
    """Save {name: Tensor} to a checkpoint directory.

    Returns None, or a `threading.Thread` (already started) if async_save —
    join it (or call wait_all()) before relying on the files.
    """
    os.makedirs(path, exist_ok=True)
    entries = {}
    arrays = {}
    for key, val in state_dict.items():
        arr = val._data if isinstance(val, Tensor) else val
        fname = _safe_name(key) + ".npy"
        entries[key] = {
            "file": fname,
            "shape": list(np.shape(arr)),
            "dtype": str(np.asarray(arr).dtype if not hasattr(arr, "dtype")
                         else arr.dtype),
            "spec": _spec_of(arr),
        }
        arrays[fname] = arr

    if async_save:
        # snapshot to host SYNCHRONOUSLY: the live jax.Arrays may be donated
        # or rebound by the very next train step (round-1 ADVICE: the writer
        # thread could read invalidated/torn buffers). Only file I/O is
        # deferred to the thread.
        arrays = {f: np.asarray(a) for f, a in arrays.items()}

    def write():
        for fname, arr in arrays.items():
            np.save(os.path.join(path, fname),
                    np.asarray(arr))  # gathers sharded arrays to host
        with open(os.path.join(path, _INDEX), "w") as f:
            json.dump({"tensors": entries}, f, indent=1)

    if async_save:
        t = threading.Thread(target=write, daemon=True)
        t.start()
        _pending.append(t)
        return t
    write()
    return None


_pending: list = []


def wait_all():
    """Block until every async save has finished."""
    while _pending:
        _pending.pop().join()


def load_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, offload=False):
    """Load a checkpoint INTO the given {name: Tensor} dict, placing each
    value with the destination tensor's current sharding (reshard-on-load).
    Missing keys raise; extra checkpoint keys are ignored."""
    with open(os.path.join(path, _INDEX)) as f:
        index = json.load(f)["tensors"]
    for key, dest in state_dict.items():
        if key not in index:
            raise KeyError(f"checkpoint at {path} has no tensor {key!r}")
        meta = index[key]
        arr = np.load(os.path.join(path, meta["file"]))
        if not isinstance(dest, Tensor):
            continue
        if tuple(arr.shape) != tuple(dest.shape):
            raise ValueError(
                f"{key}: checkpoint shape {arr.shape} != dest {dest.shape} "
                "(shape-changing conversion is not a reshard)")
        sharding = getattr(dest._data, "sharding", None)
        new = np.asarray(arr, dtype=dest._data.dtype)
        if sharding is not None:
            dest._data = jax.device_put(new, sharding)
        else:
            dest._data = jax.device_put(new)
    return state_dict


def load_checkpoint(path):
    """Load to host: {name: np.ndarray} without placement."""
    with open(os.path.join(path, _INDEX)) as f:
        index = json.load(f)["tensors"]
    return {k: np.load(os.path.join(path, m["file"]))
            for k, m in index.items()}
