"""Parallel environment bootstrap and DataParallel.

Reference parity: `paddle.distributed.init_parallel_env`
(`python/paddle/distributed/parallel.py:915`), `paddle.DataParallel`
(`parallel.py:191`) and the C++ `EagerReducer` gradient-fusion machinery
(`fluid/distributed/collective/reducer.cc`).

TPU-first design: DP is a sharding, not a wrapper protocol. The batch is
sharded over the 'dp' mesh axis and parameters are replicated; when jax
differentiates that computation, XLA itself emits the gradient all-reduce
(GSPMD completes shardings through the backward graph), overlapped by the
scheduler. The EagerReducer's 1.3K lines of bucketing/overlap therefore
have no equivalent here — `DataParallel` only annotates inputs and exposes
the reference's API surface.
"""
from __future__ import annotations

import os

from . import env as env_mod
from .shard import sharding_constraint
from ..framework.core import Tensor


def init_parallel_env(dp=-1, mp=1, pp=1, sharding=1, sep=1):
    """Parity: `paddle.distributed.init_parallel_env`. Bootstraps multi-host
    coordination if PADDLE_TRAINERS_NUM/PADDLE_MASTER env are set (the
    launcher contract, `launch/controllers/collective.py:124-220`), then
    builds the global mesh."""
    addr = os.environ.get("PADDLE_MASTER") or None
    nproc = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    pid = os.environ.get("PADDLE_TRAINER_ID")
    if addr and nproc > 1:
        env_mod.init_distributed_runtime(
            coordinator_address=addr, num_processes=nproc,
            process_id=int(pid) if pid is not None else None,
        )
    return env_mod.init_mesh(dp=dp, mp=mp, pp=pp, sharding=sharding, sep=sep)


def get_rank(group=None):
    e = env_mod.get_env()
    return e.rank if e is not None else int(os.environ.get("PADDLE_TRAINER_ID", 0))


def get_world_size(group=None):
    if group is not None:
        from .collective import get_group

        return get_group(group).nranks
    e = env_mod.get_env()
    return e.world_size if e is not None else int(
        os.environ.get("PADDLE_TRAINERS_NUM", 1))


def is_initialized():
    return env_mod.get_env() is not None


class ParallelEnv:
    """Parity shim: `paddle.distributed.ParallelEnv` attribute surface."""

    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def device_id(self):
        return 0

    @property
    def dev_id(self):
        return 0


class DataParallel:
    """Parity: `paddle.DataParallel(layer)` (`parallel.py:191`).

    Wraps a Layer; shards every batch input over the 'dp' mesh axis. Gradient
    synchronization is implicit (see module docstring), so
    `no_sync()` is a no-op context and the reducer knobs are accepted and
    ignored.
    """

    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        self._layers = layers
        env_mod.ensure_env()

    def __getattr__(self, name):
        return getattr(self._layers, name)

    def __call__(self, *args, **kwargs):
        return self.forward(*args, **kwargs)

    def forward(self, *inputs, **kwargs):
        inputs = tuple(
            sharding_constraint(x, "dp") if isinstance(x, Tensor) and x.ndim
            else x
            for x in inputs
        )
        return self._layers(*inputs, **kwargs)

    def no_sync(self):
        import contextlib

        return contextlib.nullcontext()

    # state passthrough so checkpointing sees the inner layer
    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)

    def parameters(self, *a, **k):
        return self._layers.parameters(*a, **k)

    def named_parameters(self, *a, **k):
        return self._layers.named_parameters(*a, **k)

    def train(self):
        return self._layers.train()

    def eval(self):
        return self._layers.eval()


def spawn(func, args=(), nprocs=-1, **options):
    """Parity: `paddle.distributed.spawn`. Single-controller SPMD drives all
    local chips from one process — run the function directly."""
    func(*args)
