"""Group-sharded data parallelism (ZeRO stages 1-3).

Reference parity: `paddle.distributed.sharding.group_sharded_parallel`
(`python/paddle/distributed/sharding/group_sharded.py`) and the stage
implementations `GroupShardedOptimizerStage2`
(`fleet/meta_parallel/sharding/group_sharded_optimizer_stage2.py:53`),
`GroupShardedStage2` (`:46`), `GroupShardedStage3` (`:59`).

TPU-first design: ZeRO is a *layout*, not a protocol. The reference
implements stage 2/3 with rank-owned parameter slices, broadcast/allgather
hooks on every forward, and reduce-scatter hooks on every backward — ~3K
lines of Python choreography. Under GSPMD the same memory scaling is a
sharding spec:

- stage 1/2 ("os"/"os_g"): optimizer moments (and fp32 masters) are placed
  sharded over the 'sharding' mesh axis; XLA partitions the optimizer
  update and the gradient reduce becomes reduce-scatter + sharded update +
  all-gather of the new params, fused into the step program.
- stage 3 ("p_g_os"): parameters themselves are stored sharded; every use
  inside the compiled step triggers an XLA-inserted all-gather (exactly the
  reference's on-demand `_all_gather` in Stage3) and grads come back
  reduce-scattered.

Tensors whose first dim doesn't divide the axis stay replicated — the
reference pads instead (`_param2align`); dropping the pad logic costs a few
small tensors' worth of savings and removes a whole class of bugs.

offload=True places optimizer states + fp32 masters in pinned HOST memory
(sharded layout preserved): the eager step streams them to HBM, updates,
and streams back; the compiled TrainStep stages the same transfers inside
the one XLA program (reference `group_sharded.py:43,61`).
"""
from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec

from .. import env as env_mod


def _shard_axis():
    e = env_mod.ensure_env()
    if e.degree("sharding") > 1:
        return "sharding"
    if e.degree("dp") > 1:
        return "dp"
    return None


def _sharded_put(arr, axis):
    """Add `axis` to the first divisible, currently-unsharded dim of `arr`,
    PRESERVING any existing layout (a TP-sharded weight keeps its 'mp' dims
    — ZeRO composes with tensor parallelism, it doesn't replace it).
    Replicates nothing new: 0-d / indivisible tensors pass through."""
    e = env_mod.ensure_env()
    n = e.degree(axis)
    cur = list(getattr(getattr(arr, "sharding", None), "spec", ()) or ())
    cur += [None] * (arr.ndim - len(cur))
    if any(axis in (p if isinstance(p, tuple) else (p,)) for p in cur
           if p is not None):
        return arr  # already sharded over this axis
    for dim, size in enumerate(arr.shape):
        if cur[dim] is None and size % n == 0 and size > 0:
            parts = list(cur)
            parts[dim] = axis
            return jax.device_put(
                arr, NamedSharding(e.mesh, PartitionSpec(*parts)))
    return arr


def _host_put(arr):
    """Move `arr` to pinned host memory, keeping its (sharded) layout —
    the ZeRO offload placement (reference `group_sharded.py:43,61`
    `offload=True`: optimizer states + fp32 masters live on CPU). On
    backends without a "pinned_host" space (the CPU test backend only
    addresses "unpinned_host") offload degrades to a no-op: state stays
    in default memory, which IS host memory there."""
    s = getattr(arr, "sharding", None)
    if s is None or not hasattr(s, "with_memory_kind"):
        return arr
    try:
        return jax.device_put(arr, s.with_memory_kind("pinned_host"))
    except ValueError:
        return arr


def _dev_put(arr):
    # stage back device-ward ONLY from the offload placement; comparing
    # != "device" would misfire on the CPU backend's default
    # "unpinned_host" kind (same trap as jit/train_step host_shardings)
    s = getattr(arr, "sharding", None)
    if s is None or getattr(s, "memory_kind", None) != "pinned_host":
        return arr
    return jax.device_put(arr, s.with_memory_kind("device"))


def _wrap_accessors_for_offload(optimizer):
    """Eager offload: bracket ONE param's state at a time through the
    optimizer's state accessors — _get stages host->HBM just before the
    update consumes it, _set parks the new state back in host memory, so
    peak HBM holds a single param's moments+master rather than the whole
    optimizer (mixed host/device operands are a hard error in XLA, which
    is why the staging must bracket the compute). Mirrors the reference
    offload's per-param host-resident state."""

    def get_accum(key):
        st = Optimizer_get_accum(optimizer, key)
        if st is None:
            return None
        return {k: _dev_put(v) for k, v in st.items()}

    def set_accum(key, st):
        Optimizer_set_accum(optimizer, key,
                            {k: _host_put(v) for k, v in st.items()})

    def get_master(key):
        m = Optimizer_get_master(optimizer, key)
        return None if m is None else _dev_put(m)

    def set_master(key, m):
        Optimizer_set_master(optimizer, key, _host_put(m))

    from ...optimizer.optimizer import Optimizer

    Optimizer_get_accum = Optimizer._get_accum
    Optimizer_set_accum = Optimizer._set_accum
    Optimizer_get_master = Optimizer._get_master
    Optimizer_set_master = Optimizer._set_master
    optimizer._get_accum = get_accum
    optimizer._set_accum = set_accum
    optimizer._get_master = get_master
    optimizer._set_master = set_master


def group_sharded_parallel(model, optimizer, level="p_g_os", scaler=None,
                           group=None, offload=False, sync_buffers=False,
                           buffer_max_size=2**23, segment_size=2**20,
                           sync_comm=False, dp_group=None,
                           exclude_layer=None):
    """Parity: `group_sharded.py` `group_sharded_parallel(model, optimizer,
    level)`. Returns (model, optimizer, scaler) like the reference."""
    if level not in ("os", "os_g", "p_g_os"):
        raise ValueError("level must be os, os_g or p_g_os")
    axis = _shard_axis()
    if axis is None:
        return model, optimizer, scaler

    dev_place = lambda arr: _sharded_put(arr, axis)  # noqa: E731
    if offload:
        host_place = lambda arr: _host_put(_sharded_put(arr, axis))  # noqa: E731
        # in-step creations stay in device memory (they are consumed
        # immediately); the accessors park state in host memory after
        # each per-param update, and _initial_state_placement host-places
        # state created OUTSIDE a step (compiled TrainStep._ensure_state)
        place = host_place
        optimizer._state_placement = dev_place
        optimizer._initial_state_placement = host_place
        _wrap_accessors_for_offload(optimizer)
        optimizer._offload_state = True
    else:
        place = dev_place
        optimizer._state_placement = place

    # re-place any state that already exists
    for key, st in list(optimizer._accumulators.items()):
        optimizer._accumulators[key] = {
            k: place(v) for k, v in st.items()}
    for key, m in list(optimizer._master_weights.items()):
        optimizer._master_weights[key] = place(m)

    if level == "p_g_os":
        for p in model.parameters():
            if not p.stop_gradient:
                p._data = _sharded_put(p._data, axis)
    return model, optimizer, scaler


def save_group_sharded_model(model, output, optimizer=None):
    """Parity: `group_sharded.py` save_group_sharded_model. Global arrays
    make this trivial: state_dicts already hold full tensors."""
    import os

    from ...framework.io import save

    os.makedirs(output, exist_ok=True)
    save(model.state_dict(), os.path.join(output, "model.pdparams"))
    if optimizer is not None:
        save(optimizer.state_dict() if hasattr(optimizer, "state_dict")
             else {}, os.path.join(output, "model.pdopt"))
