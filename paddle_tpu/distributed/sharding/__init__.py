from .group_sharded import (  # noqa: F401
    group_sharded_parallel, save_group_sharded_model,
)

__all__ = ["group_sharded_parallel", "save_group_sharded_model"]
