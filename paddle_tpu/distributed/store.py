"""TCPStore: native KV rendezvous store.

Reference parity: `paddle/phi/core/distributed/store/tcp_store.{h,cc}` (the
C++ master/worker bootstrap store) and its python binding used by
`init_parallel_env` (`parallel.py:858` `_start_kv_server`).

The server/client are C++ (`native/tcp_store.cpp`), compiled on first use
via `paddle_tpu.utils.cpp_extension.load` (same mechanism users get for
custom ops) and driven through ctypes. jax's own coordination service
bootstraps the XLA runtime; this store carries framework-level rendezvous:
elastic membership, user barriers, launcher coordination.
"""
from __future__ import annotations

import ctypes
import os
import threading
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "tcp_store.cpp")

_lib = None


def _load():
    global _lib
    if _lib is None:
        from ..utils.cpp_extension import load

        _lib = load("paddle_tpu_tcp_store", [_SRC])
        _lib.tcp_store_server_start.restype = ctypes.c_void_p
        _lib.tcp_store_server_start.argtypes = [ctypes.c_int]
        _lib.tcp_store_server_port.argtypes = [ctypes.c_void_p]
        _lib.tcp_store_server_port.restype = ctypes.c_int
        _lib.tcp_store_server_stop.argtypes = [ctypes.c_void_p]
        _lib.tcp_store_client_connect.restype = ctypes.c_void_p
        _lib.tcp_store_client_connect.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
        _lib.tcp_store_client_close.argtypes = [ctypes.c_void_p]
        _lib.tcp_store_set.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int]
        _lib.tcp_store_set.restype = ctypes.c_int
        _lib.tcp_store_get.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int]
        _lib.tcp_store_get.restype = ctypes.c_int
        _lib.tcp_store_add.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_longlong]
        _lib.tcp_store_add.restype = ctypes.c_longlong
        _lib.tcp_store_wait.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p,
            ctypes.c_int]
        _lib.tcp_store_wait.restype = ctypes.c_int
        _lib.tcp_store_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        _lib.tcp_store_delete.restype = ctypes.c_int
    return _lib


class TCPStore:
    """Parity: `paddle.distributed.TCPStore(host, port, world_size,
    is_master, timeout)` — master also runs the in-process C++ server."""

    def __init__(self, host="127.0.0.1", port=0, is_master=False,
                 world_size=1, timeout=900):
        lib = _load()
        self._lib = lib
        self._server = None
        self._timeout_ms = int(timeout * 1000)
        # One socket per client: request/response frames must not interleave
        # when several threads (e.g. an elastic heartbeat) share the store.
        self._lock = threading.Lock()
        if is_master:
            self._server = lib.tcp_store_server_start(port)
            if not self._server:
                raise RuntimeError(f"TCPStore: cannot listen on port {port}")
            port = lib.tcp_store_server_port(self._server)
        self.host = host
        self.port = port
        self._client = lib.tcp_store_client_connect(
            host.encode(), port, self._timeout_ms)
        if not self._client:
            self._shutdown_server()
            raise RuntimeError(f"TCPStore: cannot connect {host}:{port}")

    # -- KV API (paddle/torch-shaped) --
    def set(self, key, value):
        data = value if isinstance(value, bytes) else str(value).encode()
        with self._lock:
            rc = self._lib.tcp_store_set(self._client, key.encode(), data,
                                         len(data))
        if rc < 0:
            raise RuntimeError("TCPStore.set failed")

    def get(self, key):
        buf = ctypes.create_string_buffer(1 << 20)
        with self._lock:
            n = self._lib.tcp_store_get(self._client, key.encode(), buf,
                                        len(buf))
        if n == -1:
            raise KeyError(key)
        if n < 0:
            raise RuntimeError("TCPStore.get failed")
        return buf.raw[:n]

    def add(self, key, amount=1):
        with self._lock:
            res = self._lib.tcp_store_add(self._client, key.encode(), amount)
        if res < 0 and amount >= 0:
            raise RuntimeError("TCPStore.add failed")
        return int(res)

    def wait(self, keys, timeout=None):
        if isinstance(keys, str):
            keys = [keys]
        to = int((timeout or self._timeout_ms / 1000) * 1000)
        buf = ctypes.create_string_buffer(1 << 20)
        for k in keys:
            # poll in short slices so the lock is released between probes —
            # a blocking hold would starve other threads (e.g. the elastic
            # heartbeat) for the whole wait timeout
            deadline = time.monotonic() + to / 1000.0
            while True:
                with self._lock:
                    n = self._lib.tcp_store_wait(self._client, k.encode(),
                                                 100, buf, len(buf))
                if n >= 0:
                    break
                if n < -1:
                    raise RuntimeError("TCPStore.wait failed")
                if time.monotonic() >= deadline:
                    raise TimeoutError(f"TCPStore.wait timed out on {k!r}")

    def delete_key(self, key):
        with self._lock:
            return self._lib.tcp_store_delete(self._client,
                                              key.encode()) >= 0

    def _shutdown_server(self):
        if self._server:
            self._lib.tcp_store_server_stop(self._server)
            self._server = None

    def __del__(self):
        try:
            if getattr(self, "_client", None):
                self._lib.tcp_store_client_close(self._client)
                self._client = None
            self._shutdown_server()
        except Exception:
            pass
