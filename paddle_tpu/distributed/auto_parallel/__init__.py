"""Semi-automatic parallelism (parity: `python/paddle/distributed/auto_parallel/`).

Reference parity: `shard_tensor` annotations (`interface.py:28`), ProcessMesh,
and the `Engine` train driver (`static/engine.py:55` — fit/evaluate/predict
over an annotated model). The reference's Completer/Partitioner/Resharder
compiler stages (`completion.py`, `partitioner.py`, `reshard.py`) ARE
XLA's GSPMD propagation (SURVEY §2.6 "TPU build"), so this module is thin:
mesh description + annotations + a fit driver over the whole-step compiled
TrainStep.
"""
from __future__ import annotations

import numpy as np

from .. import env as env_mod
from ..shard import shard_tensor as _shard_tensor_spec
from ...framework.core import Tensor

__all__ = ["ProcessMesh", "shard_tensor", "shard_op", "Shard", "Replicate",
           "Partial", "Engine", "Strategy", "to_static"]


class Shard:
    """Placement: shard along tensor dim `dim` (parity: dist.Shard)."""

    def __init__(self, dim):
        self.dim = dim

    def __repr__(self):
        return f"Shard(dim={self.dim})"


class Replicate:
    def __repr__(self):
        return "Replicate()"


class Partial:
    def __init__(self, reduce_type="sum"):
        self.reduce_type = reduce_type


class ProcessMesh:
    """Parity: `paddle.distributed.ProcessMesh(mesh, dim_names)`. Maps to
    (a view of) the global device mesh: dim_names must be mesh axis names."""

    def __init__(self, mesh=None, dim_names=None, shape=None,
                 process_ids=None):
        if shape is None and mesh is not None:
            shape = np.asarray(mesh).shape
        self.shape = list(shape) if shape is not None else []
        self.dim_names = list(dim_names) if dim_names else \
            [f"d{i}" for i in range(len(self.shape))]
        self.process_ids = process_ids

    def __getitem__(self, idx):
        return ProcessMesh(shape=self.shape[1:], dim_names=self.dim_names[1:])

    @property
    def ndim(self):
        return len(self.shape)


_DIM_ALIAS = {"x": "dp", "y": "mp", "z": "pp", "dp": "dp", "mp": "mp",
              "tp": "mp", "pp": "pp", "sharding": "sharding", "sep": "sep"}


def shard_tensor(x, mesh=None, placements=None, **kwargs):
    """Parity: `dist.shard_tensor(x, process_mesh, placements)` with
    Shard/Replicate placement objects; maps mesh dim names onto the global
    mesh axes."""
    t = x if isinstance(x, Tensor) else Tensor(x)
    if placements is None:
        return t
    ndim = t.ndim
    parts = [None] * ndim
    dim_names = mesh.dim_names if isinstance(mesh, ProcessMesh) else []
    for mesh_dim, p in enumerate(placements):
        if isinstance(p, Shard):
            name = dim_names[mesh_dim] if mesh_dim < len(dim_names) else "dp"
            parts[p.dim] = _DIM_ALIAS.get(name, name)
    return _shard_tensor_spec(t, spec=tuple(parts))


def shard_op(op_fn, mesh=None, in_placements=None, out_placements=None):
    """Parity: `dist.shard_op` — annotations on an op call; GSPMD derives
    the rest, so this is a passthrough wrapper."""

    def wrapped(*args, **kwargs):
        return op_fn(*args, **kwargs)

    return wrapped


class Strategy:
    """Parity: `auto_parallel.Strategy` (strategy.py + constants.py)."""

    class _Section(dict):
        def __getattr__(self, k):
            return self.get(k)

        def __setattr__(self, k, v):
            self[k] = v

    def __init__(self, config=None):
        self.amp = Strategy._Section(enable=False, dtype="float16", level="o1")
        self.recompute = Strategy._Section(enable=False)
        self.sharding = Strategy._Section(enable=False, degree=1, stage=1)
        self.pipeline = Strategy._Section(enable=False, schedule_mode="1F1B",
                                          accumulate_steps=1)
        self.gradient_merge = Strategy._Section(enable=False, k_steps=1)
        self.fused_passes = Strategy._Section(enable=False)


class Engine:
    """Parity: `auto_parallel.Engine(model, loss, optimizer, metrics,
    strategy)` (`static/engine.py:55`): fit/evaluate/predict drive the
    GSPMD-compiled train step; dist_saver-style save/load via
    `distributed.checkpoint`."""

    def __init__(self, model=None, loss=None, optimizer=None, metrics=None,
                 cluster=None, strategy=None):
        self._model = model
        self._loss = loss
        self._optimizer = optimizer
        self._metrics = metrics or []
        self._strategy = strategy or Strategy()
        env_mod.ensure_env()
        self._train_step = None

    def _ensure_step(self):
        if self._train_step is None:
            from ...jit.train_step import TrainStep

            def loss_fn(model, *batch):
                n_in = max(len(batch) - 1, 1)
                outs = model(*batch[:n_in])
                if self._loss is None:
                    return outs
                loss = self._loss(outs, *batch[n_in:])
                return loss.mean() if loss.ndim else loss

            self._train_step = TrainStep(self._model, self._optimizer,
                                         loss_fn)
        return self._train_step

    def fit(self, train_data, train_sample_split=None, batch_size=1,
            epochs=1, steps_per_epoch=None, log_freq=10, valid_data=None,
            **kwargs):
        from ...io.reader import DataLoader

        step_fn = self._ensure_step()
        loader = train_data if isinstance(train_data, DataLoader) else \
            DataLoader(train_data, batch_size=batch_size, shuffle=True)
        history = []
        for epoch in range(epochs):
            for i, batch in enumerate(loader):
                batch = batch if isinstance(batch, (list, tuple)) else [batch]
                loss = step_fn(*batch)
                if i % log_freq == 0:
                    history.append(float(loss.numpy()))
                if steps_per_epoch and i + 1 >= steps_per_epoch:
                    break
        return history

    def evaluate(self, valid_data, batch_size=1, steps=None, **kwargs):
        from ...autograd.tape import no_grad
        from ...io.reader import DataLoader

        loader = valid_data if isinstance(valid_data, DataLoader) else \
            DataLoader(valid_data, batch_size=batch_size)
        losses = []
        with no_grad():
            for i, batch in enumerate(loader):
                batch = batch if isinstance(batch, (list, tuple)) else [batch]
                n_in = max(len(batch) - 1, 1)
                outs = self._model(*batch[:n_in])
                if self._loss is not None:
                    loss = self._loss(outs, *batch[n_in:])
                    losses.append(float(np.asarray(loss.numpy()).mean()))
                if steps and i + 1 >= steps:
                    break
        return {"loss": float(np.mean(losses))} if losses else {}

    def predict(self, test_data, batch_size=1, steps=None, **kwargs):
        from ...autograd.tape import no_grad
        from ...io.reader import DataLoader

        loader = test_data if isinstance(test_data, DataLoader) else \
            DataLoader(test_data, batch_size=batch_size)
        outs = []
        with no_grad():
            for i, batch in enumerate(loader):
                batch = batch if isinstance(batch, (list, tuple)) else [batch]
                outs.append(self._model(*batch[:max(len(batch) - 1, 1)]))
                if steps and i + 1 >= steps:
                    break
        return outs

    def save(self, path, training=True):
        from ..checkpoint import save_state_dict

        save_state_dict(dict(self._model.named_parameters()), path)

    def load(self, path, strict=True, load_optimizer=True):
        from ..checkpoint import load_state_dict

        load_state_dict(dict(self._model.named_parameters()), path)


def to_static(layer, loader=None, loss=None, optimizer=None, strategy=None):
    """Parity: `dist.to_static` — returns an Engine-like compiled wrapper."""
    return Engine(model=layer, loss=loss, optimizer=optimizer,
                  strategy=strategy)
