"""Auto-tuner: black-box search over hybrid-parallel configurations.

Reference parity: `python/paddle/distributed/auto_tuner/{tuner,search,
prune}.py` — enumerate (dp, mp, pp, sharding, micro-batch, recompute)
candidates for a world size, prune invalid/doomed ones, launch trials,
record metrics, return the best config.

TPU-first design: a trial is not a multi-process relaunch (the reference
re-execs `paddle.distributed.launch` per candidate) but one in-process
re-jit of the whole train step over a re-factorized `jax.sharding.Mesh` —
GSPMD makes re-partitioning a compile-time decision, so candidates cost
seconds, not process round-trips. The measurement callback is pluggable so
tests (and CPU hosts) can search synthetic cost surfaces.

Pruning rules mirror `prune.py`:
- product(dp, mp, pp, sharding) must equal the device count
- mp must divide attention heads and hidden size
- pp must divide layer count; micro-batches must divide the global batch
- optional HBM estimate against per-chip capacity (prune_by_memory)
"""
from __future__ import annotations

import itertools
import json
import os
import time

__all__ = ["AutoTuner", "generate_candidates", "default_prunes",
           "estimate_memory_bytes"]


def _divisors(n):
    return [d for d in range(1, n + 1) if n % d == 0]


def generate_candidates(world_size, tuner_cfg=None):
    """Cartesian candidate list (reference `search.py` GridSearch).

    tuner_cfg keys (all optional): dp_degree/mp_degree/pp_degree/
    sharding_degree ("auto" or list), micro_batch_size (list),
    use_recompute (list of bool), global_batch_size.
    """
    cfg = dict(tuner_cfg or {})

    def axis(name):
        v = cfg.get(name, "auto")
        return _divisors(world_size) if v in (None, "auto") else [
            int(x) for x in (v if isinstance(v, (list, tuple)) else [v])
        ]

    gbs = int(cfg.get("global_batch_size", 0) or 0)
    micro = cfg.get("micro_batch_size", "auto")
    if micro in (None, "auto"):
        micros = _divisors(gbs) if gbs else [1]
    else:
        micros = [int(x) for x in (
            micro if isinstance(micro, (list, tuple)) else [micro])]
    recomputes = cfg.get("use_recompute", [False, True])
    if not isinstance(recomputes, (list, tuple)):
        recomputes = [bool(recomputes)]

    out = []
    for dp, mp, pp, sh, mb, rc in itertools.product(
            axis("dp_degree"), axis("mp_degree"), axis("pp_degree"),
            axis("sharding_degree"), micros, recomputes):
        out.append({
            "dp_degree": dp, "mp_degree": mp, "pp_degree": pp,
            "sharding_degree": sh, "micro_batch_size": mb,
            "use_recompute": bool(rc),
        })
    return out


def estimate_memory_bytes(candidate, model_cfg):
    """Coarse per-chip HBM estimate (reference `prune.py` memory prune):
    params sharded over (mp, pp, sharding), optimizer x3 (fp32 master +
    two Adam moments), activations ~ micro_batch * seq * hidden * layers /
    (mp * pp), halved under recompute."""
    h = model_cfg.get("hidden_size", 0)
    layers = model_cfg.get("num_hidden_layers", 0)
    vocab = model_cfg.get("vocab_size", 0)
    seq = model_cfg.get("seq_length", 1024)
    ffn = model_cfg.get("intermediate_size", 4 * h)
    n_params = layers * (4 * h * h + 3 * h * ffn) + 2 * vocab * h
    mp = candidate["mp_degree"]
    pp = candidate["pp_degree"]
    sh = max(candidate["sharding_degree"], 1)
    param_bytes = 2 * n_params / (mp * pp)            # bf16 shards
    opt_bytes = 12 * n_params / (mp * pp * sh)        # ZeRO over sharding
    act = candidate["micro_batch_size"] * seq * h * layers * 16 / (mp * pp)
    if candidate["use_recompute"]:
        act /= 4
    return param_bytes + opt_bytes + act


def default_prunes(world_size, model_cfg=None, hbm_bytes=None):
    """The rule set from `prune.py`, as composable predicates
    (candidate -> reason-string-or-None)."""
    model_cfg = model_cfg or {}

    def prune_world(c):
        prod = (c["dp_degree"] * c["mp_degree"] * c["pp_degree"]
                * c["sharding_degree"])
        if prod != world_size:
            return f"dp*mp*pp*sharding={prod} != world_size={world_size}"
        return None

    def prune_mp(c):
        heads = model_cfg.get("num_attention_heads")
        hidden = model_cfg.get("hidden_size")
        if heads and heads % c["mp_degree"]:
            return f"mp={c['mp_degree']} does not divide heads={heads}"
        if hidden and hidden % c["mp_degree"]:
            return f"mp={c['mp_degree']} does not divide hidden={hidden}"
        return None

    def prune_pp(c):
        layers = model_cfg.get("num_hidden_layers")
        if layers and layers % c["pp_degree"]:
            return f"pp={c['pp_degree']} does not divide layers={layers}"
        return None

    def prune_batch(c):
        gbs = model_cfg.get("global_batch_size")
        if not gbs:
            return None
        local = gbs // c["dp_degree"] if gbs % c["dp_degree"] == 0 else None
        if local is None:
            return f"dp={c['dp_degree']} does not divide batch={gbs}"
        if local % c["micro_batch_size"]:
            return (f"micro_batch={c['micro_batch_size']} does not divide "
                    f"local batch={local}")
        return None

    def prune_memory(c):
        if not hbm_bytes:
            return None
        est = estimate_memory_bytes(c, model_cfg)
        if est > hbm_bytes:
            return f"estimated {est/2**30:.1f}GiB > HBM {hbm_bytes/2**30:.1f}GiB"
        return None

    return [prune_world, prune_mp, prune_pp, prune_batch, prune_memory]


class AutoTuner:
    """Parity: `tuner.py` AutoTuner.

    ``run_fn(candidate) -> float`` measures one candidate (higher is
    better, e.g. tokens/s); exceptions or non-finite results mark the
    candidate failed (the reference parses launch logs for OOM the same
    way). ``history_path`` persists every trial as JSON lines.
    """

    def __init__(self, world_size, tuner_cfg=None, model_cfg=None,
                 run_fn=None, hbm_bytes=None, history_path=None,
                 max_trials=None, time_budget_s=None):
        self.world_size = world_size
        self.tuner_cfg = dict(tuner_cfg or {})
        self.model_cfg = dict(model_cfg or {})
        if "global_batch_size" in self.tuner_cfg:
            self.model_cfg.setdefault(
                "global_batch_size", self.tuner_cfg["global_batch_size"])
        self.run_fn = run_fn
        self.prunes = default_prunes(world_size, self.model_cfg, hbm_bytes)
        self.history: list = []
        self.history_path = history_path
        self.max_trials = max_trials
        self.time_budget_s = time_budget_s
        self._pruned: list = []

    def candidates(self):
        cands, seen = [], set()
        for c in generate_candidates(self.world_size, self.tuner_cfg):
            key = tuple(sorted(c.items()))
            if key in seen:
                continue
            seen.add(key)
            reason = next(
                (r for r in (p(c) for p in self.prunes) if r), None)
            if reason:
                self._pruned.append({"candidate": c, "reason": reason})
            else:
                cands.append(c)
        # memory-safest first (the reference sorts candidates so OOM-prone
        # configs run last): more sharding/recompute first, then larger mp
        cands.sort(key=lambda c: (
            -c["use_recompute"], -c["sharding_degree"], -c["mp_degree"],
            c["micro_batch_size"]))
        return cands

    def tune(self):
        """Run trials; returns (best_candidate, best_metric)."""
        if self.run_fn is None:
            raise ValueError("AutoTuner needs run_fn to measure candidates")
        t0 = time.time()
        best, best_metric = None, float("-inf")
        for i, cand in enumerate(self.candidates()):
            if self.max_trials is not None and i >= self.max_trials:
                break
            if (self.time_budget_s is not None
                    and time.time() - t0 > self.time_budget_s):
                break
            rec = {"candidate": cand, "ok": False, "metric": None}
            try:
                t1 = time.time()
                metric = float(self.run_fn(cand))
                rec["elapsed_s"] = round(time.time() - t1, 3)
                if metric == metric and metric not in (float("inf"),):
                    rec["ok"] = True
                    rec["metric"] = metric
                    if metric > best_metric:
                        best, best_metric = cand, metric
            except Exception as e:  # failed trial = pruned at runtime
                rec["error"] = f"{type(e).__name__}: {e}"[:300]
            self.history.append(rec)
            self._persist()
        return best, best_metric

    def _persist(self):
        if not self.history_path:
            return
        d = os.path.dirname(self.history_path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(self.history_path, "w") as f:
            json.dump({"history": self.history, "pruned": self._pruned}, f,
                      indent=1)

    @property
    def pruned(self):
        return list(self._pruned)
