"""Process groups and collective communication.

Reference parity: the `ProcessGroup` abstraction and its per-collective
Python API — `paddle/fluid/distributed/collective/process_group.h:53`,
`python/paddle/distributed/communication/{all_reduce,all_gather,...}.py`,
group management `python/paddle/distributed/collective.py:178` (`new_group`).

TPU-first design: a "group" is a set of mesh axes, not an NCCL ring. Eager
collectives are tiny compiled shard_map programs over those axes (SURVEY §5.8:
"Eager-mode collectives = tiny compiled programs"); collectives that appear
inside a traced program (jit / shard_map) lower directly to XLA collective
HLOs (`psum`, `all_gather`, `ppermute`, …) and ride ICI. There are no
streams, events, or ncclUniqueId bootstrap — XLA owns ordering, and the mesh
is the membership.

Semantics note (single-controller): an eager Tensor is a *global* array. A
collective over a group reads the tensor's per-shard view along the group's
axes: `all_reduce` on an axis-sharded tensor sums the shards (replicating the
result); on a replicated tensor each participant holds the same value, so the
sum is value × group size — identical to what N identical NCCL ranks would
produce.
"""
from __future__ import annotations

import functools
import sys
import time

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from ..monitor import _register as _monitor_register

# Telemetry slots (see paddle_tpu.monitor): when wired, each collective
# reports one call + payload bytes, and `_spans` (monitor/spans.py) gets
# one `dispatch` span per eager collective's host-side enqueue. In-trace
# collectives count once per *trace*, not per execution — XLA owns the
# executed schedule.
_monitor = None
_spans = None


def _mon_collective(name, arr, axes=()):
    m = _monitor
    if m is not None:
        # axes = the group's mesh axes: the monitor splits the byte
        # counter per axis (collective/bytes/<axis>) so the planner's
        # per-axis cost model has a measured twin (docs/AUTOSHARD.md)
        m.on_collective(name, int(getattr(arr, "nbytes", 0) or 0),
                        axes=axes)


def _traced_collective(fn):
    """Span-record the collective's host-side wall time (program-cache
    lookup + dispatch enqueue; compile on a fresh shape). Off, the wrapper
    costs one ``is None`` check — the counter path (`_mon_collective`)
    stays where it is, past the trivial early returns."""
    name = f"collective/{fn.__name__}"

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        sp = _spans
        if sp is None:
            return fn(*args, **kwargs)
        t0 = time.perf_counter()
        try:
            return fn(*args, **kwargs)
        finally:
            sp.record(name, "dispatch", t0)

    return wrapper


def shard_map(fn, mesh, in_specs, out_specs, check_rep=False):
    from ..framework.jax_compat import shard_map as _shard_map

    return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_vma=check_rep)

from . import env as env_mod
from ..framework.core import Tensor
from ..ops.dispatch import apply


class ReduceOp:
    """Parity: `paddle.distributed.ReduceOp`."""

    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    """A communicator: one or more mesh axes.

    Parity: the `Group` returned by `paddle.distributed.new_group`
    (`collective.py:178`). `axes` is the mesh-axis tuple the collectives
    run over; `nranks` is the product of those axis sizes.
    """

    def __init__(self, axes, name=None):
        self.axes = tuple(axes)
        self.name = name or "_".join(self.axes)

    @property
    def nranks(self) -> int:
        e = env_mod.ensure_env()
        n = 1
        for a in self.axes:
            n *= e.degree(a)
        return n

    world_size = nranks

    @property
    def rank(self) -> int:
        """Single-controller semantics: the python process is not one rank
        of the group — it drives ALL shards of the mesh at once, so "this
        process's rank" is 0 by convention (the reference's per-process
        rank does not map onto GSPMD). Code that branches per-rank should
        instead shard by mesh axis; see `get_group_rank`."""
        if self.nranks <= 0:
            return -1
        return 0

    def get_group_rank(self, rank):
        """Identity under the single-controller model: global rank == group
        rank because there is exactly one controller. Reference code that
        uses this to pick a subset of data must use sharding instead —
        raise loudly if the caller asks for a rank this controller does
        not own (anything other than its own world)."""
        if not isinstance(rank, int) or rank < 0 or rank >= max(self.nranks, 1):
            raise ValueError(
                f"rank {rank} out of range for single-controller group "
                f"with {self.nranks} shards; per-rank branching does not "
                f"exist under GSPMD — express the split as a sharding")
        return rank

    def __repr__(self):
        return f"Group(axes={self.axes}, nranks={self.nranks})"


_WORLD: Group | None = None


def _world_group() -> Group:
    global _WORLD
    if _WORLD is None:
        env_mod.ensure_env()
        _WORLD = Group(env_mod.AXIS_ORDER, name="world")
    return _WORLD


def get_group(group=None) -> Group:
    if group is None:
        return _world_group()
    if isinstance(group, Group):
        return group
    if isinstance(group, str):
        return Group((group,))
    return Group(tuple(group))


def new_group(ranks=None, backend=None, timeout=None, axes=None, name=None):
    """Parity: `paddle.distributed.new_group`. In SPMD the membership is a
    mesh-axis set; rank lists (a multi-controller concept) are accepted when
    they exactly cover one axis of the current mesh, otherwise axes must be
    given explicitly."""
    if axes is not None:
        return Group(axes if isinstance(axes, (tuple, list)) else (axes,), name)
    e = env_mod.ensure_env()
    if ranks is None or len(ranks) == e.world_size:
        return _world_group()
    matching = [ax for ax in env_mod.AXIS_ORDER
                if e.degree(ax) == len(ranks)]
    if len(matching) == 1:
        return Group((matching[0],), name)
    raise ValueError(
        f"cannot map ranks {ranks} unambiguously onto mesh axes "
        f"{e.degrees} (matching axes: {matching}); pass axes=... explicitly"
    )


# ---------------------------------------------------------------------------
# in-trace detection: inside shard_map the group's axes are bound axis names
# ---------------------------------------------------------------------------

def _axes_in_scope(axes) -> bool:
    try:
        for a in axes:
            jax.lax.axis_index(a)  # raises NameError outside shard_map
        return True
    except (NameError, Exception):
        return False


# ---------------------------------------------------------------------------
# eager collectives: cached compiled shard_map programs
# ---------------------------------------------------------------------------

def _spec_on(ndim, axes, dim):
    parts = [None] * ndim
    parts[dim] = axes if len(axes) > 1 else axes[0]
    return PartitionSpec(*parts)


@functools.lru_cache(maxsize=512)
def _reduce_program(mesh, axes, op, shape, dtype, in_spec_key):
    in_spec = PartitionSpec(*in_spec_key)
    red = {
        "sum": jax.lax.psum, "avg": jax.lax.pmean,
        "max": jax.lax.pmax, "min": jax.lax.pmin,
        "prod": _prod_reduce,
    }[op]
    ax = axes if len(axes) > 1 else axes[0]

    # result replicated over the reduced axes
    out_parts = [p if not _mentions(p, axes) else None for p in in_spec_key]
    out_spec = PartitionSpec(*out_parts)

    def shard_fn(x):
        return red(x, ax)

    fn = shard_map(shard_fn, mesh=mesh, in_specs=(in_spec,),
                   out_specs=out_spec, check_rep=False)
    return jax.jit(fn)


def _mentions(part, axes):
    if part is None:
        return False
    if isinstance(part, (tuple, list)):
        return any(p in axes for p in part)
    return part in axes


def _current_spec(arr) -> tuple:
    s = getattr(arr, "sharding", None)
    if isinstance(s, NamedSharding):
        spec = tuple(s.spec)
        spec = spec + (None,) * (arr.ndim - len(spec))
        return spec
    return (None,) * arr.ndim


def _on_mesh(arr):
    """Place an off-mesh (single-device) array onto the mesh replicated;
    mesh-resident arrays pass through with their layout."""
    e = env_mod.ensure_env()
    s = getattr(arr, "sharding", None)
    if isinstance(s, NamedSharding) and s.mesh.shape == e.mesh.shape:
        return arr
    return jax.device_put(arr, NamedSharding(e.mesh, PartitionSpec()))


def _prod_reduce(x, ax):
    # jax.lax has no pprod: |x| in log space + sign parity + zero sweep
    mag = jnp.exp(jax.lax.psum(jnp.log(jnp.maximum(jnp.abs(x), 1e-38)), ax))
    n_neg = jax.lax.psum((x < 0).astype(jnp.int32), ax)
    sign = 1.0 - 2.0 * (n_neg % 2).astype(jnp.float32)
    any_zero = jax.lax.pmin(jnp.abs(x), ax) == 0
    return jnp.where(any_zero, 0.0, mag * sign).astype(x.dtype)


@_traced_collective
def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """Parity: `paddle.distributed.all_reduce`. In-place on the Tensor shell
    (rebinds the buffer), also returns it."""
    g = get_group(group)
    if g.nranks == 1:
        return tensor
    t = tensor if isinstance(tensor, Tensor) else Tensor(tensor)
    _mon_collective("all_reduce", t._data, g.axes)
    if _axes_in_scope(g.axes):
        ax = g.axes if len(g.axes) > 1 else g.axes[0]
        red = {"sum": jax.lax.psum, "avg": jax.lax.pmean,
               "max": jax.lax.pmax, "min": jax.lax.pmin,
               "prod": _prod_reduce}[op]
        out = apply(f"all_reduce_{op}", lambda x: red(x, ax), (t,))
        t._replace_(out._data)
        t._grad_node = out._grad_node
        t._out_index = out._out_index
        t.stop_gradient = out.stop_gradient and t.stop_gradient
        return t
    arr = _on_mesh(t._data)
    prog = _reduce_program(env_mod.get_env().mesh, g.axes, op,
                           tuple(arr.shape), str(arr.dtype),
                           _current_spec(arr))
    t._replace_(prog(arr))
    return t


@functools.lru_cache(maxsize=512)
def _gather_program(mesh, axes, dim, shape, dtype, in_spec_key):
    in_spec = PartitionSpec(*in_spec_key)
    ax = axes if len(axes) > 1 else axes[0]
    out_parts = [p if not _mentions(p, axes) else None for p in in_spec_key]
    out_spec = PartitionSpec(*out_parts)

    def shard_fn(x):
        return jax.lax.all_gather(x, ax, axis=dim, tiled=True)

    fn = shard_map(shard_fn, mesh=mesh, in_specs=(in_spec,),
                   out_specs=out_spec, check_rep=False)
    return jax.jit(fn)


@_traced_collective
def all_gather(tensor_or_list, tensor=None, group=None, sync_op=True, axis=0):
    """Parity: `paddle.distributed.all_gather(tensor_list, tensor)`. Also
    callable functional-style: `all_gather(tensor)` returns the gathered
    Tensor (concatenated along ``axis``)."""
    g = get_group(group)
    out_list = None
    if isinstance(tensor_or_list, list) and tensor is not None:
        out_list, x = tensor_or_list, tensor
    else:
        x = tensor_or_list
    t = x if isinstance(x, Tensor) else Tensor(x)
    if g.nranks > 1:
        _mon_collective("all_gather", t._data, g.axes)
    if g.nranks == 1:
        gathered = t
    elif _axes_in_scope(g.axes):
        ax = g.axes if len(g.axes) > 1 else g.axes[0]
        gathered = apply(
            "all_gather",
            lambda a: jax.lax.all_gather(a, ax, axis=axis, tiled=True),
            (t,),
        )
    else:
        arr = _on_mesh(t._data)
        prog = _gather_program(env_mod.get_env().mesh, g.axes, axis,
                               tuple(arr.shape),
                               str(arr.dtype), _current_spec(arr))
        gathered = Tensor(prog(arr))
    if out_list is not None:
        from ..tensor.manipulation import split as _split

        out_list.extend(_split(gathered, g.nranks, axis=axis))
        return out_list
    return gathered


@_traced_collective
def broadcast(tensor, src=0, group=None, sync_op=True):
    """Parity: `paddle.distributed.broadcast`. SPMD: a global array is
    already consistent across the mesh; replicate it over the group's axes."""
    g = get_group(group)
    t = tensor if isinstance(tensor, Tensor) else Tensor(tensor)
    if g.nranks == 1 or _axes_in_scope(g.axes):
        return t
    _mon_collective("broadcast", t._data, g.axes)
    e = env_mod.ensure_env()
    spec = _current_spec(t._data)
    parts = [None if _mentions(p, g.axes) else p for p in spec]
    t._replace_(jax.device_put(
        _on_mesh(t._data), NamedSharding(e.mesh, PartitionSpec(*parts))))
    return t


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    """SPMD reduce == all_reduce (every participant holds the result)."""
    return all_reduce(tensor, op=op, group=group)


@_traced_collective
def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    """Parity: `paddle.distributed.scatter`. SPMD: shard dim 0 over the
    group's axes (src is irrelevant — data is global)."""
    g = get_group(group)
    if tensor_list is not None:
        from ..tensor.manipulation import concat

        tensor = concat([x if isinstance(x, Tensor) else Tensor(x)
                         for x in tensor_list], axis=0)
    t = tensor if isinstance(tensor, Tensor) else Tensor(tensor)
    if g.nranks == 1 or _axes_in_scope(g.axes):
        return t
    _mon_collective("scatter", t._data, g.axes)
    e = env_mod.ensure_env()
    t._replace_(jax.device_put(
        _on_mesh(t._data), NamedSharding(e.mesh, _spec_on(t.ndim, g.axes, 0))))
    return t


@_traced_collective
def all_to_all(out_tensor_list, in_tensor_list=None, group=None, sync_op=True,
               split_axis=0, concat_axis=0):
    """Parity: `paddle.distributed.alltoall`. Functional form
    `all_to_all(x, split_axis=, concat_axis=)` is the EP dispatch primitive
    (reference `global_scatter`/`global_gather` ops); inside shard_map it
    lowers to the XLA AllToAll HLO."""
    g = get_group(group)
    if isinstance(out_tensor_list, list) and in_tensor_list is not None:
        from ..tensor.manipulation import concat, split as _split

        x = concat([t if isinstance(t, Tensor) else Tensor(t)
                    for t in in_tensor_list], axis=0)
        res = all_to_all(x, group=group, split_axis=0, concat_axis=0)
        out_tensor_list.extend(_split(res, g.nranks, axis=0))
        return out_tensor_list
    x = out_tensor_list
    t = x if isinstance(x, Tensor) else Tensor(x)
    if g.nranks == 1:
        return t
    _mon_collective("all_to_all", t._data, g.axes)
    ax = g.axes if len(g.axes) > 1 else g.axes[0]
    if _axes_in_scope(g.axes):
        return apply(
            "all_to_all",
            lambda a: jax.lax.all_to_all(a, ax, split_axis=split_axis,
                                         concat_axis=concat_axis, tiled=True),
            (t,),
        )
    e = env_mod.ensure_env()
    fn = _a2a_program(e.mesh, g.axes, t.ndim, split_axis, concat_axis)
    in_spec = _spec_on(t.ndim, g.axes, concat_axis)
    sharding = NamedSharding(e.mesh, in_spec)

    # route through the tape (placement inside the traced fn): an eager
    # all-to-all is linear, and jax derives its vjp — the transposed
    # all-to-all — from the shard_map program
    def _placed_a2a(a):
        return fn(jax.device_put(a, sharding))

    return apply("all_to_all", _placed_a2a, (t,))


@functools.lru_cache(maxsize=512)
def _a2a_program(mesh, axes, ndim, split_axis, concat_axis):
    ax = axes if len(axes) > 1 else axes[0]
    in_spec = _spec_on(ndim, axes, concat_axis)
    out_spec = _spec_on(ndim, axes, split_axis)

    def shard_fn(a):
        return jax.lax.all_to_all(a, ax, split_axis=split_axis,
                                  concat_axis=concat_axis, tiled=True)

    return jax.jit(shard_map(shard_fn, mesh=mesh, in_specs=(in_spec,),
                             out_specs=out_spec))


alltoall = all_to_all


@_traced_collective
def reduce_scatter(tensor, op=ReduceOp.SUM, group=None, sync_op=True, axis=0):
    """Parity: `paddle.distributed.reduce_scatter` — XLA ReduceScatter HLO
    in-trace; eager form shards the summed result along ``axis``."""
    g = get_group(group)
    t = tensor if isinstance(tensor, Tensor) else Tensor(tensor)
    if g.nranks == 1:
        return t
    _mon_collective("reduce_scatter", t._data, g.axes)
    ax = g.axes if len(g.axes) > 1 else g.axes[0]
    if _axes_in_scope(g.axes):
        return apply(
            "reduce_scatter",
            lambda a: jax.lax.psum_scatter(a, ax, scatter_dimension=axis,
                                           tiled=True),
            (t,),
        )
    red = all_reduce(Tensor(t._data), op=op, group=group)
    e = env_mod.ensure_env()
    red._replace_(jax.device_put(
        _on_mesh(red._data), NamedSharding(e.mesh, _spec_on(t.ndim, g.axes, axis))))
    return red


@_traced_collective
def ppermute(tensor, perm, group=None):
    """`jax.lax.ppermute` exposed for pipeline schedules (reference p2p
    send/recv, `pp_utils/p2p_communication.py`). In-trace only."""
    g = get_group(group)
    ax = g.axes if len(g.axes) > 1 else g.axes[0]
    t = tensor if isinstance(tensor, Tensor) else Tensor(tensor)
    _mon_collective("ppermute", t._data, g.axes)
    return apply("ppermute", lambda a: jax.lax.ppermute(a, ax, perm), (t,))


def send(tensor, dst=0, group=None, sync_op=True):
    raise NotImplementedError(
        "point-to-point send/recv is expressed as ppermute inside pipeline "
        "schedules on TPU (XLA CollectivePermute); host-level p2p is not a "
        "TPU primitive"
    )


recv = send


@_traced_collective
def barrier(group=None):
    """Parity: `paddle.distributed.barrier`.

    Multi-host: a real rendezvous — every process must reach this point
    before any continues (host-side effects ordered around it, e.g. rank-0
    writes a file the others read). Uses the jax.distributed coordination
    service when initialized; a compiled psum over the mesh only orders
    *device* work, not hosts, so it is not sufficient (round-1 ADVICE).
    Single-process: a device round-trip flushes dispatched work.
    """
    _mon_collective("barrier", None)
    e = env_mod.ensure_env()
    if jax.process_count() > 1:
        try:
            from jax._src import distributed as _jd

            client = getattr(_jd.global_state, "client", None)
            if client is not None:
                client.wait_at_barrier(
                    f"paddle_tpu_barrier_{_barrier_seq[0]}", 60_000)
                _barrier_seq[0] += 1
                return None
        except Exception:
            pass
        # fallback: an all-reduce across the world mesh — devices of every
        # host participate, so completion implies every host dispatched it
        f = _barrier_fns.get(e.mesh)
        if f is None:
            from ..framework.jax_compat import shard_map
            from jax.sharding import PartitionSpec as P

            ax = tuple(e.mesh.axis_names)
            f = jax.jit(shard_map(lambda x: jax.lax.psum(x, ax), mesh=e.mesh,
                                  in_specs=P(), out_specs=P()))
            _barrier_fns[e.mesh] = f
        from ..utils.timing import device_sync

        # transfer-backed fence: block_until_ready acks enqueue, not
        # completion, through tunneled PJRT plugins (utils/timing.py)
        device_sync(f(jnp.ones(())))
        return None
    from ..utils.timing import device_sync

    device_sync(jnp.zeros(()))
    return None


_barrier_seq = [0]
_barrier_fns: dict = {}


def wait(tensor, group=None, use_calc_stream=True):
    if isinstance(tensor, Tensor):
        from ..utils.timing import device_sync

        device_sync(tensor._data)


# ---- object collectives (host-side; parity communication/all_gather_object) ----

def all_gather_object(object_list, obj, group=None):
    """Single-controller: every "rank" holds the same object graph."""
    g = get_group(group)
    object_list.extend([obj] * g.nranks)
    return object_list


def broadcast_object_list(object_list, src=0, group=None):
    return object_list


def alltoall_single(out_tensor, in_tensor, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    """Parity: paddle.distributed.alltoall_single — single-tensor
    all-to-all with optional uneven splits. Equal splits ride the XLA
    AllToAll HLO; uneven splits are unsupported under SPMD static shapes
    (same constraint the reference documents for its equal-split fast
    path)."""
    if in_split_sizes is not None or out_split_sizes is not None:
        raise NotImplementedError(
            "alltoall_single with uneven split sizes needs dynamic shapes, "
            "which a compiled SPMD program cannot express; pad to equal "
            "splits (the reference's fast path has the same requirement)")
    res = all_to_all(in_tensor, group=group, split_axis=0, concat_axis=0)
    if isinstance(out_tensor, Tensor):
        # inplace-adopt (same pattern as tensor inplace ops): the out=
        # form must stay differentiable through the collective
        out_tensor._data = res._data
        out_tensor._grad_node = res._grad_node
        out_tensor._out_index = res._out_index
        out_tensor.stop_gradient = (res.stop_gradient
                                    and out_tensor.stop_gradient)
        return out_tensor
    return res


def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True):
    """Parity: paddle.distributed.gather. Single-controller SPMD holds one
    logical value per mesh: gather materializes the per-shard slices the
    way all_gather does, delivered on every host (dst is advisory)."""
    g = get_group(group)
    if gather_list is None:
        gather_list = []
    parts = []
    all_gather(parts, tensor, group=group)
    if len(parts) != g.nranks:
        raise RuntimeError(
            f"gather produced {len(parts)} shards for a "
            f"{g.nranks}-rank group")
    # a reference-style caller preallocates nranks placeholders and
    # expects them *replaced*, not appended after
    gather_list[:] = parts
    return gather_list


def scatter_object_list(out_object_list, in_object_list=None, src=0,
                        group=None):
    """Parity: paddle.distributed.scatter_object_list (single-controller:
    every rank sees the same object graph, so rank r's slot is
    in_object_list[r] — with one logical process that is slot 0)."""
    g = get_group(group)
    if in_object_list is None:
        raise ValueError("scatter_object_list needs in_object_list")
    if len(in_object_list) != g.nranks:
        raise ValueError(
            f"in_object_list must have nranks={g.nranks} entries")
    out_object_list.append(in_object_list[g.rank])
    return out_object_list


def isend(tensor, dst=0, group=None):
    """Parity: paddle.distributed.isend — same TPU constraint as send."""
    return send(tensor, dst, group)


def irecv(tensor, src=0, group=None):
    """Parity: paddle.distributed.irecv — same TPU constraint as recv."""
    return recv(tensor, src, group)


def destroy_process_group(group=None):
    """Parity: paddle.distributed.destroy_process_group. Mesh-axis groups
    hold no OS resources (they are sharding annotations); world teardown
    resets the mesh env."""
    if group is None:
        from . import env as _env

        _env.reset_env()
    return None


def get_backend(group=None):
    """Parity: paddle.distributed.get_backend — this build's collectives
    are XLA HLOs over the PJRT runtime."""
    return "XLA"


def is_available():
    """Parity: paddle.distributed.is_available."""
    return True


_monitor_register(sys.modules[__name__])
