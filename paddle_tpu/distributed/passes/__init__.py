"""paddle.distributed.passes parity (reference
`python/paddle/distributed/passes/pass_base.py`): the named-pass registry
and PassManager.

TPU-first note: the reference's pass zoo (auto_parallel_fp16,
fused_attention, pipeline scheduling, ...) rewrites ProgramDesc graphs;
here those capabilities are XLA's (fusion, AMP recording, scan-based
pipeline). The pass *framework* still carries user/third-party program
rewrites: a pass is a callable over the recorded `static.Program`,
registered by name, applied through PassManager — same surface, operating
on the op-record form.
"""
from __future__ import annotations

__all__ = ["new_pass", "PassManager", "PassContext", "register_pass"]

_PASS_REGISTRY: dict = {}


def register_pass(name):
    """Decorator: register a pass class/factory under ``name`` (parity:
    @register_pass in pass_base.py)."""
    def deco(cls):
        _PASS_REGISTRY[name] = cls
        return cls

    return deco


class PassContext:
    """Carries attributes between passes (parity: PassContext)."""

    def __init__(self):
        self._attrs: dict = {}

    def set_attr(self, key, value):
        self._attrs[key] = value

    def get_attr(self, key, default=None):
        return self._attrs.get(key, default)


class _PassBase:
    def __init__(self, name, attrs=None):
        self.name = name
        self._attrs = dict(attrs or {})

    def set_attr(self, key, value):
        self._attrs[key] = value
        return self

    def get_attr(self, key, default=None):
        return self._attrs.get(key, default)

    def apply(self, main_programs, startup_programs=None, context=None):
        raise NotImplementedError(
            f"pass {self.name!r} was created without an implementation; "
            "register one with @register_pass or subclass and override "
            "apply()")


def new_pass(name, pass_attrs=None):
    """Instantiate a registered pass by name (parity: new_pass). Unknown
    names raise with the registry contents — the reference's C++ pass zoo
    has no graph form here to silently no-op on."""
    cls = _PASS_REGISTRY.get(name)
    if cls is None:
        raise ValueError(
            f"no pass registered under {name!r} (registered: "
            f"{sorted(_PASS_REGISTRY)}); the reference's built-in graph "
            "passes are XLA's job on TPU — register custom program "
            "passes with @register_pass")
    p = cls() if isinstance(cls, type) else cls
    if not isinstance(p, _PassBase) and not hasattr(p, "apply") \
            and callable(p):
        # a registered callable is a FACTORY only when it declares no
        # parameters at all — an apply-style function (even with defaulted
        # params) must never be executed at construction time
        import inspect

        try:
            is_factory = not inspect.signature(p).parameters
        except (TypeError, ValueError):
            is_factory = False
        if is_factory:
            produced = p()
            if hasattr(produced, "apply") or callable(produced):
                p = produced
    if not isinstance(p, _PassBase):
        base = _PassBase(name, pass_attrs)
        if hasattr(p, "apply") and callable(p.apply):
            # duck-typed pass object: honor its apply()
            base.apply = p.apply
        elif callable(p):
            base.apply = p
        p = base
    for k, v in (pass_attrs or {}).items():
        p.set_attr(k, v)
    return p


class PassManager:
    """Apply a pass list in order (parity: PassManager)."""

    def __init__(self, passes):
        self._passes = list(passes)
        self.context = PassContext()

    @property
    def names(self):
        return [getattr(p, "name", type(p).__name__) for p in self._passes]

    def apply(self, main_programs, startup_programs=None):
        main_programs = main_programs if isinstance(main_programs, list) \
            else [main_programs]
        for p in self._passes:
            p.apply(main_programs, startup_programs, self.context)
        return main_programs


PassBase = _PassBase
__all__ += ["PassBase"]
