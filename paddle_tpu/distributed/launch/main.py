"""`python -m paddle_tpu.distributed.launch` — the trainer launcher.

Reference parity: `launch/main.py:18` and `CollectiveController.build_pod`
(`launch/controllers/collective.py:37,124-220`): builds the node's process
set, assigns `PADDLE_TRAINER_ID`/`PADDLE_TRAINERS_NUM`/`PADDLE_MASTER` env,
spawns and babysits workers, relaunching or tearing down on failure.

TPU-first design: single-controller SPMD needs ONE process per *host* (it
drives every local chip), not one per device — so `--nproc_per_node`
defaults to 1 and the reference's GPU-visibility plumbing
(FLAGS_selected_gpus) has no equivalent. Multi-host: the launcher stamps the
coordinator address (PADDLE_MASTER) consumed by
`init_parallel_env` -> `jax.distributed.initialize`. A local
`--nnodes`-style simulation spawns N processes with
JAX_PLATFORMS=cpu for testing the multi-process path without TPUs.
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time


def _parse():
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="launch distributed training (single-controller SPMD)")
    p.add_argument("--nnodes", type=str, default="1",
                   help="number of hosts (or host range 'N:M' for elastic)")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="processes per host (SPMD default: 1)")
    p.add_argument("--master", type=str, default=None,
                   help="coordinator ip:port (defaults to this host)")
    p.add_argument("--rank", type=int, default=-1, help="node rank")
    p.add_argument("--log_dir", type=str, default="log")
    p.add_argument("--run_mode", type=str, default="collective")
    p.add_argument("--job_id", type=str, default="default")
    p.add_argument("--devices", type=str, default=None,
                   help="visible device ids (sets JAX local device filter)")
    p.add_argument("--shard_plan", type=str, default=None,
                   help="shard_plan.json from tools/shard_plan.py: stamped "
                        "into every worker as PT_SHARD_PLAN, so scripts "
                        "(and hapi fit) apply the planned mesh + param "
                        "placements with no hand-written PartitionSpecs")
    p.add_argument("--max_restart", type=int, default=3)
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args()


def _spawn(args, rank, nprocs, master, restarts=0):
    env = dict(os.environ)
    env["PADDLE_TRAINER_ID"] = str(rank)
    env["PADDLE_TRAINERS_NUM"] = str(nprocs)
    env["PADDLE_RANK_IN_NODE"] = str(rank)
    env["PADDLE_JOB_ID"] = args.job_id
    # scripts use this to detect an elastic relaunch and resume from their
    # latest checkpoint (reference: PADDLE_ELASTIC_* env rewrite on restart)
    env["PADDLE_RESTART_COUNT"] = str(restarts)
    if master:
        env["PADDLE_MASTER"] = master
    if args.devices is not None:
        env["TPU_VISIBLE_DEVICES"] = args.devices
    if args.shard_plan is not None:
        env["PT_SHARD_PLAN"] = os.path.abspath(args.shard_plan)
    # fleet telemetry (docs/OBSERVABILITY.md "Training goodput plane"):
    # every worker heartbeats into one launcher-owned directory the
    # babysit loop tails; a launcher that holds PT_METRICS_PORT moves
    # workers to ephemeral ports (each reports its bound port in the
    # heartbeat line — the launcher serves the aggregate)
    env.setdefault("PT_HEARTBEAT_DIR", os.path.join(
        os.path.abspath(args.log_dir), "heartbeats"))
    if os.environ.get("PT_METRICS_PORT"):
        env["PT_METRICS_PORT"] = "0"
    os.makedirs(args.log_dir, exist_ok=True)
    log = open(os.path.join(args.log_dir,
                            f"workerlog.{rank}"), "ab", buffering=0)
    cmd = ([sys.executable, "-u", args.training_script]
           + args.training_script_args)
    proc = subprocess.Popen(cmd, env=env, stdout=log, stderr=subprocess.STDOUT)
    return proc, log


def _arm_fleet(args, nprocs):
    """Launcher-side fleet telemetry: a FleetMonitor tailing every
    worker's heartbeat JSONL (straggler / dp-desync / silent-worker
    detectors, exact sketch merges) plus — when the launcher holds
    PT_METRICS_PORT — an aggregated /metrics + /statusz endpoint, its
    bound port written to ``{log_dir}/metrics_port``. Soft-fails:
    babysitting must survive any telemetry error."""
    try:
        from ...monitor import exporter
        from ...monitor import heartbeat as _hb

        hb_dir = os.environ.get("PT_HEARTBEAT_DIR") or os.path.join(
            os.path.abspath(args.log_dir), "heartbeats")
        fleet = _hb.FleetMonitor(hb_dir, nprocs, log_dir=args.log_dir)
        fleet.attach()
        if os.environ.get("PT_METRICS_PORT"):
            port = exporter.start()
            if port:
                with open(os.path.join(args.log_dir,
                                       "metrics_port"), "w") as f:
                    f.write(f"{port}\n")
        return fleet
    except Exception as e:  # noqa: BLE001 — telemetry never kills launch
        print(f"launch: fleet telemetry unavailable: {e}", file=sys.stderr)
        return None


def main():
    args = _parse()
    nnodes = int(str(args.nnodes).split(":")[0])
    nprocs = args.nproc_per_node * nnodes if nnodes > 1 and args.rank < 0 \
        else args.nproc_per_node
    master = args.master
    if nprocs > 1 and master is None:
        master = "127.0.0.1:49178"

    # elastic membership watch (reference ElasticManager in the launcher
    # agent): enabled when a store server address is provided — covers
    # failures subprocess polling can't see (a remote host going dark)
    manager = None
    if os.environ.get("PADDLE_ELASTIC_SERVER") or args.run_mode == "elastic":
        try:
            from ..fleet.elastic import ElasticManager

            manager = ElasticManager(
                job_id=args.job_id, rank=max(args.rank, 0),
                is_master=args.rank <= 0, np=nnodes)
        except Exception as e:
            print(f"launch: elastic manager unavailable: {e}",
                  file=sys.stderr)

    procs = []
    restarts = 0

    def _relaunch_pod():
        nonlocal procs, restarts
        restarts += 1
        for p, _ in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p, _ in procs:
            p.wait()
        for _, log in procs:
            log.close()
        procs = [_spawn(args, r, nprocs, master, restarts)
                 for r in range(nprocs)]

    fleet = None
    try:
        for r in range(nprocs):
            procs.append(_spawn(args, r, nprocs, master))
        fleet = _arm_fleet(args, nprocs)
        members = set(manager.alive_nodes()) if manager else None
        while True:
            if fleet is not None:
                try:
                    fleet.poll()
                except Exception:  # noqa: BLE001 — babysit loop wins
                    pass
            states = [p.poll() for p, _ in procs]
            if all(s is not None for s in states):
                bad = [s for s in states if s != 0]
                if bad and restarts < args.max_restart:
                    # whole pod died (single-proc pods land here, never in
                    # the partial-failure branch below) — relaunch, resume
                    # from checkpoint via PADDLE_RESTART_COUNT
                    _relaunch_pod()
                    continue
                if manager and not bad:
                    manager.exit(completed=True)
                sys.exit(bad[0] if bad else 0)
            failed = [i for i, s in enumerate(states) if s not in (None, 0)]
            membership_changed = False
            if manager is not None:
                cur = set(manager.alive_nodes())
                membership_changed = members is not None and cur < members
                members = cur if membership_changed else (
                    cur | (members or set()))
            if failed or membership_changed:
                if restarts >= args.max_restart:
                    for p, _ in procs:
                        if p.poll() is None:
                            p.send_signal(signal.SIGTERM)
                    sys.exit(states[failed[0]] if failed else 1)
                # relaunch the whole pod (reference ElasticManager kills and
                # relaunches local trainers); workers resume from their last
                # dist.checkpoint via PADDLE_RESTART_COUNT
                _relaunch_pod()
            time.sleep(0.5)
    finally:
        if fleet is not None:
            # terminal poll: the final fleet.json snapshot (and any
            # just-landed verdict) survives the launcher's exit
            try:
                fleet.poll()
            except Exception:  # noqa: BLE001
                pass
        if manager is not None:
            manager.exit()
        for p, log in procs:
            if p.poll() is None:
                p.terminate()
            log.close()


if __name__ == "__main__":
    main()
