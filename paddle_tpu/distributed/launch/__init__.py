"""Distributed launcher (parity: `python -m paddle.distributed.launch`,
reference `launch/main.py:18`, `launch/controllers/collective.py`)."""
from .main import main  # noqa: F401
