"""paddle.distributed.rpc — EXCLUDED capability, importable surface.

The reference's user-level brpc RPC exists to build parameter-server and
actor-style systems. This TPU build's README ("Scope: deliberate
exclusions") documents why that tier is out: the single-controller JAX
model plus mesh collectives cover the in-scope distribution patterns, and
control-plane needs are met by the coordination service + TCPStore. The
functions exist so `import paddle.distributed.rpc` ports don't crash at
import time; CALLING them states the design decision instead of failing
mysteriously.
"""
from __future__ import annotations

__all__ = ["init_rpc", "rpc_sync", "rpc_async", "shutdown",
           "get_worker_info", "get_all_worker_infos",
           "get_current_worker_info"]

_MSG = (
    "paddle.distributed.rpc is deliberately excluded from this TPU build "
    "(README 'Scope: deliberate exclusions'): the single-controller model "
    "plus XLA collectives replace actor-style RPC; for host-side "
    "coordination use distributed.store.TCPStore or the jax.distributed "
    "coordination service"
)


def _excluded(name):
    def fn(*args, **kwargs):
        raise RuntimeError(f"{name}: {_MSG}")

    fn.__name__ = name
    fn.__doc__ = _MSG
    # machine-readable marker for the API_PARITY honesty column
    fn.__excluded__ = "RPC stack (README Scope)"
    return fn


init_rpc = _excluded("init_rpc")
rpc_sync = _excluded("rpc_sync")
rpc_async = _excluded("rpc_async")
shutdown = _excluded("shutdown")
get_worker_info = _excluded("get_worker_info")
get_all_worker_infos = _excluded("get_all_worker_infos")
get_current_worker_info = _excluded("get_current_worker_info")
