"""Sharding annotations: shard_tensor / sharding constraints (GSPMD).

Reference parity: `paddle.distributed.shard_tensor`
(`auto_parallel/interface.py:28`) and the whole static auto-parallel chain —
`Completer` (dist-attr propagation, `static/completion.py:108`), `Partitioner`
(`static/partitioner.py:40`) and `Resharder` (comm insertion,
`static/reshard.py:978`).

TPU-first design: those three compiler stages ARE GSPMD. We annotate tensors
with a `PartitionSpec` over the global mesh; XLA's SPMD partitioner completes
the propagation, splits per device, and inserts the collectives. So Paddle's
~15K-line auto-parallel static stack collapses to: put params on the mesh with
`jax.device_put(NamedSharding)`, and drop `with_sharding_constraint` hints at
layer boundaries inside traced code. Both paths run through the op dispatcher
so they are autograd-transparent (the VJP of a sharding constraint is the
matching constraint on the cotangent — XLA handles it).
"""
from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec

from . import env as env_mod
from ..framework.core import Tensor
from ..ops.dispatch import apply

__all__ = [
    "PartitionSpec", "shard_tensor", "sharding_constraint", "replicate",
    "get_sharding", "shard_parameter", "per_shard_bytes",
    "constrain_or_put",
]


def constrain_or_put(x, sharding):
    """Trace-aware placement of a RAW jax array (the Tensor path is
    :func:`shard_tensor`): traced -> ``with_sharding_constraint``, eager
    -> ``device_put``. On jax 0.4.37 a ``device_put`` inside a trace is
    a jaxpr NO-OP — the PR 10 incident compiled dp to fully replicated
    programs because every in-model hint vanished this way. This is the
    ONE blessed home of the branch; trace-reachable op/model code must
    call it instead of ``jax.device_put`` (lint rule PTL001,
    ``analysis/lint.py``)."""
    if isinstance(x, jax.core.Tracer):
        return jax.lax.with_sharding_constraint(x, sharding)
    return jax.device_put(x, sharding)


def per_shard_bytes(x) -> int:
    """Bytes ONE device holds for ``x`` under its current sharding — the
    per-device accounting unit of the memory observatory's census
    (``monitor/memory.py:live_census(per_device=True)``). A replicated
    (or unsharded) array costs its full ``nbytes`` on every device; a
    sharded one costs its largest addressable shard (uneven splits bill
    the worst shard, which is the one that OOMs)."""
    arr = x._data if isinstance(x, Tensor) else x
    try:
        shards = arr.addressable_shards
        if shards:
            return max(int(s.data.nbytes) for s in shards)
    except Exception:  # noqa: BLE001 — non-jax inputs fall through
        pass
    return int(getattr(arr, "nbytes", 0))


def _named_sharding(*spec) -> NamedSharding:
    e = env_mod.ensure_env()
    return NamedSharding(e.mesh, PartitionSpec(*spec))


def get_sharding(t) -> PartitionSpec | None:
    arr = t._data if isinstance(t, Tensor) else t
    s = getattr(arr, "sharding", None)
    if isinstance(s, NamedSharding):
        return s.spec
    return getattr(t, "_sharding_spec", None)


def shard_tensor(x, mesh=None, placements=None, *, spec=None,
                 stop_gradient=None):
    """Place a tensor on the mesh with the given layout.

    ``spec`` is a PartitionSpec-style tuple of mesh-axis names per dim
    (None = replicated). ``placements`` accepts the same thing for parity
    with the reference's `shard_tensor(x, mesh, [Shard(0), Replicate()])`
    vocabulary — strings/None only, e.g. ``["dp", None]``.

    Eager: physically reshards (device_put). Traced: a sharding constraint.
    """
    t = x if isinstance(x, Tensor) else Tensor(x)
    parts = tuple(spec if spec is not None else (placements or ()))
    e = env_mod.ensure_env()
    mesh = mesh or e.mesh
    # drop axes that don't divide their dim (e.g. a 'dp' batch hint on a
    # batch smaller than the dp degree) instead of failing the program
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    shape = t.shape
    cleaned = []
    for i, p in enumerate(parts):
        names = p if isinstance(p, (tuple, list)) else (p,)
        n = 1
        for nm in names:
            if nm is not None:
                n *= sizes.get(nm, 1)
        cleaned.append(p if (i < len(shape) and n and shape[i] % n == 0)
                       else None)
    parts = tuple(cleaned)
    sharding = NamedSharding(mesh, PartitionSpec(*parts))

    # Eager -> physical reshard (device_put); traced -> an EXPLICIT
    # with_sharding_constraint. On this jax (0.4.37) a device_put inside
    # a trace is a jaxpr no-op — every model's dp/mp activation hint was
    # silently dropped from compiled steps (dp lowered to fully
    # replicated programs; caught by the autoshard planner's HLO comms
    # extraction reading zero collectives). The branch is decided on the
    # INPUT array, not inside the applied fn: the tape's eager jax.vjp
    # traces the fn too, and with_sharding_constraint on an off-mesh
    # concrete cotangent rejects the device-set change device_put
    # handles. Differentiable in both (the transpose is the matching
    # constraint/device_put on the cotangent).
    if isinstance(t._data, jax.core.Tracer):
        def _constrain(a):
            return jax.lax.with_sharding_constraint(a, sharding)
    else:
        def _constrain(a):
            return jax.device_put(a, sharding)

    out = apply("shard_tensor", _constrain, (t,))
    out._sharding_spec = PartitionSpec(*parts)
    if stop_gradient is not None:
        out.stop_gradient = stop_gradient
    elif t.stop_gradient:
        out.stop_gradient = True
    return out


def sharding_constraint(x, *spec):
    """`with_sharding_constraint` as a Paddle-shaped op: hint XLA that this
    activation should be laid out as ``spec`` over the global mesh. The
    primary tool of the meta-parallel layers."""
    return shard_tensor(x, spec=spec)


def replicate(x):
    return shard_tensor(x, spec=())


def shard_parameter(param, *spec):
    """Physically shard a Parameter's buffer in place (used by the
    meta-parallel layers at construction; parity with Megatron-style weight
    partitioning in `fleet/layers/mpu/mp_layers.py` — but the weight stays a
    single *global* array and XLA owns the split)."""
    sharding = _named_sharding(*spec)
    param._replace_(jax.device_put(param._data, sharding))
    param._sharding_spec = PartitionSpec(*spec)
    return param
