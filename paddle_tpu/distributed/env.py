"""Global parallel environment: the device Mesh and its axis topology.

Reference parity: the process-group world bootstrap
(`python/paddle/distributed/parallel.py:915` `init_parallel_env`, TCPStore
rendezvous + `ProcessGroupNCCL` creation `collective.py:139`) and the 4-D
hybrid topology (`fleet/base/topology.py:58` `CommunicateTopology`).

TPU-first design: Paddle is multi-controller — N processes, one per GPU,
rendezvous over TCPStore, NCCL rings per axis. On TPU the idiomatic model is
single-controller SPMD: ONE Python process per host drives all local chips,
`jax.distributed` handles multi-host bootstrap, and the "process groups" are
axes of a `jax.sharding.Mesh`. A collective "over the mp group" is an XLA
collective over the 'mp' mesh axis, compiled into the program and riding ICI.

The mesh axes follow the reference topology order [dp, pp, sharding, sep, mp]
(`topology.py:144-240`): outermost axes map to the slowest-varying device
dimension so that mp (highest-bandwidth-need) neighbours are physically
adjacent on the ICI torus, the same reason the reference puts mp innermost on
NVLink.
"""
from __future__ import annotations

import os

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

# canonical axis order, outermost (slowest) first — mirrors the reference's
# HybridCommunicateGroup order ["data", "pipe", "sharding", "sep", "model"]
AXIS_ORDER = ("dp", "pp", "sharding", "sep", "mp")

_global_env = None


class ParallelEnv:
    """The single-controller parallel environment.

    Holds the global :class:`jax.sharding.Mesh` plus per-axis degrees. All
    distributed layers consult this via :func:`get_env`.
    """

    def __init__(self, mesh: Mesh, degrees: dict):
        self.mesh = mesh
        self.degrees = dict(degrees)

    # -- paddle-shaped queries (multi-controller vocabulary mapped to SPMD) --
    @property
    def world_size(self) -> int:
        return self.mesh.size

    @property
    def nranks(self) -> int:
        return self.mesh.size

    @property
    def rank(self) -> int:
        # single-controller: the driving process is "rank 0" of its host
        return jax.process_index()

    @property
    def local_rank(self) -> int:
        return 0

    def degree(self, axis: str) -> int:
        return self.degrees.get(axis, 1)

    def sharding_for(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, PartitionSpec(*spec))

    def __repr__(self):
        return f"ParallelEnv(degrees={self.degrees})"


def _devices_for_mesh(n: int | None = None):
    devs = jax.devices()
    return devs if n is None else devs[:n]


def init_mesh(dp: int = 1, mp: int = 1, pp: int = 1, sharding: int = 1,
              sep: int = 1, devices=None) -> ParallelEnv:
    """Build the global mesh from per-axis degrees.

    Degrees of 1 keep their axis in the mesh (size-1 axes are free in XLA),
    so `PartitionSpec('mp')` is always valid regardless of configuration.
    A degree of -1 on exactly one axis absorbs the remaining devices
    (`dp=-1` is the common "data parallel over whatever is left").
    """
    global _global_env
    degrees = {"dp": dp, "pp": pp, "sharding": sharding, "sep": sep, "mp": mp}
    devs = list(devices) if devices is not None else _devices_for_mesh()
    from ..framework.errors import InvalidArgumentError,         PreconditionNotMetError

    known = 1
    wild = None
    for ax, d in degrees.items():
        if d == 0 or d < -1:
            raise InvalidArgumentError(
                f"mesh degree {ax}={d}: degrees must be positive "
                "(or -1 on one axis to absorb the remaining devices)")
        if d == -1:
            if wild is not None:
                raise InvalidArgumentError(
                    "only one mesh axis may be -1 "
                    f"(both {wild!r} and {ax!r} are)")
            wild = ax
        else:
            known *= d
    if wild is not None:
        if len(devs) % known:
            raise PreconditionNotMetError(
                f"cannot infer {wild}: {len(devs)} devices not divisible "
                f"by the {known} explicitly requested")
        degrees[wild] = len(devs) // known
    total = int(np.prod([degrees[a] for a in AXIS_ORDER]))
    if total > len(devs):
        raise PreconditionNotMetError(
            f"mesh of {total} devices requested "
            f"({'*'.join(AXIS_ORDER)} = "
            f"{'*'.join(str(degrees[a]) for a in AXIS_ORDER)}) but only "
            f"{len(devs)} devices are available")
    devs = devs[:total]
    arr = np.array(devs).reshape([degrees[a] for a in AXIS_ORDER])
    mesh = Mesh(arr, AXIS_ORDER)
    _global_env = ParallelEnv(mesh, degrees)
    _install_mesh_hook(mesh)
    from .fleet.base import topology as _topo

    if _topo.get_hcg() is not None:  # rebuild the view over the new mesh
        _topo.set_hcg(_topo.HybridCommunicateGroup())
    return _global_env


def put_replicated(x, mesh):
    """Replicate a host value onto ``mesh``, multihost-safe.

    Single-process meshes use plain ``device_put``; when the mesh spans
    other processes (launcher + `jax.distributed.initialize`), the
    host-local value — identical on every process by the single-program
    contract — becomes the global replicated array via
    `multihost_utils.host_local_array_to_global_array` (device_put rejects
    non-addressable shardings)."""
    repl = NamedSharding(mesh, PartitionSpec())
    if repl.is_fully_addressable:
        return jax.device_put(x, repl)
    from jax.experimental import multihost_utils

    if jax.dtypes.issubdtype(getattr(x, "dtype", None),
                             jax.dtypes.prng_key):
        data = multihost_utils.host_local_array_to_global_array(
            np.asarray(jax.random.key_data(x)), mesh, PartitionSpec())
        return jax.random.wrap_key_data(
            data, impl=jax.random.key_impl(x))
    return multihost_utils.host_local_array_to_global_array(
        np.asarray(x), mesh, PartitionSpec())


def ensure_on_mesh(a, mesh):
    """Replicate a concrete array onto ``mesh`` iff it is not already on
    that mesh's device set — the one placement predicate shared by the
    param-place hook and the generation path."""
    if isinstance(a, jax.Array) \
            and len(a.sharding.device_set) != mesh.size:
        return put_replicated(a, mesh)
    return a


def _install_mesh_hook(mesh):
    """Teach the op dispatcher to replicate off-mesh eager operands onto the
    mesh (mixing a host-side batch with sharded params is the common case),
    and place newly created Parameters on the mesh."""
    from ..ops import dispatch as _dispatch
    from ..framework import core as _core

    if mesh.size == 1:
        _dispatch.set_mesh_hook(None)
        _core.set_param_place_hook(None)
        return
    n_mesh = mesh.size
    repl = NamedSharding(mesh, PartitionSpec())

    def place_param(arr):
        return ensure_on_mesh(arr, mesh)

    _core.set_param_place_hook(place_param)

    def _concrete(a):
        return isinstance(a, jax.Array) and not isinstance(a, jax.core.Tracer)

    def harmonize(arrays):
        on_mesh = off_mesh = False
        for a in arrays:
            if _concrete(a):
                if len(a.sharding.device_set) == n_mesh:
                    on_mesh = True
                else:
                    off_mesh = True
        if not (on_mesh and off_mesh):
            return arrays
        return [
            put_replicated(a, mesh)
            if _concrete(a) and len(a.sharding.device_set) != n_mesh
            else a
            for a in arrays
        ]

    _dispatch.set_mesh_hook(harmonize)


def get_env() -> ParallelEnv | None:
    return _global_env


def ensure_env() -> ParallelEnv:
    """Default single-axis env over all visible devices (dp=-1).

    The reference errors when distributed APIs run before `fleet.init`
    (`fleet/fleet.py:169`); the single-controller model can instead
    manufacture a sane default mesh — but silently doing so hides missed
    initialization, so the implicit path warns once (VERDICT r2 weak #7)."""
    if _global_env is None:
        if len(__import__("jax").devices()) > 1:
            import warnings

            warnings.warn(
                "paddle_tpu distributed API used before fleet.init()/"
                "init_mesh(); auto-initializing a data-parallel mesh over "
                "all visible devices. Call fleet.init(...) explicitly to "
                "choose a topology.", stacklevel=3)
        init_mesh(dp=-1)
        # mark the env as implicitly manufactured — test harnesses reset
        # these between tests so one test's collective cannot leave the
        # whole suite running under a surprise mesh
        _global_env.auto_initialized = True
    return _global_env


def reset_env():
    """Tear down the mesh and uninstall dispatcher/parameter hooks (test
    isolation; also the path to re-init after an elastic resize)."""
    global _global_env
    _global_env = None
    from ..ops import dispatch as _dispatch
    from ..framework import core as _core

    _dispatch.set_mesh_hook(None)
    _core.set_param_place_hook(None)
    # fleet-side globals snapshot the env; clear them too
    from .fleet.base import topology as _topo
    from . import fleet as _fleet

    _topo.set_hcg(None)
    _fleet._fleet_strategy = None


def get_mesh() -> Mesh | None:
    return _global_env.mesh if _global_env is not None else None


def init_distributed_runtime(coordinator_address=None, num_processes=None,
                             process_id=None):
    """Multi-host bootstrap (reference: TCPStore + `BroadcastUniqueNCCLID`,
    `process_group_nccl.cc:477`). On TPU: `jax.distributed.initialize` — the
    JAX coordination service plays TCPStore, PJRT plays NCCL."""
    if num_processes is None:
        num_processes = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    if num_processes <= 1 and coordinator_address is None:
        return  # single host, nothing to rendezvous
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
