"""Distributed namespace long tail (reference
`python/paddle/distributed/__init__.py` exports that predate the
collective/auto-parallel APIs): ParallelMode, split, DistAttr, and the
parameter-server dataset shims.

The PS dataset classes (InMemoryDataset/QueueDataset and the *Entry
configs) belong to the excluded parameter-server stack (see README
"Scope: deliberate exclusions") — they raise with that rationale instead
of being silently absent.
"""
from __future__ import annotations

__all__ = [
    "ParallelMode", "split", "DistAttr", "InMemoryDataset", "QueueDataset",
    "CountFilterEntry", "ProbabilityEntry", "ShowClickEntry",
]


class ParallelMode:
    """Parity: paddle.distributed.ParallelMode (hybrid-parallel mode ids)."""

    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """Parity: paddle.distributed.split — build-and-apply a
    model-parallel linear/embedding over the 'mp' mesh axis. The
    reference hand-places per-rank shards; here the meta-parallel layers
    annotate shardings and GSPMD splits the matmul."""
    from .fleet.meta_parallel import (
        ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
    )

    if operation == "linear":
        in_f, out_f = size
        if axis == 1:
            layer = ColumnParallelLinear(in_f, out_f,
                                         weight_attr=weight_attr,
                                         has_bias=bias_attr is not False,
                                         gather_output=gather_out)
        elif axis == 0:
            layer = RowParallelLinear(in_f, out_f,
                                      weight_attr=weight_attr,
                                      has_bias=bias_attr is not False,
                                      input_is_parallel=False)
        else:
            raise ValueError(f"linear split axis must be 0 or 1, got {axis}")
        return layer(x)
    if operation == "embedding":
        num_emb, emb_dim = size
        layer = VocabParallelEmbedding(num_emb, emb_dim,
                                       weight_attr=weight_attr)
        return layer(x)
    raise ValueError(
        f"split operation must be 'linear' or 'embedding', got {operation!r}")


class DistAttr:
    """Parity: paddle.distributed.DistAttr(mesh, sharding_specs) — the
    pre-Placement shard_tensor annotation form."""

    def __init__(self, mesh, sharding_specs):
        self.process_mesh = mesh
        self.sharding_specs = list(sharding_specs)

    def placements(self):
        from .auto_parallel import Replicate, Shard

        out = []
        for dim_name in self.process_mesh.dim_names:
            if dim_name in self.sharding_specs:
                out.append(Shard(self.sharding_specs.index(dim_name)))
            else:
                out.append(Replicate())
        return out


def _ps_excluded(name):
    class _Excluded:
        def __init__(self, *a, **k):
            raise RuntimeError(
                f"paddle.distributed.{name} belongs to the parameter-server "
                "stack, which this TPU build deliberately excludes (see "
                "README 'Scope: deliberate exclusions'); sharded embedding "
                "tables over the mesh (VocabParallelEmbedding) cover the "
                "large-embedding use case")

    _Excluded.__name__ = _Excluded.__qualname__ = name
    # machine-readable marker for the API_PARITY honesty column
    _Excluded.__excluded__ = "parameter-server stack (README Scope)"
    return _Excluded


InMemoryDataset = _ps_excluded("InMemoryDataset")
QueueDataset = _ps_excluded("QueueDataset")
CountFilterEntry = _ps_excluded("CountFilterEntry")
ProbabilityEntry = _ps_excluded("ProbabilityEntry")
ShowClickEntry = _ps_excluded("ShowClickEntry")
