"""paddle_tpu.distributed: SPMD distributed training over a TPU device mesh.

Reference parity: `python/paddle/distributed/` — the collective API,
`init_parallel_env`/`DataParallel`, fleet, meta-parallel layers, sharding,
auto-parallel annotations, launch.

TPU-first design (SURVEY.md §2.5-2.6 "TPU build"): one global
`jax.sharding.Mesh` with axes (dp, pp, sharding, sep, mp) replaces the
reference's per-axis NCCL communicator rings; parallelism strategies are
sharding layouts (GSPMD) rather than communication protocols; explicit
collectives exist for shard_map regions (pipeline schedules, MoE all-to-all)
and lower to XLA collective HLOs riding ICI.
"""
from __future__ import annotations

from .env import (  # noqa: F401
    AXIS_ORDER, ParallelEnv as _EnvView, get_env, get_mesh, init_mesh,
)
from .collective import (  # noqa: F401
    Group, ReduceOp, all_gather, all_gather_object, all_reduce, all_to_all,
    alltoall, alltoall_single, barrier, broadcast, broadcast_object_list,
    destroy_process_group, gather, get_backend, get_group, irecv,
    is_available, isend, new_group, ppermute, recv, reduce, reduce_scatter,
    scatter, scatter_object_list, send, wait,
)
from .compat import (  # noqa: F401
    CountFilterEntry, DistAttr, InMemoryDataset, ParallelMode,
    ProbabilityEntry, QueueDataset, ShowClickEntry, split,
)
from .parallel import (  # noqa: F401
    DataParallel, ParallelEnv, get_rank, get_world_size, init_parallel_env,
    is_initialized, spawn,
)
from .shard import (  # noqa: F401
    PartitionSpec, get_sharding, replicate, shard_parameter, shard_tensor,
    sharding_constraint,
)

from . import checkpoint  # noqa: F401
from . import passes  # noqa: F401
from . import rpc  # noqa: F401
from . import sharding  # noqa: F401
from . import fleet  # noqa: F401
from . import io  # noqa: F401
from . import launch  # noqa: F401
from .auto_parallel import ProcessMesh  # noqa: F401


def gloo_init_parallel_env(rank_id, rank_num, server_endpoint):
    """Parity shim: the reference spins a gloo ring for CPU barriers; the
    coordination service + TCPStore covers that role here."""
    from .env import ensure_env

    ensure_env()
    return None


def gloo_barrier():
    return barrier()


def gloo_release():
    return None


__all__ = [
    "init_parallel_env", "get_rank", "get_world_size", "is_initialized",
    "ParallelEnv", "DataParallel", "spawn",
    "ReduceOp", "Group", "new_group", "get_group",
    "all_reduce", "all_gather", "all_to_all", "alltoall", "alltoall_single",
    "broadcast", "reduce", "scatter", "reduce_scatter", "barrier", "wait",
    "send", "recv", "isend", "irecv", "gather", "ppermute",
    "all_gather_object", "broadcast_object_list", "scatter_object_list",
    "destroy_process_group", "get_backend", "is_available",
    "shard_tensor", "sharding_constraint", "shard_parameter", "replicate",
    "get_sharding", "PartitionSpec", "ProcessMesh", "DistAttr",
    "ParallelMode", "split",
    "init_mesh", "get_mesh", "get_env", "AXIS_ORDER",
    "fleet", "io", "launch", "checkpoint", "sharding", "rpc", "passes",
    "gloo_init_parallel_env", "gloo_barrier", "gloo_release",
    "InMemoryDataset", "QueueDataset", "CountFilterEntry",
    "ProbabilityEntry", "ShowClickEntry",
]
