"""`paddle.hub` parity (reference `python/paddle/hub.py` -> `hapi/hub.py`):
load entrypoints from a hubconf.py.

No-egress environment: only ``source='local'`` works; github/gitee sources
raise with a clear message instead of attempting a download.
"""
from __future__ import annotations

import importlib.util
import os
import sys

__all__ = ["list", "help", "load"]

_HUBCONF = "hubconf.py"


def _load_hubconf(repo_dir):
    path = os.path.join(repo_dir, _HUBCONF)
    if not os.path.isfile(path):
        raise FileNotFoundError(
            f"no {_HUBCONF} found in {repo_dir!r}")
    spec = importlib.util.spec_from_file_location("paddle_tpu_hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    sys.path.insert(0, repo_dir)
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.path.remove(repo_dir)
    return mod


def _check_source(source):
    if source != "local":
        raise RuntimeError(
            f"paddle.hub source {source!r} needs network access, which this "
            "build does not have; clone the repo and use source='local'")


def list(repo_dir, source="local", force_reload=False):  # noqa: A001
    """Entrypoint names exposed by the repo's hubconf.py."""
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    return [k for k, v in vars(mod).items()
            if callable(v) and not k.startswith("_")]


def help(repo_dir, model, source="local", force_reload=False):  # noqa: A001
    """Docstring of one entrypoint."""
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    if not hasattr(mod, model):
        raise RuntimeError(f"hubconf has no entrypoint {model!r}")
    return getattr(mod, model).__doc__


def load(repo_dir, model, source="local", force_reload=False, **kwargs):
    """Call entrypoint ``model`` from the repo's hubconf.py."""
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    if not hasattr(mod, model):
        raise RuntimeError(f"hubconf has no entrypoint {model!r}")
    return getattr(mod, model)(**kwargs)
