"""`paddle.nn.utils` (parity: `python/paddle/nn/utils/`)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ...framework.core import Tensor
from ..clip import clip_grad_norm_, clip_grad_value_  # noqa: F401


def parameters_to_vector(parameters, name=None):
    arrays = [p._data.reshape(-1) for p in parameters]
    return Tensor(jnp.concatenate(arrays))


def vector_to_parameters(vec, parameters, name=None):
    offset = 0
    v = vec._data if isinstance(vec, Tensor) else jnp.asarray(vec)
    for p in parameters:
        n = p.size
        p._data = v[offset: offset + n].reshape(p._data.shape).astype(p._data.dtype)
        offset += n


def weight_norm(layer, name="weight", dim=0):
    """Reparameterize weight = g * v / ||v|| (parity:
    `python/paddle/nn/utils/weight_norm_hook.py`). Implemented with a
    forward-pre-hook that recomputes the weight from (g, v) each call."""
    import math

    weight = getattr(layer, name)
    w = weight._data
    if dim is None:
        norm = jnp.linalg.norm(w.reshape(-1))
        v = w
    else:
        moved = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
        norm = jnp.linalg.norm(moved, axis=1)
        v = w
    from ...framework.core import EagerParamBase

    g = EagerParamBase(norm)
    v_p = EagerParamBase(v)
    layer.add_parameter(name + "_g", g)
    layer.add_parameter(name + "_v", v_p)
    # remove original param from registry; keep plain attr for forward use
    del layer._parameters[name]

    def compute(layer_, _inputs):
        vv = v_p._data if not hasattr(v_p, "_tape_val") else v_p._data
        from ...ops.dispatch import apply

        def f(g_a, v_a):
            if dim is None:
                vn = jnp.linalg.norm(v_a.reshape(-1))
                return g_a * v_a / vn
            moved_ = jnp.moveaxis(v_a, dim, 0)
            flat = moved_.reshape(moved_.shape[0], -1)
            vn = jnp.linalg.norm(flat, axis=1)
            shape = (-1,) + (1,) * (moved_.ndim - 1)
            out = moved_ * (g_a.reshape(shape) / vn.reshape(shape))
            return jnp.moveaxis(out, 0, dim)

        new_w = apply("weight_norm", f, (g, v_p))
        object.__setattr__(layer_, name, new_w)
        return None

    hook = layer.register_forward_pre_hook(compute)
    layer._weight_norm_hook = hook
    layer._weight_norm_name = name
    compute(layer, None)
    return layer


def remove_weight_norm(layer, name="weight"):
    hook = getattr(layer, "_weight_norm_hook", None)
    if hook is not None:
        hook.remove()
    g = layer._parameters.pop(name + "_g", None)
    v = layer._parameters.pop(name + "_v", None)
    if g is not None and v is not None:
        import jax.numpy as jnp

        w = getattr(layer, name)
        from ...framework.core import EagerParamBase

        layer.add_parameter(name, EagerParamBase(w._data))
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12, dim=None):
    """Parity: `python/paddle/nn/utils/spectral_norm_hook.py`."""
    weight = getattr(layer, name)
    if dim is None:
        dim = 0
    from ..layer.norm import SpectralNorm as _SN

    sn = _SN(weight.shape, dim=dim, power_iters=n_power_iterations, eps=eps)
    layer.add_sublayer(name + "_sn", sn)
    orig = layer._parameters.pop(name)
    layer.add_parameter(name + "_orig", orig)

    def compute(layer_, _inputs):
        object.__setattr__(layer_, name, sn(orig))
        return None

    layer.register_forward_pre_hook(compute)
    compute(layer, None)
    return layer
