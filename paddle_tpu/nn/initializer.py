"""Weight initializers.

Reference parity: `python/paddle/nn/initializer/` (Constant, Normal,
TruncatedNormal, Uniform, Xavier*, Kaiming*, Assign, Orthogonal, Dirac) —
the reference implements these as ops appended to the startup program /
eager fills; here each initializer is a pure function of (shape, dtype, key).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtype as dtype_mod
from ..framework import random as rng
from ..framework.core import Tensor


def calculate_gain(nonlinearity, param=None):
    gains = {
        "sigmoid": 1.0,
        "linear": 1.0,
        "conv1d": 1.0, "conv2d": 1.0, "conv3d": 1.0,
        "conv1d_transpose": 1.0, "conv2d_transpose": 1.0, "conv3d_transpose": 1.0,
        "tanh": 5.0 / 3.0,
        "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param if param is not None else 0.01) ** 2)),
        "selu": 3.0 / 4.0,
    }
    if nonlinearity not in gains:
        raise ValueError(f"unsupported nonlinearity: {nonlinearity}")
    return gains[nonlinearity]


def _fan_in_out(shape):
    shape = tuple(shape)
    if len(shape) < 2:
        fan_in = fan_out = shape[0] if shape else 1
    else:
        # conv weights are [out_c, in_c, *kernel]; linear is [in, out]
        receptive = math.prod(shape[2:]) if len(shape) > 2 else 1
        if len(shape) > 2:
            fan_in = shape[1] * receptive
            fan_out = shape[0] * receptive
        else:
            fan_in, fan_out = shape[0], shape[1]
    return fan_in, fan_out


class Initializer:
    def __call__(self, shape, dtype=None, key=None):
        raise NotImplementedError

    def _key(self, key):
        return key if key is not None else rng.next_key()


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype=None, key=None):
        d = dtype_mod.convert_dtype(dtype) if dtype else dtype_mod.get_default_dtype()
        return jnp.full(tuple(shape), self.value, d)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=None, key=None):
        d = dtype_mod.convert_dtype(dtype) if dtype else dtype_mod.get_default_dtype()
        out = jax.random.normal(self._key(key), tuple(shape), jnp.float32)
        return (out * self.std + self.mean).astype(d)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype=None, key=None):
        d = dtype_mod.convert_dtype(dtype) if dtype else dtype_mod.get_default_dtype()
        out = jax.random.truncated_normal(
            self._key(key), self.a, self.b, tuple(shape), jnp.float32
        )
        return (out * self.std + self.mean).astype(d)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype=None, key=None):
        d = dtype_mod.convert_dtype(dtype) if dtype else dtype_mod.get_default_dtype()
        out = jax.random.uniform(
            self._key(key), tuple(shape), jnp.float32, self.low, self.high
        )
        return out.astype(d)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=None, key=None):
        fi, fo = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return Uniform(-limit, limit)(shape, dtype, key)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=None, key=None):
        fi, fo = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return Normal(0.0, std)(shape, dtype, key)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype=None, key=None):
        fi, _ = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        return Uniform(-limit, limit)(shape, dtype, key)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype=None, key=None):
        fi, _ = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        return Normal(0.0, std)(shape, dtype, key)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype=None, key=None):
        d = dtype_mod.convert_dtype(dtype) if dtype else dtype_mod.get_default_dtype()
        v = self.value
        if isinstance(v, Tensor):
            v = v.numpy()
        arr = jnp.asarray(np.asarray(v), d)
        if tuple(arr.shape) != tuple(shape):
            arr = arr.reshape(tuple(shape))
        return arr


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype=None, key=None):
        d = dtype_mod.convert_dtype(dtype) if dtype else dtype_mod.get_default_dtype()
        shape = tuple(shape)
        rows = shape[0]
        cols = math.prod(shape[1:])
        flat = jax.random.normal(self._key(key), (max(rows, cols), min(rows, cols)))
        q, r = jnp.linalg.qr(flat)
        q = q * jnp.sign(jnp.diagonal(r))
        if rows < cols:
            q = q.T
        return (self.gain * q[:rows, :cols]).reshape(shape).astype(d)


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype=None, key=None):
        d = dtype_mod.convert_dtype(dtype) if dtype else dtype_mod.get_default_dtype()
        out_c, in_c = shape[0], shape[1]
        kernel = shape[2:]
        w = np.zeros(tuple(shape), np.float32)
        center = tuple(k // 2 for k in kernel)
        per_group = out_c // self.groups
        for g in range(self.groups):
            for i in range(min(per_group, in_c)):
                w[(g * per_group + i, i) + center] = 1.0
        return jnp.asarray(w, d)


# paddle aliases
constant = Constant
normal = Normal
uniform = Uniform


def set_global_initializer(weight_init, bias_init=None):
    from . import layer as _layer_mod  # noqa

    _GLOBAL[0] = weight_init
    _GLOBAL[1] = bias_init


_GLOBAL = [None, None]


class Bilinear(Initializer):
    """Bilinear-interpolation kernel init for transposed-conv upsampling
    (parity: paddle.nn.initializer.Bilinear): weight [C_out, C_in, K, K]
    gets the standard bilinear upsampling stencil per channel pair's
    diagonal."""

    def __call__(self, shape, dtype=None, key=None):
        import numpy as np

        d = dtype_mod.convert_dtype(dtype) if dtype \
            else dtype_mod.get_default_dtype()
        if len(shape) != 4:
            raise ValueError(
                f"Bilinear initializer expects a 4-D conv weight, got "
                f"shape {list(shape)}")
        if shape[2] != shape[3]:
            raise ValueError(
                "Bilinear initializer requires a square kernel "
                f"(got {shape[2]}x{shape[3]})")
        kh, kw = shape[2], shape[3]
        f_h = (kh + 1) // 2
        c_h = (2 * f_h - 1 - f_h % 2) / (2.0 * f_h)
        og = np.ogrid[:kh, :kw]
        filt = ((1 - abs(og[0] / f_h - c_h))
                * (1 - abs(og[1] / f_h - c_h)))
        # reference fills EVERY channel pair with the stencil
        # (`nn/initializer/Bilinear.py:108`)
        w = np.broadcast_to(filt, tuple(shape)).astype(np.float32)
        return jnp.asarray(w, d)
