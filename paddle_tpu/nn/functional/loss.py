"""Loss functions.

Reference parity: `python/paddle/nn/functional/loss.py` over PHI
cross_entropy / bce / smooth_l1 / kldiv kernels
(`phi/kernels/gpu/cross_entropy_kernel.cu` etc.).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.core import Tensor
from ...ops.dispatch import apply


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def cross_entropy(
    input,  # noqa: A002
    label,
    weight=None,
    ignore_index=-100,
    reduction="mean",
    soft_label=False,
    axis=-1,
    use_softmax=True,
    label_smoothing=0.0,
    name=None,
):
    """Parity: paddle.nn.functional.cross_entropy — fused
    softmax+cross-entropy (the reference's `softmax_with_cross_entropy`
    kernel); computed via log_softmax + gather so XLA emits one fused
    kernel with a numerically-stable logsumexp."""
    has_w = weight is not None
    operands = [input, label] + ([weight] if has_w else [])

    def f(logits, lab, *rest):
        ax = axis % logits.ndim
        logp = jax.nn.log_softmax(logits, axis=ax) if use_softmax else jnp.log(
            jnp.maximum(logits, 1e-30)
        )
        if soft_label or (lab.ndim == logits.ndim and lab.shape == logits.shape
                          and jnp.issubdtype(lab.dtype, jnp.floating)):
            tgt = lab
            if label_smoothing > 0:
                k = logits.shape[ax]
                tgt = tgt * (1 - label_smoothing) + label_smoothing / k
            loss = -jnp.sum(tgt * logp, axis=ax)
            return _reduce(loss, reduction)
        lab_idx = lab
        if lab_idx.ndim == logits.ndim:  # trailing 1 dim
            lab_idx = jnp.squeeze(lab_idx, axis=ax)
        lab_idx = lab_idx.astype(jnp.int32)
        valid = lab_idx != ignore_index
        safe = jnp.where(valid, lab_idx, 0)
        picked = jnp.take_along_axis(
            logp, jnp.expand_dims(safe, ax), axis=ax
        ).squeeze(ax)
        if label_smoothing > 0:
            k = logits.shape[ax]
            smooth = -jnp.mean(logp, axis=ax)
            loss = (1 - label_smoothing) * (-picked) + label_smoothing * smooth
        else:
            loss = -picked
        if has_w:
            w = rest[0]
            loss = loss * jnp.take(w, safe)
        loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            if has_w:
                denom = jnp.sum(jnp.take(rest[0], safe) * valid)
            else:
                denom = jnp.maximum(jnp.sum(valid), 1)
            return jnp.sum(loss) / denom
        if reduction == "sum":
            return jnp.sum(loss)
        return loss

    return apply("cross_entropy", f, tuple(operands))


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False, axis=-1):
    loss = cross_entropy(
        logits, label, soft_label=soft_label, ignore_index=ignore_index,
        reduction="none", axis=axis,
    )
    from .activation import softmax as _softmax
    from ...tensor.manipulation import unsqueeze
    loss = unsqueeze(loss, axis)
    if return_softmax:
        return loss, _softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):  # noqa: A002
    has_w = weight is not None
    operands = [input, label] + ([weight] if has_w else [])
    def f(logp, lab, *rest):
        lab = lab.astype(jnp.int32)
        valid = lab != ignore_index
        safe = jnp.where(valid, lab, 0)
        picked = jnp.take_along_axis(logp, safe[:, None], axis=1).squeeze(1)
        loss = -picked
        if has_w:
            loss = loss * jnp.take(rest[0], safe)
        loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            denom = (
                jnp.sum(jnp.take(rest[0], safe) * valid) if has_w
                else jnp.maximum(jnp.sum(valid), 1)
            )
            return jnp.sum(loss) / denom
        if reduction == "sum":
            return jnp.sum(loss)
        return loss
    return apply("nll_loss", f, tuple(operands))


def mse_loss(input, label, reduction="mean", name=None):  # noqa: A002
    return apply(
        "mse_loss", lambda a, b: _reduce((a - b) ** 2, reduction), (input, label)
    )


def l1_loss(input, label, reduction="mean", name=None):  # noqa: A002
    return apply(
        "l1_loss", lambda a, b: _reduce(jnp.abs(a - b), reduction), (input, label)
    )


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):  # noqa: A002
    def f(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
        return _reduce(loss, reduction)
    return apply("smooth_l1_loss", f, (input, label))


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):  # noqa: A002
    has_w = weight is not None
    operands = [input, label] + ([weight] if has_w else [])
    def f(p, t, *rest):
        p = jnp.clip(p, 1e-12, 1 - 1e-7)
        loss = -(t * jnp.log(p) + (1 - t) * jnp.log1p(-p))
        if has_w:
            loss = loss * rest[0]
        return _reduce(loss, reduction)
    return apply("binary_cross_entropy", f, tuple(operands))


def binary_cross_entropy_with_logits(
    logit, label, weight=None, reduction="mean", pos_weight=None, name=None
):
    has_w = weight is not None
    has_pw = pos_weight is not None
    operands = [logit, label]
    if has_w:
        operands.append(weight)
    if has_pw:
        operands.append(pos_weight)
    def f(z, t, *rest):
        # numerically stable: max(z,0) - z*t + log(1+exp(-|z|))
        base = jnp.maximum(z, 0) - z * t + jnp.log1p(jnp.exp(-jnp.abs(z)))
        i = 0
        if has_pw:
            pw = rest[-1]
            logsig = jax.nn.log_sigmoid(z)
            logsig_neg = jax.nn.log_sigmoid(-z)
            base = -(pw * t * logsig + (1 - t) * logsig_neg)
        if has_w:
            base = base * rest[0]
        return _reduce(base, reduction)
    return apply("bce_with_logits", f, tuple(operands))


def kl_div(input, label, reduction="mean", log_target=False, name=None):  # noqa: A002
    def f(logp, t):
        if log_target:
            loss = jnp.exp(t) * (t - logp)
        else:
            safe_t = jnp.maximum(t, 1e-12)
            loss = t * (jnp.log(safe_t) - logp)
            loss = jnp.where(t > 0, loss, 0.0)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce(loss, reduction)
    return apply("kl_div", f, (input, label))


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):  # noqa: A002
    def f(a, b, t):
        return _reduce(jnp.maximum(0.0, -t * (a - b) + margin), reduction)
    return apply("margin_ranking_loss", f, (input, other, label))


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):  # noqa: A002
    def f(a, t):
        loss = jnp.where(t == 1, a, jnp.maximum(0.0, margin - a))
        return _reduce(loss, reduction)
    return apply("hinge_embedding_loss", f, (input, label))


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean", name=None):
    def f(a, b, t):
        cos = jnp.sum(a * b, -1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12
        )
        loss = jnp.where(t == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)
    return apply("cosine_embedding_loss", f, (input1, input2, label))


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,  # noqa: A002
                        epsilon=1e-6, swap=False, reduction="mean", name=None):
    def f(a, pos, neg):
        dp = jnp.linalg.norm(a - pos + epsilon, ord=p, axis=-1)
        dn = jnp.linalg.norm(a - neg + epsilon, ord=p, axis=-1)
        if swap:
            dn2 = jnp.linalg.norm(pos - neg + epsilon, ord=p, axis=-1)
            dn = jnp.minimum(dn, dn2)
        return _reduce(jnp.maximum(0.0, dp - dn + margin), reduction)
    return apply("triplet_margin_loss", f, (input, positive, negative))


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean", name=None):  # noqa: A002
    has_w = weight is not None
    operands = [input, label] + ([weight] if has_w else [])
    def f(z, t, *rest):
        loss = -(t * jax.nn.log_sigmoid(z) + (1 - t) * jax.nn.log_sigmoid(-z))
        if has_w:
            loss = loss * rest[0]
        return _reduce(jnp.mean(loss, axis=-1), reduction)
    return apply("multi_label_soft_margin_loss", f, tuple(operands))


def soft_margin_loss(input, label, reduction="mean", name=None):  # noqa: A002
    def f(z, t):
        return _reduce(jnp.log1p(jnp.exp(-t * z)), reduction)
    return apply("soft_margin_loss", f, (input, label))


def square_error_cost(input, label):  # noqa: A002
    return apply("square_error_cost", lambda a, b: (a - b) ** 2, (input, label))


def log_loss(input, label, epsilon=1e-4, name=None):  # noqa: A002
    def f(p, t):
        return -t * jnp.log(p + epsilon) - (1 - t) * jnp.log(1 - p + epsilon)
    return apply("log_loss", f, (input, label))


def chunked_softmax_cross_entropy(hidden, weight, label, chunk_size,
                                  name=None):
    """Per-token CE of ``softmax(hidden @ weight)`` WITHOUT materializing
    the ``[N, V]`` float32 logits: online logsumexp over vocab chunks of
    ``chunk_size``, each chunk rematerialized in backward
    (``jax.checkpoint``), so live memory is O(N·chunk) instead of O(N·V).

    TPU-first design: for large-vocab LM heads the fp32 logits tensor is
    an HBM-bandwidth tax (b4·s1024·V32000·4B = 0.5 GB per step at the
    headline bench shape); the reference pays it
    (`phi/kernels/gpu/cross_entropy_kernel.cu` consumes materialized
    logits). Labels outside [0, V) yield 0 — the same contract as
    ``F.cross_entropy`` with ignored labels.

    Args: hidden [..., H]; weight [H, V]; label [...] int. Returns
    per-token loss with label's shape.
    """
    if chunk_size <= 0:
        raise ValueError(
            f"chunked_softmax_cross_entropy: chunk_size must be > 0, "
            f"got {chunk_size}")

    def f(h, w, lab):
        hd = h.reshape(-1, h.shape[-1])
        n = hd.shape[0]
        v = w.shape[1]
        chunk = int(min(chunk_size, v))
        n_chunks = -(-v // chunk)
        pad = n_chunks * chunk - v
        # chunk-divisible vocab (the common config) slices the weight in
        # place; only a ragged tail pays one padded copy
        wp = w if pad == 0 else jnp.pad(w, ((0, 0), (0, pad)))
        labf = lab.reshape(-1)
        m0 = jnp.full((n,), -1e30, jnp.float32)
        s0 = jnp.zeros((n,), jnp.float32)
        ll0 = jnp.zeros((n,), jnp.float32)

        def inner(hd, wp, c0, m, s, ll):
            wc = jax.lax.dynamic_slice_in_dim(wp, c0, chunk, axis=1)
            logits = jax.lax.dot(
                hd, wc, preferred_element_type=jnp.float32)
            col = c0 + jnp.arange(chunk)
            logits = jnp.where(col[None, :] < v, logits, -1e30)
            m_new = jnp.maximum(m, logits.max(-1))
            s_new = s * jnp.exp(m - m_new) + jnp.exp(
                logits - m_new[:, None]).sum(-1)
            in_chunk = (labf >= c0) & (labf < c0 + chunk)
            gathered = jnp.take_along_axis(
                logits, jnp.clip(labf - c0, 0, chunk - 1)[:, None],
                1)[:, 0]
            return m_new, s_new, ll + jnp.where(in_chunk, gathered, 0.0)

        def body(carry, idx):
            m, s, ll = jax.checkpoint(inner)(
                hd, wp, idx * chunk, *carry)
            return (m, s, ll), None

        (m, s, ll), _ = jax.lax.scan(
            body, (m0, s0, ll0), jnp.arange(n_chunks))
        per_tok = m + jnp.log(jnp.maximum(s, 1e-30)) - ll
        per_tok = jnp.where((labf >= 0) & (labf < v), per_tok, 0.0)
        return per_tok.reshape(lab.shape)

    return apply("chunked_lm_ce", f, (hidden, weight, label))


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC via the standard alpha recursion in log space with lax.scan
    (the reference links warpctc; here it's a pure XLA scan).
    log_probs: [T, B, C] (paddle layout), labels: [B, L]."""
    def f(lp, lab, in_len, lab_len):
        T, B, C = lp.shape
        L = lab.shape[1]
        S = 2 * L + 1
        # extended label sequence: blank l1 blank l2 ... blank
        ext = jnp.full((B, S), blank, dtype=jnp.int32)
        ext = ext.at[:, 1::2].set(lab.astype(jnp.int32))
        neg_inf = jnp.asarray(-1e30, lp.dtype)
        # transition mask: allow skip from s-2 when ext[s] != blank and
        # ext[s] != ext[s-2]
        ext_prev2 = jnp.pad(ext[:, :-2], ((0, 0), (2, 0)), constant_values=-1)
        can_skip = (ext != blank) & (ext != ext_prev2)
        init = jnp.full((B, S), neg_inf)
        init = init.at[:, 0].set(lp[0, jnp.arange(B), ext[:, 0]])
        init = init.at[:, 1].set(
            jnp.where(L > 0, lp[0, jnp.arange(B), ext[:, 1]], neg_inf)
        )
        def step(alpha, lp_t):
            a_prev = alpha
            a_shift1 = jnp.pad(alpha[:, :-1], ((0, 0), (1, 0)), constant_values=-1e30)
            a_shift2 = jnp.pad(alpha[:, :-2], ((0, 0), (2, 0)), constant_values=-1e30)
            a_shift2 = jnp.where(can_skip, a_shift2, neg_inf)
            merged = jnp.logaddexp(jnp.logaddexp(a_prev, a_shift1), a_shift2)
            emit = jnp.take_along_axis(lp_t, ext, axis=1)
            return merged + emit, merged + emit
        _, alphas = jax.lax.scan(step, init, lp[1:])
        alphas = jnp.concatenate([init[None], alphas], axis=0)  # [T, B, S]
        t_idx = (in_len.astype(jnp.int32) - 1)
        last = alphas[t_idx, jnp.arange(B)]  # [B, S]
        send = 2 * lab_len.astype(jnp.int32)
        p_blank = jnp.take_along_axis(last, send[:, None], axis=1)[:, 0]
        p_label = jnp.take_along_axis(
            last, jnp.maximum(send - 1, 0)[:, None], axis=1
        )[:, 0]
        ll = jnp.logaddexp(p_blank, jnp.where(lab_len > 0, p_label, neg_inf))
        loss = -ll
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(lab_len.astype(lp.dtype), 1))
        if reduction == "sum":
            return jnp.sum(loss)
        return loss
    return apply("ctc_loss", f, (log_probs, labels, input_lengths, label_lengths))


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    has_n = normalizer is not None
    operands = [logit, label] + ([normalizer] if has_n else [])
    def f(z, t, *rest):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0) - z * t + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * t + (1 - p) * (1 - t)
        a_t = alpha * t + (1 - alpha) * (1 - t)
        loss = a_t * ((1 - p_t) ** gamma) * ce
        if has_n:
            loss = loss / rest[0]
        return _reduce(loss, reduction)
    return apply("sigmoid_focal_loss", f, tuple(operands))


def huber_loss(input, label, delta=1.0, reduction="mean", name=None):  # noqa: A002
    """Quadratic below ``delta``, linear above (parity: F.huber_loss —
    note paddle's huber is smooth_l1 scaled by delta:
    0.5*r^2 if |r|<=delta else delta*(|r|-0.5*delta))."""

    def f(a, b):
        r = jnp.abs(a - b)
        return jnp.where(r <= delta, 0.5 * r * r,
                         delta * (r - 0.5 * delta))

    return _reduce(apply("huber_loss", f, (input, label)), reduction)


def edit_distance(input, label, normalized=True, ignored_tokens=None,  # noqa: A002
                  input_length=None, label_length=None, name=None):
    """Batched Levenshtein distance (parity: F.edit_distance, ref
    `nn/functional/loss.py:451`, `edit_distance` op).

    Returns (distance [batch, 1] float32, sequence_num [1] int64). The DP
    recurrence runs as a `lax.scan` over hypothesis tokens with the
    classic one-row formulation — O(batch·|input|·|label|) on device, no
    host loop."""

    def fn(hyp, ref, hyp_len, ref_len):
        b, li = hyp.shape
        lr = ref.shape[1]
        cols = jnp.arange(lr + 1, dtype=jnp.float32)

        def step(row_prev, xs):
            # row_prev: [b, lr+1] = distances for first i-1 hyp tokens
            h_tok, i = xs  # h_tok: [b]
            in_range = (i < hyp_len)[:, None]  # [b, 1]
            sub = row_prev[:, :-1] + jnp.where(
                ref == h_tok[:, None], 0.0, 1.0)  # [b, lr]
            dele = row_prev[:, 1:] + 1.0
            first = row_prev[:, :1] + 1.0  # j=0: i deletions

            def inner(carry, xs2):
                s, d = xs2  # [b], [b]
                val = jnp.minimum(jnp.minimum(s, d), carry + 1.0)
                return val, val

            _, rest = jax.lax.scan(
                inner, first[:, 0], (sub.T, dele.T))
            row = jnp.concatenate([first, rest.T], axis=1)
            # past the hypothesis end the row stops updating
            row = jnp.where(in_range, row, row_prev)
            return row, None

        row0 = jnp.broadcast_to(cols, (b, lr + 1))
        # column beyond the reference length is ignored at the end
        rowN, _ = jax.lax.scan(
            step, row0, (hyp.T, jnp.arange(li)))
        dist = jnp.take_along_axis(rowN, ref_len[:, None], axis=1)
        # rows where the hyp is empty: distance = ref_len
        dist = jnp.where(hyp_len[:, None] == 0,
                         ref_len[:, None].astype(jnp.float32), dist)
        dist = jnp.where((ref_len[:, None] == 0) & (hyp_len[:, None] > 0),
                         hyp_len[:, None].astype(jnp.float32), dist)
        if normalized:
            denom = jnp.maximum(ref_len[:, None].astype(jnp.float32), 1.0)
            dist = dist / denom
        # int64 intent, silently canonicalized to the x32 default like
        # every other integer tensor in the framework (explicit jnp.int64
        # would emit a truncation warning per call)
        return dist.astype(jnp.float32), jnp.asarray(np.asarray([b],
                                                                np.int64))

    from ...framework.core import Tensor as _T

    def _arr(x):
        return x._data if isinstance(x, _T) else jnp.asarray(x)

    hyp, ref = _arr(input), _arr(label)
    if ignored_tokens:
        # drop ignored tokens host-side (ragged -> repack right-padded)
        import numpy as _np

        def repack(a):
            a = _np.asarray(a)
            rows, lens = [], []
            for r in a:
                keep = r[~_np.isin(r, ignored_tokens)]
                rows.append(keep)
                lens.append(len(keep))
            out = _np.zeros((len(rows), max(lens) if lens else 0), a.dtype)
            for i, r in enumerate(rows):
                out[i, :len(r)] = r
            return jnp.asarray(out), jnp.asarray(_np.asarray(lens, _np.int64))

        hyp, hl = repack(hyp)
        ref, rl = repack(ref)
    else:
        hl = (_arr(input_length).astype(jnp.int32) if input_length is not None
              else jnp.full((hyp.shape[0],), hyp.shape[1], jnp.int32))
        rl = (_arr(label_length).astype(jnp.int32) if label_length is not None
              else jnp.full((ref.shape[0],), ref.shape[1], jnp.int32))
    from ...ops.dispatch import apply_nondiff

    return apply_nondiff("edit_distance", fn, (hyp, ref, hl, rl))


import functools as _functools


@_functools.lru_cache(maxsize=32)
def _simple_code_tables(num_classes):
    """SimpleCode path tables (reference MatrixBitCodeFunctor): for class
    c, code = c + num_classes; walking bits from the MSB-1 down gives node
    index (code >> k) - 1 and branch bit. Cached per num_classes — hsigmoid
    exists for large vocabularies, so the O(C log C) host loop must run
    once, not per training step."""
    max_len = int(np.ceil(np.log2(max(num_classes, 2))))
    tbl = np.full((num_classes, max_len), -1, np.int32)
    code_bits = np.zeros((num_classes, max_len), np.float32)
    for c in range(num_classes):
        code = c + num_classes
        length = code.bit_length() - 1
        for j in range(length):
            tbl[c, j] = (code >> (length - j)) - 1
            code_bits[c, j] = (code >> (length - 1 - j)) & 1
    return jnp.asarray(tbl), jnp.asarray(code_bits)


def hsigmoid_loss(input, label, num_classes, weight, bias=None,  # noqa: A002
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid loss (parity: F.hsigmoid_loss, ref
    `nn/functional/loss.py`, `hsigmoid_loss` op / MatrixBitCodeFunctor).

    Default tree: the complete binary tree the reference's SimpleCode
    uses — for class c the path of internal nodes is derived from the
    binary representation of (c + num_classes). Custom trees via
    path_table/path_code [batch, path_len] (-1 padded)."""
    from ...framework.core import Tensor as _T

    lab = label._data if isinstance(label, _T) else jnp.asarray(label)
    lab = lab.reshape(-1)

    if path_table is None:
        table_all, bits_all = _simple_code_tables(num_classes)
        ptab = jnp.take(table_all, lab, axis=0)
        pcode = jnp.take(bits_all, lab, axis=0)
    else:
        ptab = (path_table._data if isinstance(path_table, _T)
                else jnp.asarray(path_table)).astype(jnp.int32)
        pcode = (path_code._data if isinstance(path_code, _T)
                 else jnp.asarray(path_code)).astype(jnp.float32)

    def _uncommit(a):
        # concrete closure constants must not carry a device commitment:
        # under a distributed mesh the weights are mesh-placed, and jit
        # rejects mixing them with cpu:0-committed captures
        if isinstance(a, jax.Array) and not isinstance(a, jax.core.Tracer):
            return np.asarray(a)
        return a

    ptab = _uncommit(ptab)
    pcode = _uncommit(pcode)

    def fn(x, w, *maybe_bias):
        valid = (ptab >= 0).astype(x.dtype)  # [b, L]
        idx = jnp.maximum(ptab, 0)
        wn = jnp.take(w, idx, axis=0)  # [b, L, d]
        logits = jnp.einsum("bd,bld->bl", x, wn)
        if maybe_bias:
            logits = logits + jnp.take(maybe_bias[0].reshape(-1), idx, axis=0)
        # bce-with-logits against the branch bit, masked to the real path
        per_node = jnp.maximum(logits, 0) - logits * pcode.astype(x.dtype) \
            + jnp.log1p(jnp.exp(-jnp.abs(logits)))
        return jnp.sum(per_node * valid, axis=1, keepdims=True)

    operands = (input, weight) + ((bias,) if bias is not None else ())
    return apply("hsigmoid_loss", fn, operands)


def poisson_nll_loss(input, label, log_input=True, full=False,  # noqa: A002
                     epsilon=1e-8, reduction="mean", name=None):
    """Poisson NLL (parity: paddle.nn.functional.poisson_nll_loss)."""

    def f(x, t):
        if log_input:
            out = jnp.exp(x) - t * x
        else:
            out = x - t * jnp.log(x + epsilon)
        if full:
            # Stirling approximation for log(t!) at t > 1
            stirling = t * jnp.log(t) - t + 0.5 * jnp.log(2 * jnp.pi * t)
            out = out + jnp.where(t > 1, stirling, jnp.zeros((), x.dtype))
        return _reduce(out, reduction)

    return apply("poisson_nll_loss", f, (input, label))


def gaussian_nll_loss(input, label, variance, full=False,  # noqa: A002
                      epsilon=1e-6, reduction="mean", name=None):
    """Gaussian NLL with predicted variance (parity:
    paddle.nn.functional.gaussian_nll_loss)."""

    def f(mu, t, var):
        var = jnp.maximum(var, epsilon)
        out = 0.5 * (jnp.log(var) + (t - mu) ** 2 / var)
        if full:
            out = out + 0.5 * jnp.log(jnp.asarray(2 * jnp.pi, mu.dtype))
        return _reduce(out, reduction)

    return apply("gaussian_nll_loss", f, (input, label, variance))


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,  # noqa: A002
                      reduction="mean", name=None):
    """Multi-class margin (hinge) loss (parity:
    paddle.nn.functional.multi_margin_loss). input [N, C], label [N]."""
    operands = (input, label) + ((weight,) if weight is not None else ())

    def f(x, t, *rest):
        n, c = x.shape
        t = t.reshape(-1).astype(jnp.int32)
        x_t = jnp.take_along_axis(x, t[:, None], axis=1)
        m = jnp.maximum(margin - x_t + x, 0.0) ** p
        if rest:
            m = m * rest[0][t][:, None]
        # the target class itself contributes 0
        m = m * (1 - jax.nn.one_hot(t, c, dtype=x.dtype))
        out = jnp.sum(m, axis=1) / c
        return _reduce(out, reduction)

    return apply("multi_margin_loss", f, operands)


def triplet_margin_with_distance_loss(input, positive, negative,  # noqa: A002
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean",
                                      name=None):
    """Triplet loss with a custom distance callable (parity:
    paddle.nn.functional.triplet_margin_with_distance_loss)."""
    if distance_function is None:
        def distance_function(a, b):
            return ((a - b) ** 2).sum(-1).sqrt()

    d_ap = distance_function(input, positive)
    d_an = distance_function(input, negative)
    if swap:
        d_pn = distance_function(positive, negative)
        d_an = apply("minimum", jnp.minimum, (d_an, d_pn))
    hinge = apply("relu", jax.nn.relu, (d_ap - d_an + margin,))
    if reduction == "none":
        return hinge
    return apply("reduce_" + reduction,
                 (jnp.mean if reduction == "mean" else jnp.sum), (hinge,))


def dice_loss(input, label, epsilon=1e-5, name=None):  # noqa: A002
    """Dice loss over the last (class-prob) axis (parity:
    paddle.nn.functional.dice_loss): input [..., C] probs, label [..., 1]."""

    def f(x, t):
        c = x.shape[-1]
        t1 = jax.nn.one_hot(t.squeeze(-1).astype(jnp.int32), c,
                            dtype=x.dtype)
        red = tuple(range(1, x.ndim))
        inter = jnp.sum(x * t1, axis=red)
        union = jnp.sum(x, axis=red) + jnp.sum(t1, axis=red)
        return jnp.mean(1.0 - (2.0 * inter + epsilon) / (union + epsilon))

    return apply("dice_loss", f, (input, label))


def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    """N-pair loss (parity: paddle.nn.functional.npair_loss)."""

    def f(a, p, lab):
        lab = lab.reshape(-1)
        sim = a @ p.T  # [N, N]
        tgt = (lab[:, None] == lab[None, :]).astype(a.dtype)
        tgt = tgt / jnp.maximum(jnp.sum(tgt, axis=1, keepdims=True), 1.0)
        logp = jax.nn.log_softmax(sim, axis=1)
        xent_r = -jnp.mean(jnp.sum(tgt * logp, axis=1))
        logp_c = jax.nn.log_softmax(sim.T, axis=1)
        xent_c = -jnp.mean(jnp.sum(tgt * logp_c, axis=1))
        reg = l2_reg * (jnp.mean(jnp.sum(a * a, axis=1))
                        + jnp.mean(jnp.sum(p * p, axis=1))) * 0.25
        return (xent_r + xent_c) / 2.0 + reg

    return apply("npair_loss", f, (anchor, positive, labels))


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,  # noqa: A002
              fastemit_lambda=0.001, reduction="mean", name=None):
    """RNN-Transducer loss (parity: paddle.nn.functional.rnnt_loss; the
    reference links warprnnt — here the (T, U) lattice runs as a pure XLA
    program: scan over T, and the within-row recurrence
    alpha(t,u) = logaddexp(b(u), alpha(t,u-1) + emit(u-1)) is solved in
    closed form with an associative log-cumsum-exp, so each row is
    parallel over U on the VPU instead of a sequential loop).

    input: [B, T, U+1, C] logits; label: [B, U].
    """

    def f(logits, lab, in_len, lab_len):
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        B, T, U1, _ = lp.shape
        lab = lab.astype(jnp.int32)
        blank_lp = lp[..., blank]                      # [B, T, U+1]
        emit_lp = jnp.take_along_axis(
            lp[:, :, :U1 - 1, :],
            jnp.broadcast_to(lab[:, None, :, None], (B, T, U1 - 1, 1)),
            axis=-1)[..., 0]                           # [B, T, U]
        if fastemit_lambda:
            # FastEmit (arXiv:2010.11148) as arc scaling: emit transitions
            # weighted up by (1+lambda) in log space (the k2-style loss
            # form of the paper's gradient blending), biasing alignments
            # toward earlier emissions
            emit_lp = emit_lp + float(np.log1p(fastemit_lambda))
        neg_inf = jnp.asarray(-1e30, jnp.float32)

        def logcumsumexp(z):
            # streaming logsumexp as an associative (max, scaled-sum) pair
            # — the flash-attention running-max trick, scan-parallel
            def comb(a, b):
                m1, s1 = a
                m2, s2 = b
                m = jnp.maximum(m1, m2)
                return m, s1 * jnp.exp(m1 - m) + s2 * jnp.exp(m2 - m)

            m, s = jax.lax.associative_scan(
                comb, (z, jnp.ones_like(z)), axis=-1)
            return m + jnp.log(s)

        def row_solve(b_row, e_row):
            # a(u) = logaddexp(b(u), a(u-1) + e(u-1)) solved as
            # a = Ecum + logcumsumexp(b - Ecum), Ecum(u) = sum_{w<u} e(w)
            ecum = jnp.concatenate(
                [jnp.zeros_like(e_row[..., :1]),
                 jnp.cumsum(e_row, axis=-1)], axis=-1)  # [B, U+1]
            return ecum + logcumsumexp(b_row - ecum)

        # t = 0 row: alpha(0,u) = cumsum of emit(0, :u)
        first_b = jnp.concatenate(
            [jnp.zeros((B, 1), jnp.float32),
             jnp.full((B, U1 - 1), neg_inf)], axis=-1)
        alpha0 = row_solve(first_b, emit_lp[:, 0])

        def step(alpha_prev, te):
            blank_t, emit_t = te
            b_row = alpha_prev + blank_t
            alpha_t = row_solve(b_row, emit_t)
            return alpha_t, alpha_t

        _, rows = jax.lax.scan(
            step, alpha0,
            (jnp.swapaxes(blank_lp[:, :-1], 0, 1),
             jnp.swapaxes(emit_lp[:, 1:], 0, 1)))
        alphas = jnp.concatenate([alpha0[None], rows], axis=0)  # [T, B, U+1]
        t_idx = in_len.astype(jnp.int32) - 1
        u_idx = lab_len.astype(jnp.int32)
        last = alphas[t_idx, jnp.arange(B)]                     # [B, U+1]
        a_end = jnp.take_along_axis(last, u_idx[:, None], axis=1)[:, 0]
        b_end = blank_lp[jnp.arange(B), t_idx, u_idx]
        loss = -(a_end + b_end)
        if reduction == "mean":
            return jnp.mean(loss)
        if reduction == "sum":
            return jnp.sum(loss)
        return loss

    return apply("rnnt_loss", f,
                 (input, label, input_lengths, label_lengths))


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean"):
    """Combined-margin softmax CE (ArcFace family; parity:
    paddle.nn.functional.margin_cross_entropy). The target-class cosine
    cos(theta) becomes cos(margin1*theta + margin2) - margin3, everything
    scaled by `scale`. Under model parallelism the sharded-logits variant
    is GSPMD's job: annotate the logits sharding and the same math
    compiles to the collective form the reference hand-writes."""

    def f(x, t):
        n, c = x.shape
        t = t.reshape(-1).astype(jnp.int32)
        cos_t = jnp.clip(jnp.take_along_axis(x, t[:, None], axis=1),
                         -1.0, 1.0)
        theta = jnp.arccos(cos_t)
        cos_m = jnp.cos(margin1 * theta + margin2) - margin3
        oh = jax.nn.one_hot(t, c, dtype=x.dtype)
        adj = x + oh * (cos_m - cos_t)
        z = adj * scale
        logp = jax.nn.log_softmax(z, axis=1)
        loss = -jnp.take_along_axis(logp, t[:, None], axis=1)[:, 0]
        sm = jnp.exp(logp)
        loss = _reduce(loss, reduction)
        return (loss, sm) if return_softmax else loss

    return apply("margin_cross_entropy", f, (logits, label))
