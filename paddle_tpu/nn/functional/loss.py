"""Loss functions.

Reference parity: `python/paddle/nn/functional/loss.py` over PHI
cross_entropy / bce / smooth_l1 / kldiv kernels
(`phi/kernels/gpu/cross_entropy_kernel.cu` etc.).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.core import Tensor
from ...ops.dispatch import apply


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def cross_entropy(
    input,  # noqa: A002
    label,
    weight=None,
    ignore_index=-100,
    reduction="mean",
    soft_label=False,
    axis=-1,
    use_softmax=True,
    label_smoothing=0.0,
    name=None,
):
    """Parity: paddle.nn.functional.cross_entropy — fused
    softmax+cross-entropy (the reference's `softmax_with_cross_entropy`
    kernel); computed via log_softmax + gather so XLA emits one fused
    kernel with a numerically-stable logsumexp."""
    has_w = weight is not None
    operands = [input, label] + ([weight] if has_w else [])

    def f(logits, lab, *rest):
        ax = axis % logits.ndim
        logp = jax.nn.log_softmax(logits, axis=ax) if use_softmax else jnp.log(
            jnp.maximum(logits, 1e-30)
        )
        if soft_label or (lab.ndim == logits.ndim and lab.shape == logits.shape
                          and jnp.issubdtype(lab.dtype, jnp.floating)):
            tgt = lab
            if label_smoothing > 0:
                k = logits.shape[ax]
                tgt = tgt * (1 - label_smoothing) + label_smoothing / k
            loss = -jnp.sum(tgt * logp, axis=ax)
            return _reduce(loss, reduction)
        lab_idx = lab
        if lab_idx.ndim == logits.ndim:  # trailing 1 dim
            lab_idx = jnp.squeeze(lab_idx, axis=ax)
        lab_idx = lab_idx.astype(jnp.int32)
        valid = lab_idx != ignore_index
        safe = jnp.where(valid, lab_idx, 0)
        picked = jnp.take_along_axis(
            logp, jnp.expand_dims(safe, ax), axis=ax
        ).squeeze(ax)
        if label_smoothing > 0:
            k = logits.shape[ax]
            smooth = -jnp.mean(logp, axis=ax)
            loss = (1 - label_smoothing) * (-picked) + label_smoothing * smooth
        else:
            loss = -picked
        if has_w:
            w = rest[0]
            loss = loss * jnp.take(w, safe)
        loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            if has_w:
                denom = jnp.sum(jnp.take(rest[0], safe) * valid)
            else:
                denom = jnp.maximum(jnp.sum(valid), 1)
            return jnp.sum(loss) / denom
        if reduction == "sum":
            return jnp.sum(loss)
        return loss

    return apply("cross_entropy", f, tuple(operands))


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False, axis=-1):
    loss = cross_entropy(
        logits, label, soft_label=soft_label, ignore_index=ignore_index,
        reduction="none", axis=axis,
    )
    from .activation import softmax as _softmax
    from ...tensor.manipulation import unsqueeze
    loss = unsqueeze(loss, axis)
    if return_softmax:
        return loss, _softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):  # noqa: A002
    has_w = weight is not None
    operands = [input, label] + ([weight] if has_w else [])
    def f(logp, lab, *rest):
        lab = lab.astype(jnp.int32)
        valid = lab != ignore_index
        safe = jnp.where(valid, lab, 0)
        picked = jnp.take_along_axis(logp, safe[:, None], axis=1).squeeze(1)
        loss = -picked
        if has_w:
            loss = loss * jnp.take(rest[0], safe)
        loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            denom = (
                jnp.sum(jnp.take(rest[0], safe) * valid) if has_w
                else jnp.maximum(jnp.sum(valid), 1)
            )
            return jnp.sum(loss) / denom
        if reduction == "sum":
            return jnp.sum(loss)
        return loss
    return apply("nll_loss", f, tuple(operands))


def mse_loss(input, label, reduction="mean", name=None):  # noqa: A002
    return apply(
        "mse_loss", lambda a, b: _reduce((a - b) ** 2, reduction), (input, label)
    )


def l1_loss(input, label, reduction="mean", name=None):  # noqa: A002
    return apply(
        "l1_loss", lambda a, b: _reduce(jnp.abs(a - b), reduction), (input, label)
    )


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):  # noqa: A002
    def f(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
        return _reduce(loss, reduction)
    return apply("smooth_l1_loss", f, (input, label))


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):  # noqa: A002
    has_w = weight is not None
    operands = [input, label] + ([weight] if has_w else [])
    def f(p, t, *rest):
        p = jnp.clip(p, 1e-12, 1 - 1e-7)
        loss = -(t * jnp.log(p) + (1 - t) * jnp.log1p(-p))
        if has_w:
            loss = loss * rest[0]
        return _reduce(loss, reduction)
    return apply("binary_cross_entropy", f, tuple(operands))


def binary_cross_entropy_with_logits(
    logit, label, weight=None, reduction="mean", pos_weight=None, name=None
):
    has_w = weight is not None
    has_pw = pos_weight is not None
    operands = [logit, label]
    if has_w:
        operands.append(weight)
    if has_pw:
        operands.append(pos_weight)
    def f(z, t, *rest):
        # numerically stable: max(z,0) - z*t + log(1+exp(-|z|))
        base = jnp.maximum(z, 0) - z * t + jnp.log1p(jnp.exp(-jnp.abs(z)))
        i = 0
        if has_pw:
            pw = rest[-1]
            logsig = jax.nn.log_sigmoid(z)
            logsig_neg = jax.nn.log_sigmoid(-z)
            base = -(pw * t * logsig + (1 - t) * logsig_neg)
        if has_w:
            base = base * rest[0]
        return _reduce(base, reduction)
    return apply("bce_with_logits", f, tuple(operands))


def kl_div(input, label, reduction="mean", log_target=False, name=None):  # noqa: A002
    def f(logp, t):
        if log_target:
            loss = jnp.exp(t) * (t - logp)
        else:
            safe_t = jnp.maximum(t, 1e-12)
            loss = t * (jnp.log(safe_t) - logp)
            loss = jnp.where(t > 0, loss, 0.0)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce(loss, reduction)
    return apply("kl_div", f, (input, label))


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):  # noqa: A002
    def f(a, b, t):
        return _reduce(jnp.maximum(0.0, -t * (a - b) + margin), reduction)
    return apply("margin_ranking_loss", f, (input, other, label))


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):  # noqa: A002
    def f(a, t):
        loss = jnp.where(t == 1, a, jnp.maximum(0.0, margin - a))
        return _reduce(loss, reduction)
    return apply("hinge_embedding_loss", f, (input, label))


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean", name=None):
    def f(a, b, t):
        cos = jnp.sum(a * b, -1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12
        )
        loss = jnp.where(t == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)
    return apply("cosine_embedding_loss", f, (input1, input2, label))


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,  # noqa: A002
                        epsilon=1e-6, swap=False, reduction="mean", name=None):
    def f(a, pos, neg):
        dp = jnp.linalg.norm(a - pos + epsilon, ord=p, axis=-1)
        dn = jnp.linalg.norm(a - neg + epsilon, ord=p, axis=-1)
        if swap:
            dn2 = jnp.linalg.norm(pos - neg + epsilon, ord=p, axis=-1)
            dn = jnp.minimum(dn, dn2)
        return _reduce(jnp.maximum(0.0, dp - dn + margin), reduction)
    return apply("triplet_margin_loss", f, (input, positive, negative))


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean", name=None):  # noqa: A002
    has_w = weight is not None
    operands = [input, label] + ([weight] if has_w else [])
    def f(z, t, *rest):
        loss = -(t * jax.nn.log_sigmoid(z) + (1 - t) * jax.nn.log_sigmoid(-z))
        if has_w:
            loss = loss * rest[0]
        return _reduce(jnp.mean(loss, axis=-1), reduction)
    return apply("multi_label_soft_margin_loss", f, tuple(operands))


def soft_margin_loss(input, label, reduction="mean", name=None):  # noqa: A002
    def f(z, t):
        return _reduce(jnp.log1p(jnp.exp(-t * z)), reduction)
    return apply("soft_margin_loss", f, (input, label))


def square_error_cost(input, label):  # noqa: A002
    return apply("square_error_cost", lambda a, b: (a - b) ** 2, (input, label))


def log_loss(input, label, epsilon=1e-4, name=None):  # noqa: A002
    def f(p, t):
        return -t * jnp.log(p + epsilon) - (1 - t) * jnp.log(1 - p + epsilon)
    return apply("log_loss", f, (input, label))


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC via the standard alpha recursion in log space with lax.scan
    (the reference links warpctc; here it's a pure XLA scan).
    log_probs: [T, B, C] (paddle layout), labels: [B, L]."""
    def f(lp, lab, in_len, lab_len):
        T, B, C = lp.shape
        L = lab.shape[1]
        S = 2 * L + 1
        # extended label sequence: blank l1 blank l2 ... blank
        ext = jnp.full((B, S), blank, dtype=jnp.int32)
        ext = ext.at[:, 1::2].set(lab.astype(jnp.int32))
        neg_inf = jnp.asarray(-1e30, lp.dtype)
        # transition mask: allow skip from s-2 when ext[s] != blank and
        # ext[s] != ext[s-2]
        ext_prev2 = jnp.pad(ext[:, :-2], ((0, 0), (2, 0)), constant_values=-1)
        can_skip = (ext != blank) & (ext != ext_prev2)
        init = jnp.full((B, S), neg_inf)
        init = init.at[:, 0].set(lp[0, jnp.arange(B), ext[:, 0]])
        init = init.at[:, 1].set(
            jnp.where(L > 0, lp[0, jnp.arange(B), ext[:, 1]], neg_inf)
        )
        def step(alpha, lp_t):
            a_prev = alpha
            a_shift1 = jnp.pad(alpha[:, :-1], ((0, 0), (1, 0)), constant_values=-1e30)
            a_shift2 = jnp.pad(alpha[:, :-2], ((0, 0), (2, 0)), constant_values=-1e30)
            a_shift2 = jnp.where(can_skip, a_shift2, neg_inf)
            merged = jnp.logaddexp(jnp.logaddexp(a_prev, a_shift1), a_shift2)
            emit = jnp.take_along_axis(lp_t, ext, axis=1)
            return merged + emit, merged + emit
        _, alphas = jax.lax.scan(step, init, lp[1:])
        alphas = jnp.concatenate([init[None], alphas], axis=0)  # [T, B, S]
        t_idx = (in_len.astype(jnp.int32) - 1)
        last = alphas[t_idx, jnp.arange(B)]  # [B, S]
        send = 2 * lab_len.astype(jnp.int32)
        p_blank = jnp.take_along_axis(last, send[:, None], axis=1)[:, 0]
        p_label = jnp.take_along_axis(
            last, jnp.maximum(send - 1, 0)[:, None], axis=1
        )[:, 0]
        ll = jnp.logaddexp(p_blank, jnp.where(lab_len > 0, p_label, neg_inf))
        loss = -ll
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(lab_len.astype(lp.dtype), 1))
        if reduction == "sum":
            return jnp.sum(loss)
        return loss
    return apply("ctc_loss", f, (log_probs, labels, input_lengths, label_lengths))


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    has_n = normalizer is not None
    operands = [logit, label] + ([normalizer] if has_n else [])
    def f(z, t, *rest):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0) - z * t + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * t + (1 - p) * (1 - t)
        a_t = alpha * t + (1 - alpha) * (1 - t)
        loss = a_t * ((1 - p_t) ** gamma) * ce
        if has_n:
            loss = loss / rest[0]
        return _reduce(loss, reduction)
    return apply("sigmoid_focal_loss", f, tuple(operands))


def huber_loss(input, label, delta=1.0, reduction="mean", name=None):  # noqa: A002
    """Quadratic below ``delta``, linear above (parity: F.huber_loss —
    note paddle's huber is smooth_l1 scaled by delta:
    0.5*r^2 if |r|<=delta else delta*(|r|-0.5*delta))."""

    def f(a, b):
        r = jnp.abs(a - b)
        return jnp.where(r <= delta, 0.5 * r * r,
                         delta * (r - 0.5 * delta))

    return _reduce(apply("huber_loss", f, (input, label)), reduction)


def edit_distance(input, label, normalized=True, ignored_tokens=None,  # noqa: A002
                  input_length=None, label_length=None, name=None):
    """Batched Levenshtein distance (parity: F.edit_distance, ref
    `nn/functional/loss.py:451`, `edit_distance` op).

    Returns (distance [batch, 1] float32, sequence_num [1] int64). The DP
    recurrence runs as a `lax.scan` over hypothesis tokens with the
    classic one-row formulation — O(batch·|input|·|label|) on device, no
    host loop."""

    def fn(hyp, ref, hyp_len, ref_len):
        b, li = hyp.shape
        lr = ref.shape[1]
        cols = jnp.arange(lr + 1, dtype=jnp.float32)

        def step(row_prev, xs):
            # row_prev: [b, lr+1] = distances for first i-1 hyp tokens
            h_tok, i = xs  # h_tok: [b]
            in_range = (i < hyp_len)[:, None]  # [b, 1]
            sub = row_prev[:, :-1] + jnp.where(
                ref == h_tok[:, None], 0.0, 1.0)  # [b, lr]
            dele = row_prev[:, 1:] + 1.0
            first = row_prev[:, :1] + 1.0  # j=0: i deletions

            def inner(carry, xs2):
                s, d = xs2  # [b], [b]
                val = jnp.minimum(jnp.minimum(s, d), carry + 1.0)
                return val, val

            _, rest = jax.lax.scan(
                inner, first[:, 0], (sub.T, dele.T))
            row = jnp.concatenate([first, rest.T], axis=1)
            # past the hypothesis end the row stops updating
            row = jnp.where(in_range, row, row_prev)
            return row, None

        row0 = jnp.broadcast_to(cols, (b, lr + 1))
        # column beyond the reference length is ignored at the end
        rowN, _ = jax.lax.scan(
            step, row0, (hyp.T, jnp.arange(li)))
        dist = jnp.take_along_axis(rowN, ref_len[:, None], axis=1)
        # rows where the hyp is empty: distance = ref_len
        dist = jnp.where(hyp_len[:, None] == 0,
                         ref_len[:, None].astype(jnp.float32), dist)
        dist = jnp.where((ref_len[:, None] == 0) & (hyp_len[:, None] > 0),
                         hyp_len[:, None].astype(jnp.float32), dist)
        if normalized:
            denom = jnp.maximum(ref_len[:, None].astype(jnp.float32), 1.0)
            dist = dist / denom
        # int64 intent, silently canonicalized to the x32 default like
        # every other integer tensor in the framework (explicit jnp.int64
        # would emit a truncation warning per call)
        return dist.astype(jnp.float32), jnp.asarray(np.asarray([b],
                                                                np.int64))

    from ...framework.core import Tensor as _T

    def _arr(x):
        return x._data if isinstance(x, _T) else jnp.asarray(x)

    hyp, ref = _arr(input), _arr(label)
    if ignored_tokens:
        # drop ignored tokens host-side (ragged -> repack right-padded)
        import numpy as _np

        def repack(a):
            a = _np.asarray(a)
            rows, lens = [], []
            for r in a:
                keep = r[~_np.isin(r, ignored_tokens)]
                rows.append(keep)
                lens.append(len(keep))
            out = _np.zeros((len(rows), max(lens) if lens else 0), a.dtype)
            for i, r in enumerate(rows):
                out[i, :len(r)] = r
            return jnp.asarray(out), jnp.asarray(_np.asarray(lens, _np.int64))

        hyp, hl = repack(hyp)
        ref, rl = repack(ref)
    else:
        hl = (_arr(input_length).astype(jnp.int32) if input_length is not None
              else jnp.full((hyp.shape[0],), hyp.shape[1], jnp.int32))
        rl = (_arr(label_length).astype(jnp.int32) if label_length is not None
              else jnp.full((ref.shape[0],), ref.shape[1], jnp.int32))
    from ...ops.dispatch import apply_nondiff

    return apply_nondiff("edit_distance", fn, (hyp, ref, hl, rl))


import functools as _functools


@_functools.lru_cache(maxsize=32)
def _simple_code_tables(num_classes):
    """SimpleCode path tables (reference MatrixBitCodeFunctor): for class
    c, code = c + num_classes; walking bits from the MSB-1 down gives node
    index (code >> k) - 1 and branch bit. Cached per num_classes — hsigmoid
    exists for large vocabularies, so the O(C log C) host loop must run
    once, not per training step."""
    max_len = int(np.ceil(np.log2(max(num_classes, 2))))
    tbl = np.full((num_classes, max_len), -1, np.int32)
    code_bits = np.zeros((num_classes, max_len), np.float32)
    for c in range(num_classes):
        code = c + num_classes
        length = code.bit_length() - 1
        for j in range(length):
            tbl[c, j] = (code >> (length - j)) - 1
            code_bits[c, j] = (code >> (length - 1 - j)) & 1
    return jnp.asarray(tbl), jnp.asarray(code_bits)


def hsigmoid_loss(input, label, num_classes, weight, bias=None,  # noqa: A002
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid loss (parity: F.hsigmoid_loss, ref
    `nn/functional/loss.py`, `hsigmoid_loss` op / MatrixBitCodeFunctor).

    Default tree: the complete binary tree the reference's SimpleCode
    uses — for class c the path of internal nodes is derived from the
    binary representation of (c + num_classes). Custom trees via
    path_table/path_code [batch, path_len] (-1 padded)."""
    from ...framework.core import Tensor as _T

    lab = label._data if isinstance(label, _T) else jnp.asarray(label)
    lab = lab.reshape(-1)

    if path_table is None:
        table_all, bits_all = _simple_code_tables(num_classes)
        ptab = jnp.take(table_all, lab, axis=0)
        pcode = jnp.take(bits_all, lab, axis=0)
    else:
        ptab = (path_table._data if isinstance(path_table, _T)
                else jnp.asarray(path_table)).astype(jnp.int32)
        pcode = (path_code._data if isinstance(path_code, _T)
                 else jnp.asarray(path_code)).astype(jnp.float32)

    def fn(x, w, *maybe_bias):
        valid = (ptab >= 0).astype(x.dtype)  # [b, L]
        idx = jnp.maximum(ptab, 0)
        wn = jnp.take(w, idx, axis=0)  # [b, L, d]
        logits = jnp.einsum("bd,bld->bl", x, wn)
        if maybe_bias:
            logits = logits + jnp.take(maybe_bias[0].reshape(-1), idx, axis=0)
        # bce-with-logits against the branch bit, masked to the real path
        per_node = jnp.maximum(logits, 0) - logits * pcode.astype(x.dtype) \
            + jnp.log1p(jnp.exp(-jnp.abs(logits)))
        return jnp.sum(per_node * valid, axis=1, keepdims=True)

    operands = (input, weight) + ((bias,) if bias is not None else ())
    return apply("hsigmoid_loss", fn, operands)
