"""Pooling via `lax.reduce_window` (parity:
`python/paddle/nn/functional/pooling.py`, PHI `pool_kernel`)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.core import Tensor
from ...ops.dispatch import apply
from .conv import _ntuple


def _window(nd, ksize, stride, channel_last):
    k = _ntuple(ksize, nd)
    s = _ntuple(stride if stride is not None else ksize, nd)
    if channel_last:
        dims = (1,) + k + (1,)
        strides = (1,) + s + (1,)
    else:
        dims = (1, 1) + k
        strides = (1, 1) + s
    return dims, strides, k, s


def _pool_padding(padding, nd, channel_last):
    if isinstance(padding, str):
        return padding.upper()
    p = _ntuple(padding, nd)
    spatial = [(pp, pp) for pp in p]
    if channel_last:
        return [(0, 0)] + spatial + [(0, 0)]
    return [(0, 0), (0, 0)] + spatial


def _ceil_adjust(pad, a_shape, dims, strides, ceil_mode):
    """Extend high-side padding so output size ceils instead of floors
    (paddle's ceil_mode; reference pool kernels compute this in
    `phi/kernels/funcs/pooling.h`)."""
    if not ceil_mode or isinstance(pad, str):
        return pad
    new_pad = []
    for ax, (lo, hi) in enumerate(pad):
        k, s = dims[ax], strides[ax]
        if k == 1 and s == 1:
            new_pad.append((lo, hi))
            continue
        eff = a_shape[ax] + lo + hi
        rem = (eff - k) % s
        extra = (s - rem) % s if rem else 0
        new_pad.append((lo, hi + extra))
    return new_pad


def _max_pool(x, nd, kernel_size, stride, padding, ceil_mode, data_format, op_name):
    channel_last = not data_format.startswith("NC")
    dims, strides, _, _ = _window(nd, kernel_size, stride, channel_last)
    pad = _pool_padding(padding, nd, channel_last)
    def f(a):
        p = _ceil_adjust(pad, a.shape, dims, strides, ceil_mode)
        init = -jnp.inf if jnp.issubdtype(a.dtype, jnp.floating) else jnp.iinfo(a.dtype).min
        return jax.lax.reduce_window(
            a, jnp.asarray(init, a.dtype), jax.lax.max, dims, strides, p
        )
    return apply(op_name, f, (x,))


def _avg_pool(x, nd, kernel_size, stride, padding, exclusive, ceil_mode, data_format, op_name):
    channel_last = not data_format.startswith("NC")
    dims, strides, _, _ = _window(nd, kernel_size, stride, channel_last)
    pad = _pool_padding(padding, nd, channel_last)
    def f(a):
        p = _ceil_adjust(pad, a.shape, dims, strides, ceil_mode)
        summed = jax.lax.reduce_window(
            a, jnp.asarray(0, a.dtype), jax.lax.add, dims, strides, p
        )
        if exclusive and p not in ("VALID",):
            ones = jnp.ones_like(a)
            counts = jax.lax.reduce_window(
                ones, jnp.asarray(0, a.dtype), jax.lax.add, dims, strides, p
            )
            return summed / counts
        return summed / np.prod([d for d in dims if d > 1])
    return apply(op_name, f, (x,))


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    fmt = "NCW" if data_format in ("NCL", "NCW") else "NWC"
    return _max_pool(x, 1, kernel_size, stride, padding, ceil_mode, fmt, "max_pool1d")


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    return _max_pool(x, 2, kernel_size, stride, padding, ceil_mode, data_format, "max_pool2d")


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    return _max_pool(x, 3, kernel_size, stride, padding, ceil_mode, data_format, "max_pool3d")


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    fmt = "NCW" if data_format in ("NCL", "NCW") else "NWC"
    return _avg_pool(x, 1, kernel_size, stride, padding, exclusive, ceil_mode, fmt, "avg_pool1d")


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    return _avg_pool(x, 2, kernel_size, stride, padding, exclusive, ceil_mode, data_format, "avg_pool2d")


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    return _avg_pool(x, 3, kernel_size, stride, padding, exclusive, ceil_mode, data_format, "avg_pool3d")


def _adaptive_windows(in_size, out_size):
    """Start/end boundaries identical to paddle's adaptive pooling."""
    starts = (np.arange(out_size) * in_size) // out_size
    ends = ((np.arange(out_size) + 1) * in_size + out_size - 1) // out_size
    return starts, ends


def _adaptive_pool(x, output_size, nd, reduce_fn, data_format, op_name):
    channel_last = not data_format.startswith("NC")
    out = _ntuple(output_size, nd)
    def f(a):
        spatial_axes = list(range(1, a.ndim - 1)) if channel_last else list(range(2, a.ndim))
        res = a
        for i, ax in enumerate(spatial_axes):
            if out[i] is None:
                continue
            in_size = res.shape[ax]
            o = out[i]
            if in_size % o == 0:
                # uniform windows: reshape + reduce (fast path)
                k = in_size // o
                new_shape = res.shape[:ax] + (o, k) + res.shape[ax + 1:]
                res = reduce_fn(res.reshape(new_shape), ax + 1)
            else:
                starts, ends = _adaptive_windows(in_size, o)
                slices = [
                    reduce_fn(
                        jax.lax.slice_in_dim(res, int(s), int(e), axis=ax), ax
                    )
                    for s, e in zip(starts, ends)
                ]
                res = jnp.stack(slices, axis=ax)
        return res
    return apply(op_name, f, (x,))


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(x, output_size, 1, jnp.mean, "NCW", "adaptive_avg_pool1d")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(x, output_size, 2, jnp.mean, data_format, "adaptive_avg_pool2d")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(x, output_size, 3, jnp.mean, data_format, "adaptive_avg_pool3d")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 1, jnp.max, "NCW", "adaptive_max_pool1d")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 2, jnp.max, "NCHW", "adaptive_max_pool2d")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 3, jnp.max, "NCDHW", "adaptive_max_pool3d")


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCHW", name=None):
    channel_last = not data_format.startswith("NC")
    dims, strides, _, _ = _window(2, kernel_size, stride, channel_last)
    pad = _pool_padding(padding, 2, channel_last)
    p = float(norm_type)
    def f(a):
        pp = _ceil_adjust(pad, a.shape, dims, strides, ceil_mode)
        powered = jnp.abs(a) ** p
        summed = jax.lax.reduce_window(
            powered, jnp.asarray(0, a.dtype), jax.lax.add, dims, strides, pp
        )
        return summed ** (1.0 / p)
    return apply("lp_pool2d", f, (x,))
