"""Pooling via `lax.reduce_window` (parity:
`python/paddle/nn/functional/pooling.py`, PHI `pool_kernel`)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.core import Tensor
from ...ops.dispatch import apply
from .conv import _ntuple


def _window(nd, ksize, stride, channel_last):
    k = _ntuple(ksize, nd)
    s = _ntuple(stride if stride is not None else ksize, nd)
    if channel_last:
        dims = (1,) + k + (1,)
        strides = (1,) + s + (1,)
    else:
        dims = (1, 1) + k
        strides = (1, 1) + s
    return dims, strides, k, s


def _pool_padding(padding, nd, channel_last):
    if isinstance(padding, str):
        return padding.upper()
    p = _ntuple(padding, nd)
    spatial = [(pp, pp) for pp in p]
    if channel_last:
        return [(0, 0)] + spatial + [(0, 0)]
    return [(0, 0), (0, 0)] + spatial


def _ceil_adjust(pad, a_shape, dims, strides, ceil_mode):
    """Extend high-side padding so output size ceils instead of floors
    (paddle's ceil_mode; reference pool kernels compute this in
    `phi/kernels/funcs/pooling.h`)."""
    if not ceil_mode or isinstance(pad, str):
        return pad
    new_pad = []
    for ax, (lo, hi) in enumerate(pad):
        k, s = dims[ax], strides[ax]
        if k == 1 and s == 1:
            new_pad.append((lo, hi))
            continue
        eff = a_shape[ax] + lo + hi
        rem = (eff - k) % s
        extra = (s - rem) % s if rem else 0
        new_pad.append((lo, hi + extra))
    return new_pad


def _max_pool(x, nd, kernel_size, stride, padding, ceil_mode, data_format, op_name):
    channel_last = not data_format.startswith("NC")
    dims, strides, _, _ = _window(nd, kernel_size, stride, channel_last)
    pad = _pool_padding(padding, nd, channel_last)
    def f(a):
        p = _ceil_adjust(pad, a.shape, dims, strides, ceil_mode)
        # init must be a python scalar, not a jnp array: under jit an
        # array init is a tracer and jax's reduce_window transpose rule
        # can no longer recognize the max monoid ("Linearization failed")
        init = -jnp.inf if jnp.issubdtype(a.dtype, jnp.floating) \
            else jnp.iinfo(a.dtype).min
        return jax.lax.reduce_window(
            a, init, jax.lax.max, dims, strides, p
        )
    return apply(op_name, f, (x,))


def _avg_pool(x, nd, kernel_size, stride, padding, exclusive, ceil_mode, data_format, op_name):
    channel_last = not data_format.startswith("NC")
    dims, strides, _, _ = _window(nd, kernel_size, stride, channel_last)
    pad = _pool_padding(padding, nd, channel_last)
    def f(a):
        p = _ceil_adjust(pad, a.shape, dims, strides, ceil_mode)
        summed = jax.lax.reduce_window(
            a, 0.0 if jnp.issubdtype(a.dtype, jnp.inexact) else 0, jax.lax.add, dims, strides, p
        )
        if exclusive and p not in ("VALID",):
            ones = jnp.ones_like(a)
            counts = jax.lax.reduce_window(
                ones, 0.0 if jnp.issubdtype(a.dtype, jnp.inexact) else 0, jax.lax.add, dims, strides, p
            )
            return summed / counts
        return summed / np.prod([d for d in dims if d > 1])
    return apply(op_name, f, (x,))


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    fmt = "NCW" if data_format in ("NCL", "NCW") else "NWC"
    if return_mask:
        if fmt != "NCW":
            raise ValueError("return_mask requires NCL layout")
        return _max_pool_with_index(x, 1, kernel_size, stride, padding,
                                    ceil_mode, "max_pool2d_with_index")
    return _max_pool(x, 1, kernel_size, stride, padding, ceil_mode, fmt, "max_pool1d")


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    if return_mask:
        if not data_format.startswith("NC"):
            raise ValueError("return_mask requires NCHW layout")
        return _max_pool_with_index(x, 2, kernel_size, stride, padding,
                                    ceil_mode, "max_pool2d_with_index")
    return _max_pool(x, 2, kernel_size, stride, padding, ceil_mode, data_format, "max_pool2d")


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    if return_mask:
        if not data_format.startswith("NC"):
            raise ValueError("return_mask requires NCDHW layout")
        return _max_pool_with_index(x, 3, kernel_size, stride, padding,
                                    ceil_mode, "max_pool3d_with_index")
    return _max_pool(x, 3, kernel_size, stride, padding, ceil_mode, data_format, "max_pool3d")


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    fmt = "NCW" if data_format in ("NCL", "NCW") else "NWC"
    return _avg_pool(x, 1, kernel_size, stride, padding, exclusive, ceil_mode, fmt, "avg_pool1d")


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    return _avg_pool(x, 2, kernel_size, stride, padding, exclusive, ceil_mode, data_format, "avg_pool2d")


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    return _avg_pool(x, 3, kernel_size, stride, padding, exclusive, ceil_mode, data_format, "avg_pool3d")


def _adaptive_windows(in_size, out_size):
    """Start/end boundaries identical to paddle's adaptive pooling."""
    starts = (np.arange(out_size) * in_size) // out_size
    ends = ((np.arange(out_size) + 1) * in_size + out_size - 1) // out_size
    return starts, ends


def _adaptive_pool(x, output_size, nd, reduce_fn, data_format, op_name):
    channel_last = not data_format.startswith("NC")
    out = _ntuple(output_size, nd)
    def f(a):
        spatial_axes = list(range(1, a.ndim - 1)) if channel_last else list(range(2, a.ndim))
        res = a
        for i, ax in enumerate(spatial_axes):
            if out[i] is None:
                continue
            in_size = res.shape[ax]
            o = out[i]
            if in_size % o == 0:
                # uniform windows: reshape + reduce (fast path)
                k = in_size // o
                new_shape = res.shape[:ax] + (o, k) + res.shape[ax + 1:]
                res = reduce_fn(res.reshape(new_shape), ax + 1)
            else:
                starts, ends = _adaptive_windows(in_size, o)
                slices = [
                    reduce_fn(
                        jax.lax.slice_in_dim(res, int(s), int(e), axis=ax), ax
                    )
                    for s, e in zip(starts, ends)
                ]
                res = jnp.stack(slices, axis=ax)
        return res
    return apply(op_name, f, (x,))


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(x, output_size, 1, jnp.mean, "NCW", "adaptive_avg_pool1d")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(x, output_size, 2, jnp.mean, data_format, "adaptive_avg_pool2d")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(x, output_size, 3, jnp.mean, data_format, "adaptive_avg_pool3d")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 1, jnp.max, "NCW", "adaptive_max_pool1d")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 2, jnp.max, "NCHW", "adaptive_max_pool2d")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 3, jnp.max, "NCDHW", "adaptive_max_pool3d")


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCHW", name=None):
    channel_last = not data_format.startswith("NC")
    dims, strides, _, _ = _window(2, kernel_size, stride, channel_last)
    pad = _pool_padding(padding, 2, channel_last)
    p = float(norm_type)
    def f(a):
        pp = _ceil_adjust(pad, a.shape, dims, strides, ceil_mode)
        powered = jnp.abs(a) ** p
        summed = jax.lax.reduce_window(
            powered, 0.0 if jnp.issubdtype(a.dtype, jnp.inexact) else 0, jax.lax.add, dims, strides, pp
        )
        return summed ** (1.0 / p)
    return apply("lp_pool2d", f, (x,))


# ---- max-pool indices + unpooling (round-3 op-coverage additions) ----

def _spatial_windows(a, dims, strides, pads):
    """Gather pooling windows: a [N, C, *S] -> (win [N, C, *So, K],
    flat_idx [*So, K]) where K = prod(kernel), pads is per-dim (lo, hi)
    and flat_idx indexes the un-padded spatial plane (-1 for padding
    positions)."""
    spatial = a.shape[2:]
    nd = len(spatial)
    neg = (-jnp.inf if jnp.issubdtype(a.dtype, jnp.floating)
           else jnp.iinfo(a.dtype).min)
    cfg = [(0, 0), (0, 0)] + list(pads)
    ap = jnp.pad(a, cfg, constant_values=neg)
    outs = [(spatial[d] + pads[d][0] + pads[d][1] - dims[d]) // strides[d] + 1
            for d in range(nd)]
    # per-dim padded coordinates of each (out, k) pair
    coords = [jnp.arange(outs[d])[:, None] * strides[d] + jnp.arange(dims[d])
              for d in range(nd)]
    win = ap
    for d in range(nd):
        # spatial dim d sits at axis 2+d (earlier dims' k-axes moved last)
        win = jnp.take(win, coords[d].reshape(-1), axis=2 + d)
        win = win.reshape(win.shape[:2 + d] + (outs[d], dims[d])
                          + win.shape[3 + d:])
        win = jnp.moveaxis(win, 3 + d, win.ndim - 1)
    # win: [N, C, *So, k0, k1, ...] -> [N, C, *So, K]
    win = win.reshape(win.shape[:2 + nd] + (-1,))
    # true (unpadded) flat spatial index per (out..., k...) combination
    orig = [coords[d] - pads[d][0] for d in range(nd)]  # <0 in lo padding
    grids_o = jnp.meshgrid(*[jnp.arange(o) for o in outs], indexing="ij")
    flat = jnp.zeros(tuple(outs) + (1,) * nd, jnp.int32)
    valid = jnp.ones(tuple(outs) + (1,) * nd, bool)
    for d in range(nd):
        shape_k = [1] * nd + [1] * nd
        shape_k[nd + d] = dims[d]
        od = orig[d][grids_o[d].reshape(-1)].reshape(
            tuple(outs) + (1,) * d + (dims[d],) + (1,) * (nd - d - 1))
        flat = flat * spatial[d] + od
        valid = valid & (od >= 0) & (od < spatial[d])
    flat = jnp.where(valid, flat, -1).reshape(tuple(outs) + (-1,))
    return win, flat


def _max_pool_with_index(x, nd, kernel_size, stride, padding, ceil_mode,
                         op_name):
    """(pooled, indices): indices are flat positions in the spatial plane
    (parity: PHI `max_pool2d_with_index` / `max_pool3d_with_index`)."""
    dims, strides, _, _ = _window(nd, kernel_size, stride, False)
    pad = _pool_padding(padding, nd, False)
    kdims, kstrides = dims[2:], strides[2:]

    if isinstance(pad, str):
        if pad != "VALID":
            raise ValueError(
                f"return_mask does not support padding={padding!r}")
        pad = [(0, 0)] * (nd + 2)

    def f(a):
        # ceil_mode extends high-side padding exactly like the maskless
        # path, so pooled shapes/values agree between the two
        adj = _ceil_adjust(pad, a.shape, dims, strides, ceil_mode)
        pads = list(adj[2:])
        win, flat = _spatial_windows(a, kdims, kstrides, pads)
        arg = jnp.argmax(win, axis=-1)
        pooled = jnp.take_along_axis(win, arg[..., None], axis=-1)[..., 0]
        idx = jnp.take_along_axis(
            jnp.broadcast_to(flat, win.shape[:2] + flat.shape),
            arg[..., None], axis=-1)[..., 0]
        return pooled, idx.astype(jnp.int32)

    from ...ops.dispatch import apply as _apply

    return _apply(op_name, f, (x,), n_outputs=2)


def _max_unpool(x, indices, nd, kernel_size, stride, padding, output_size,
                op_name):
    dims, strides, _, _ = _window(nd, kernel_size, stride, False)
    kdims, kstrides = dims[2:], strides[2:]

    def f(a, idx):
        spatial_in = a.shape[2:]
        if output_size is not None:
            out_sp = tuple(output_size)[-nd:]
        else:
            pads = padding if isinstance(padding, (list, tuple)) \
                else [padding] * nd
            out_sp = tuple(
                (spatial_in[d] - 1) * kstrides[d] - 2 * pads[d] + kdims[d]
                for d in range(nd))
        n, c = a.shape[0], a.shape[1]
        flat_len = 1
        for s in out_sp:
            flat_len *= s
        af = a.reshape(n * c, -1)
        ixf = idx.reshape(n * c, -1)
        out = jnp.zeros((n * c, flat_len), a.dtype)
        out = out.at[jnp.arange(n * c)[:, None], ixf].set(af)
        return out.reshape((n, c) + out_sp)

    from ...ops.dispatch import apply as _apply

    return _apply(op_name, f, (x, indices))


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCL", name=None):
    """Inverse of max_pool1d(return_mask=True) (parity:
    `nn/functional/pooling.py:737`, PHI `unpool` kernel)."""
    return _max_unpool(x, indices, 1, kernel_size, stride, padding,
                       output_size, "unpool")


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCHW", name=None):
    """Inverse of max_pool2d(return_mask=True) (PHI `unpool` kernel)."""
    return _max_unpool(x, indices, 2, kernel_size, stride, padding,
                       output_size, "unpool")


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCDHW", name=None):
    """Inverse of max_pool3d(return_mask=True) (PHI `unpool3d` kernel)."""
    return _max_unpool(x, indices, 3, kernel_size, stride, padding,
                       output_size, "unpool3d")
