"""Common NN functional ops: linear, dropout, embedding, pad, one_hot,
interpolate, normalize, cosine_similarity...

Reference parity: `python/paddle/nn/functional/common.py` + `input.py`.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...framework import random as rng
from ...framework.core import Tensor
from ...framework.dtype import convert_dtype
from ...ops.dispatch import apply, apply_nondiff


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b, W is [in, out] (parity: paddle.nn.functional.linear,
    PHI kernel `phi/kernels/.../matmul_kernel` + fused bias; XLA fuses the
    bias add into the MXU matmul epilogue)."""
    if bias is None:
        return apply("linear", lambda a, w: a @ w, (x, weight))
    return apply("linear", lambda a, w, b: a @ w + b, (x, weight, bias))


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    """Parity: paddle.nn.functional.dropout (`phi/kernels/gpu/dropout_kernel`).

    Keys come from the functional RNG (`framework.random.next_key`) so the
    mask is reproducible and trace-safe."""
    if not training or p == 0.0:
        # downscale_in_infer trains unscaled and scales at inference
        # (reference common.py eval branch: scale(x, keep_prob))
        if mode == "downscale_in_infer" and not training and p > 0.0:
            return apply("dropout", lambda a: a * (1.0 - p), (x,))
        return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
    if p == 1.0:
        return apply("dropout", lambda a: jnp.zeros_like(a), (x,))
    key = rng.next_key()
    def f(a):
        shape = list(a.shape)
        if axis is not None:
            axes = axis if isinstance(axis, (list, tuple)) else [axis]
            shape = [s if i in [ax % a.ndim for ax in axes] else 1 for i, s in enumerate(shape)]
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), jnp.zeros((), a.dtype))
        return jnp.where(keep, a, jnp.zeros((), a.dtype))
    return apply("dropout", f, (x,))


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    key = rng.next_key()
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    def f(a):
        keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        coef_a = (1.0 / np.sqrt((1 - p) * (1 + p * alpha_p ** 2))).astype(np.float32)
        coef_b = -coef_a * p * alpha_p
        return coef_a * jnp.where(keep, a, jnp.asarray(alpha_p, a.dtype)) + coef_b
    return apply("alpha_dropout", f, (x,))


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """Parity: paddle.nn.functional.embedding
    (`phi/kernels/.../embedding_kernel`). On TPU a gather from the table;
    padding_idx rows contribute zero gradient via mask."""
    def f(idx, w):
        out = jnp.take(w, idx, axis=0)
        if padding_idx is not None:
            pad = padding_idx if padding_idx >= 0 else w.shape[0] + padding_idx
            mask = (idx != pad)[..., None]
            out = out * mask.astype(out.dtype)
        return out
    return apply("embedding", f, (x, weight))


def one_hot(x, num_classes, name=None):
    return apply(
        "one_hot",
        lambda idx: jax.nn.one_hot(idx, num_classes, dtype=jnp.float32),
        (x,),
    )


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def f(l, *rest):
        k = l.shape[-1]
        if rest:
            return (1 - epsilon) * l + epsilon * rest[0]
        return (1 - epsilon) * l + epsilon / k
    ops = (label,) if prior_dist is None else (label, prior_dist)
    return apply("label_smooth", f, ops)


_PAD_MODES = {"constant": "constant", "reflect": "reflect", "replicate": "edge", "circular": "wrap"}


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):  # noqa: A002
    """Parity: paddle.nn.functional.pad (`phi/kernels/.../pad3d_kernel`).
    `pad` is paddle-style [left, right, top, bottom, ...] over the last dims
    (or per-dim pairs when len == 2*ndim)."""
    if isinstance(pad, Tensor):
        pad = pad.tolist()
    pad = list(int(p) for p in pad)
    jmode = _PAD_MODES[mode]
    def f(a):
        nd = a.ndim
        cfg = [(0, 0)] * nd
        if len(pad) == 2 * nd:
            # full per-dim spec, paddle order = numpy order
            for i in range(nd):
                cfg[i] = (pad[2 * i], pad[2 * i + 1])
        else:
            # spatial-only spec over trailing dims; paddle lists (left,right)
            # starting from the LAST spatial dim backwards
            n_spatial = len(pad) // 2
            if data_format.startswith("NC"):
                spatial = list(range(2, nd))
            else:
                spatial = list(range(1, nd - 1))
            assert n_spatial <= len(spatial), "pad spec longer than spatial dims"
            for i in range(n_spatial):
                dim = spatial[-(i + 1)]
                cfg[dim] = (pad[2 * i], pad[2 * i + 1])
        if jmode == "constant":
            return jnp.pad(a, cfg, mode="constant", constant_values=value)
        return jnp.pad(a, cfg, mode=jmode)
    return apply("pad", f, (x,))


def zeropad2d(x, padding, data_format="NCHW", name=None):
    return pad(x, padding, mode="constant", value=0.0, data_format=data_format)


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def f(a):
        n = jnp.linalg.norm(a, ord=p, axis=axis, keepdims=True)
        return a / jnp.maximum(n, epsilon)
    return apply("normalize", f, (x,))


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    def f(a, b):
        dot = jnp.sum(a * b, axis=axis)
        na = jnp.linalg.norm(a, axis=axis)
        nb = jnp.linalg.norm(b, axis=axis)
        return dot / jnp.maximum(na * nb, eps)
    return apply("cosine_similarity", f, (x1, x2))


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    def f(a, b):
        d = a - b + epsilon
        return jnp.linalg.norm(d, ord=p, axis=-1, keepdims=keepdim)
    return apply("pairwise_distance", f, (x, y))


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor
    def f(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, c // (r * r), r, r, h, w)
            a = a.transpose(0, 1, 4, 2, 5, 3)
            return a.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = a.shape
        a = a.reshape(n, h, w, r, r, c // (r * r))
        a = a.transpose(0, 1, 3, 2, 4, 5)
        return a.reshape(n, h * r, w * r, c // (r * r))
    return apply("pixel_shuffle", f, (x,))


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor
    def f(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, c, h // r, r, w // r, r)
            a = a.transpose(0, 1, 3, 5, 2, 4)
            return a.reshape(n, c * r * r, h // r, w // r)
        n, h, w, c = a.shape
        a = a.reshape(n, h // r, r, w // r, r, c)
        a = a.transpose(0, 1, 3, 2, 4, 5)
        return a.reshape(n, h // r, w // r, c * r * r)
    return apply("pixel_unshuffle", f, (x,))


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    def f(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, groups, c // groups, h, w)
            return a.transpose(0, 2, 1, 3, 4).reshape(n, c, h, w)
        n, h, w, c = a.shape
        a = a.reshape(n, h, w, groups, c // groups)
        return a.transpose(0, 1, 2, 4, 3).reshape(n, h, w, c)
    return apply("channel_shuffle", f, (x,))


def interpolate(
    x,
    size=None,
    scale_factor=None,
    mode="nearest",
    align_corners=False,
    align_mode=0,
    data_format="NCHW",
    name=None,
):
    """Parity: paddle.nn.functional.interpolate (`phi/kernels/.../interpolate_kernel`),
    including align_corners / align_mode and 'area' (adaptive-average) modes.

    TPU-first design: resampling is separable, so each spatial axis is
    resized by a static [out, in] weight matrix (computed host-side at trace
    time) applied as a tensordot — a dense matmul XLA tiles onto the MXU,
    instead of per-pixel gathers."""
    if isinstance(size, Tensor):
        size = [int(s) for s in size.tolist()]
    mode = mode.lower()
    if mode not in ("nearest", "linear", "bilinear", "bicubic", "trilinear", "area"):
        raise ValueError(f"unsupported interpolate mode {mode!r}")

    def _axis_weights(n_in, n_out, kind):
        """[n_out, n_in] resampling matrix for one axis (float32 numpy)."""
        j = np.arange(n_out, dtype=np.float64)
        W = np.zeros((n_out, n_in), dtype=np.float64)
        rows = np.arange(n_out)
        if kind == "nearest":
            if align_corners:
                src = np.rint(j * (n_in - 1) / max(n_out - 1, 1)).astype(int)
            else:
                src = np.floor(j * n_in / n_out).astype(int)
            W[rows, np.clip(src, 0, n_in - 1)] = 1.0
            return W
        if kind == "area":
            for jj in range(n_out):
                start = int(np.floor(jj * n_in / n_out))
                end = max(int(np.ceil((jj + 1) * n_in / n_out)), start + 1)
                W[jj, start:end] = 1.0 / (end - start)
            return W
        # source coordinate per output index (reference interpolate_kernel:
        # align_corners -> corner-aligned; align_mode 0 -> half-pixel,
        # align_mode 1 -> asymmetric)
        if align_corners:
            src = j * (n_in - 1) / max(n_out - 1, 1)
        elif kind == "linear" and align_mode == 1:
            src = j * (n_in / n_out)
        else:
            src = (j + 0.5) * (n_in / n_out) - 0.5
        if kind == "linear":
            src = np.clip(src, 0, n_in - 1)
            lo = np.floor(src).astype(int)
            hi = np.minimum(lo + 1, n_in - 1)
            frac = src - lo
            np.add.at(W, (rows, lo), 1.0 - frac)
            np.add.at(W, (rows, hi), frac)
            return W
        # bicubic: Keys kernel, A=-0.75 (reference cubic_interp)
        A = -0.75
        def cubic(t):
            t = np.abs(t)
            return np.where(
                t <= 1, (A + 2) * t**3 - (A + 3) * t**2 + 1,
                np.where(t < 2, A * t**3 - 5 * A * t**2 + 8 * A * t - 4 * A, 0.0),
            )
        base = np.floor(src).astype(int)
        for tap in (-1, 0, 1, 2):
            idx = base + tap
            w = cubic(src - idx)
            np.add.at(W, (rows, np.clip(idx, 0, n_in - 1)), w)
        return W

    kind_per_axis = {
        "nearest": "nearest", "area": "area", "linear": "linear",
        "bilinear": "linear", "trilinear": "linear", "bicubic": "cubic",
    }[mode]

    def f(a):
        nd = a.ndim
        channel_last = not data_format.startswith("NC")
        spatial = list(range(1, nd - 1)) if channel_last else list(range(2, nd))
        if size is not None:
            tgt = list(size) if isinstance(size, (list, tuple)) else [size]
        else:
            sf = scale_factor if isinstance(scale_factor, (list, tuple)) else [scale_factor] * len(spatial)
            tgt = [int(a.shape[d] * s) for d, s in zip(spatial, sf)]
        out = a
        compute_dtype = a.dtype if jnp.issubdtype(a.dtype, jnp.floating) else jnp.float32
        for d, s in zip(spatial, tgt):
            n_in = out.shape[d]
            if n_in == s:
                continue
            W = jnp.asarray(
                _axis_weights(n_in, s, kind_per_axis), dtype=compute_dtype
            )
            moved = jnp.tensordot(out.astype(compute_dtype), W, axes=[[d], [1]])
            out = jnp.moveaxis(moved, -1, d)
        if out.dtype != a.dtype:
            if kind_per_axis == "nearest":
                out = jnp.rint(out).astype(a.dtype)
            else:
                out = out.astype(a.dtype)
        return out
    return apply("interpolate", f, (x,))


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
             align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (parity: paddle.nn.functional.unfold,
    `phi/kernels/.../unfold_kernel`)."""
    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)
    kh, kw = _pair(kernel_sizes)
    sh, sw = _pair(strides)
    ph, pw = _pair(paddings) if not (isinstance(paddings, (list, tuple)) and len(paddings) == 4) else (None, None)
    dh, dw = _pair(dilations)
    def f(a):
        n, c, h, w = a.shape
        if ph is not None:
            a = jnp.pad(a, ((0, 0), (0, 0), (ph, ph), (pw, pw)))
        else:
            pt, pl, pb, pr = paddings
            a = jnp.pad(a, ((0, 0), (0, 0), (pt, pb), (pl, pr)))
        hh, ww = a.shape[2], a.shape[3]
        out_h = (hh - (dh * (kh - 1) + 1)) // sh + 1
        out_w = (ww - (dw * (kw - 1) + 1)) // sw + 1
        patches = jax.lax.conv_general_dilated_patches(
            a, (kh, kw), (sh, sw), "VALID", rhs_dilation=(dh, dw),
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )  # [n, c*kh*kw, out_h, out_w]
        return patches.reshape(n, c * kh * kw, out_h * out_w)
    return apply("unfold", f, (x,))


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """col2im — the adjoint of unfold; expressed via the VJP of unfold so
    behavior matches exactly (overlaps sum)."""
    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)
    oh, ow = _pair(output_sizes)
    kh, kw = _pair(kernel_sizes)
    def f(cols):
        n = cols.shape[0]
        c = cols.shape[1] // (kh * kw)
        def unfold_arr(img):
            sh, sw = _pair(strides)
            dh, dw = _pair(dilations)
            ph, pw = _pair(paddings)
            img = jnp.pad(img, ((0, 0), (0, 0), (ph, ph), (pw, pw)))
            patches = jax.lax.conv_general_dilated_patches(
                img, (kh, kw), (sh, sw), "VALID", rhs_dilation=(dh, dw),
                dimension_numbers=("NCHW", "OIHW", "NCHW"),
            )
            return patches.reshape(n, c * kh * kw, -1)
        zeros = jnp.zeros((n, c, oh, ow), cols.dtype)
        _, vjp = jax.vjp(unfold_arr, zeros)
        (img,) = vjp(cols)
        return img
    return apply("fold", f, (x,))


def bilinear(x1, x2, weight, bias=None, name=None):
    def f(a, b, w, *rest):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if rest:
            out = out + rest[0]
        return out
    ops = (x1, x2, weight) if bias is None else (x1, x2, weight, bias)
    return apply("bilinear", f, ops)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros", align_corners=True, name=None):
    """Parity: paddle.nn.functional.grid_sample (bilinear only)."""
    def f(a, g):
        n, c, h, w = a.shape
        gx = (g[..., 0] + 1) * (w - 1) / 2 if align_corners else ((g[..., 0] + 1) * w - 1) / 2
        gy = (g[..., 1] + 1) * (h - 1) / 2 if align_corners else ((g[..., 1] + 1) * h - 1) / 2
        x0 = jnp.floor(gx); x1 = x0 + 1
        y0 = jnp.floor(gy); y1 = y0 + 1
        wx1 = gx - x0; wx0 = 1 - wx1
        wy1 = gy - y0; wy0 = 1 - wy1
        def sample(yy, xx):
            valid = (xx >= 0) & (xx < w) & (yy >= 0) & (yy < h)
            xi = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
            yi = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
            vals = a[jnp.arange(n)[:, None, None], :, yi, xi]  # [n,gh,gw,c]
            return vals * valid[..., None].astype(a.dtype)
        out = (
            sample(y0, x0) * (wy0 * wx0)[..., None]
            + sample(y0, x1) * (wy0 * wx1)[..., None]
            + sample(y1, x0) * (wy1 * wx0)[..., None]
            + sample(y1, x1) * (wy1 * wx1)[..., None]
        )
        return jnp.moveaxis(out, -1, 1)
    return apply("grid_sample", f, (x, grid))


def affine_grid(theta, out_shape, align_corners=True, name=None):
    if isinstance(out_shape, Tensor):
        out_shape = [int(v) for v in out_shape.tolist()]
    n, c, h, w = out_shape
    def f(th):
        if align_corners:
            ys = jnp.linspace(-1, 1, h)
            xs = jnp.linspace(-1, 1, w)
        else:
            ys = (jnp.arange(h) * 2 + 1) / h - 1
            xs = (jnp.arange(w) * 2 + 1) / w - 1
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1)  # [h, w, 3]
        return jnp.einsum("hwk,nok->nhwo", base, th)
    return apply("affine_grid", f, (theta,))


def sequence_mask(lengths, maxlen=None, dtype="int64", name=None):
    d = convert_dtype(dtype)
    def f(l):
        m = maxlen if maxlen is not None else int(jnp.max(l))
        return (jnp.arange(m)[None, :] < l[..., None]).astype(np.dtype(d) if d != jnp.bfloat16 else d)
    lens = lengths if isinstance(lengths, Tensor) else Tensor(jnp.asarray(lengths))
    if maxlen is None:
        m = int(np.asarray(lens._data).max())
        return apply(
            "sequence_mask",
            lambda l: (jnp.arange(m)[None, :] < l[..., None]).astype(np.dtype(d) if d != jnp.bfloat16 else d),
            (lens,),
        )
    return apply("sequence_mask", f, (lens,))


def class_center_sample(label, num_classes, num_samples, group=None):
    """Sample ``num_samples`` class centers always containing every
    positive class; remap labels into the sampled set (parity:
    F.class_center_sample, ref `phi/kernels/gpu/class_center_sample_kernel.cu`
    — the margin-loss partial-fc sampler).

    Output shapes are static (``num_samples``); the sampling itself runs
    host-side on the concrete labels, seeded from the framework PRNG, the
    same split as the device random ops."""
    orig = np.asarray(label._data if isinstance(label, Tensor) else label)
    lab = orig.reshape(-1)
    positives = np.unique(lab)
    if num_samples < positives.size:
        raise ValueError(
            f"class_center_sample: num_samples={num_samples} is smaller "
            f"than the {positives.size} distinct positive classes")
    if num_samples > num_classes:
        raise ValueError(
            f"class_center_sample: num_samples={num_samples} exceeds "
            f"num_classes={num_classes}; the sampled set is a subset of "
            "the classes, so its static size cannot exceed num_classes")
    negatives = np.setdiff1d(np.arange(num_classes), positives)
    n_extra = num_samples - positives.size
    key = rng.next_key()
    perm = np.asarray(jax.random.permutation(key, negatives.size))
    sampled = np.sort(np.concatenate(
        [positives, negatives[perm[:n_extra]]])).astype(np.int64)
    remapped = np.searchsorted(sampled, lab).astype(np.int64)
    return (Tensor(jnp.asarray(remapped.reshape(orig.shape))),
            Tensor(jnp.asarray(sampled)))


def gather_tree(ids, parents):
    """Backtrace beam-search sequences: ids/parents [max_time, batch,
    beam] -> full sequences (parity: F.gather_tree, ref
    `nn/functional/extension.py:248`, `gather_tree` op). The backtrace
    walks time in reverse inside one `lax.scan` (compiler-friendly, no
    host loop)."""

    def fn(ids_a, par_a):
        t, b, k = ids_a.shape
        beams = jnp.arange(k, dtype=par_a.dtype)[None, :].repeat(b, 0)

        def step(carry, xs):
            beam_sel = carry  # [b, k] beam index chosen at time t+1
            ids_t, par_t = xs
            out = jnp.take_along_axis(ids_t, beam_sel, axis=1)
            prev = jnp.take_along_axis(par_t, beam_sel, axis=1)
            return prev, out

        # last step selects its own beams
        init = beams
        out_last = ids_a[-1]
        prev = jnp.take_along_axis(par_a[-1], init, axis=1)
        _, outs = jax.lax.scan(
            step, prev, (ids_a[:-1], par_a[:-1]), reverse=True)
        return jnp.concatenate([outs, out_last[None]], axis=0)

    return apply_nondiff("gather_tree", fn, (ids, parents))


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None,
                   data_format="NCHW"):
    """TSM temporal shift: [N*T, C, H, W] with T=seg_num; the first
    shift_ratio of channels shift t-1, the next shift_ratio shift t+1
    (parity: F.temporal_shift, ref `nn/functional/extension.py:335`,
    `temporal_shift` op)."""
    if data_format not in ("NCHW", "NHWC"):
        raise ValueError(f"unsupported data_format {data_format!r}")

    def fn(a):
        if data_format == "NHWC":
            a = jnp.transpose(a, (0, 3, 1, 2))
        nt, c, h, w = a.shape
        n = nt // seg_num
        v = a.reshape(n, seg_num, c, h, w)
        c1 = int(c * shift_ratio)
        c2 = int(c * 2 * shift_ratio)
        pad = jnp.zeros((n, 1, c, h, w), a.dtype)
        fwd = jnp.concatenate([v[:, 1:], pad], axis=1)      # slice <- t+1
        bwd = jnp.concatenate([pad, v[:, :-1]], axis=1)     # slice <- t-1
        out = jnp.concatenate(
            [bwd[:, :, :c1], fwd[:, :, c1:c2], v[:, :, c2:]], axis=2)
        out = out.reshape(nt, c, h, w)
        if data_format == "NHWC":
            out = jnp.transpose(out, (0, 2, 3, 1))
        return out

    return apply("temporal_shift", fn, (x,))


def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None):  # noqa: A002
    """Alias of paddle.diag_embed at the functional namespace (parity:
    paddle.nn.functional.diag_embed)."""
    from ...tensor.manipulation import diag_embed as _de

    return _de(input, offset=offset, dim1=dim1, dim2=dim2)


def sparse_attention(query, key, value, sparse_csr_offset,
                     sparse_csr_columns, key_padding_mask=None,
                     attn_mask=None, name=None):
    """Block-sparse attention with a CSR-described visibility pattern
    (parity: paddle.nn.functional.sparse_attention, a CUDA-only op in the
    reference). TPU build: the CSR pattern becomes an additive mask and
    the matmuls stay dense on the MXU — at the sparsity levels this API
    targets the MXU's dense throughput beats a gather-based kernel.
    query/key/value: [B, H, T, D]; offset: [B, H, T+1]; columns [B, H, nnz].
    """
    import jax

    has_kp = key_padding_mask is not None

    def f(q, k, v, off, cols, *rest):
        b, h, t, d = q.shape
        nnz = cols.shape[-1]
        # row id of each nnz entry: searchsorted over the offset vector
        row_of = jax.vmap(jax.vmap(
            lambda o, c: jnp.searchsorted(o, jnp.arange(nnz), side="right")
            - 1))(off, cols)
        mask = jnp.zeros((b, h, t, t), bool)
        b_idx = jnp.arange(b)[:, None, None]
        h_idx = jnp.arange(h)[None, :, None]
        mask = mask.at[b_idx, h_idx, row_of, cols.astype(jnp.int32)].set(True)
        bias = jnp.where(mask, 0.0, -1e30).astype(q.dtype)
        i = 0
        if has_kp:
            kp = rest[i]  # [B, T] 0/1 key padding
            i += 1
            bias = bias + (kp[:, None, None, :] - 1.0) * 1e30
        if i < len(rest):
            am = rest[i]  # additive [.., T, T] attention mask
            bias = bias + jnp.broadcast_to(am, bias.shape).astype(q.dtype)
        logits = jnp.einsum("bhtd,bhsd->bhts", q, k) / jnp.sqrt(
            jnp.asarray(d, q.dtype))
        p = jax.nn.softmax(logits + bias, axis=-1)
        return jnp.einsum("bhts,bhsd->bhtd", p, v)

    operands = (query, key, value, sparse_csr_offset, sparse_csr_columns)
    if key_padding_mask is not None:
        operands = operands + (key_padding_mask,)
    if attn_mask is not None:
        operands = operands + (attn_mask,)
    return apply("sparse_attention", f, operands)
