"""Convolutions via `lax.conv_general_dilated` (the XLA conv that tiles onto
the MXU), replacing the reference's cuDNN dispatch
(`paddle/phi/kernels/gpu/conv_kernel.cu`, `python/paddle/nn/functional/conv.py`).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.core import Tensor
from ...ops.dispatch import apply


def _ntuple(v, n):
    if isinstance(v, int):
        return (v,) * n
    v = tuple(v)
    if len(v) == 1:
        return v * n
    return v


def _resolve_padding(padding, nd, strides, dilations, ksizes):
    """paddle padding: int, list of ints, list of pairs, 'SAME', 'VALID'."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * nd
    padding = list(padding)
    if all(isinstance(p, int) for p in padding):
        if len(padding) == nd:
            return [(p, p) for p in padding]
        if len(padding) == 2 * nd:
            return [
                (padding[2 * i], padding[2 * i + 1]) for i in range(nd)
            ]
    return [tuple(p) for p in padding]


def _conv(x, weight, bias, stride, padding, dilation, groups, data_format, nd, op_name):
    strides = _ntuple(stride, nd)
    dilations = _ntuple(dilation, nd)
    channel_last = not data_format.startswith("NC")
    if nd == 1:
        dn_in = "NWC" if channel_last else "NCW"
        dn_out = dn_in
        dn_k = "WIO" if channel_last else "OIW"
    elif nd == 2:
        dn_in = "NHWC" if channel_last else "NCHW"
        dn_out = dn_in
        dn_k = "HWIO" if channel_last else "OIHW"
    else:
        dn_in = "NDHWC" if channel_last else "NCDHW"
        dn_out = dn_in
        dn_k = "DHWIO" if channel_last else "OIDHW"

    def f(a, w, *rest):
        # paddle weights are always [out_c, in_c/groups, *k]
        if channel_last:
            if nd == 1:
                wk = jnp.transpose(w, (2, 1, 0))
            elif nd == 2:
                wk = jnp.transpose(w, (2, 3, 1, 0))
            else:
                wk = jnp.transpose(w, (2, 3, 4, 1, 0))
        else:
            wk = w
        ksz = w.shape[2:]
        pad = _resolve_padding(padding, nd, strides, dilations, ksz)
        out = jax.lax.conv_general_dilated(
            a, wk,
            window_strides=strides,
            padding=pad,
            rhs_dilation=dilations,
            dimension_numbers=(dn_in, dn_k, dn_out),
            feature_group_count=groups,
        )
        if rest:
            b = rest[0]
            shape = [1] * out.ndim
            shape[-1 if channel_last else 1] = b.shape[0]
            out = out + b.reshape(shape)
        return out

    ops = (x, weight) if bias is None else (x, weight, bias)
    return apply(op_name, f, ops)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    fmt = "NCW" if data_format in ("NCL", "NCW") else "NWC"
    return _conv(x, weight, bias, stride, padding, dilation, groups, fmt, 1, "conv1d")


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, data_format, 2, "conv2d")


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, data_format, 3, "conv3d")


def _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation,
                    groups, data_format, nd, op_name, output_size=None):
    strides = _ntuple(stride, nd)
    dilations = _ntuple(dilation, nd)
    out_pad = _ntuple(output_padding, nd)
    channel_last = not data_format.startswith("NC")

    def f(a, w, *rest):
        # paddle transpose-conv weights are [in_c, out_c/groups, *k]
        if channel_last:
            a_ncx = jnp.moveaxis(a, -1, 1)
        else:
            a_ncx = a
        # implement via gradient of forward conv: conv_transpose(x, w) is
        # the VJP of conv(y, w) wrt y — XLA lowers this as a dilated conv
        ksz = w.shape[2:]
        pad = padding if isinstance(padding, str) else _ntuple(padding, nd)
        if isinstance(pad, str):
            raise NotImplementedError("SAME/VALID transpose padding: use ints")
        n, cin = a_ncx.shape[0], a_ncx.shape[1]
        cout = w.shape[1] * groups
        in_spatial = a_ncx.shape[2:]
        out_spatial = tuple(
            (in_spatial[i] - 1) * strides[i]
            - 2 * pad[i]
            + dilations[i] * (ksz[i] - 1)
            + 1 + out_pad[i]
            for i in range(nd)
        )
        if output_size is not None:
            osz = tuple(int(v) for v in _ntuple(output_size, nd))
            out_spatial = osz
        dn = ("NCHW", "OIHW", "NCHW") if nd == 2 else (
            ("NCW", "OIW", "NCW") if nd == 1 else ("NCDHW", "OIDHW", "NCDHW")
        )
        # forward conv maps [n, cout, *out_spatial] -> [n, cin, *in_spatial]
        # with weights [cin, cout/groups, *k]; paddle stores exactly that.
        def fwd_conv(y):
            return jax.lax.conv_general_dilated(
                y, w,
                window_strides=strides,
                padding=[(pad[i], pad[i]) for i in range(nd)],
                rhs_dilation=dilations,
                dimension_numbers=dn,
                feature_group_count=groups,
            )
        zeros = jnp.zeros((n, cout) + out_spatial, a_ncx.dtype)
        _, vjp = jax.vjp(fwd_conv, zeros)
        (out,) = vjp(a_ncx)
        if rest:
            out = out + rest[0].reshape((1, -1) + (1,) * nd)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out

    ops = (x, weight) if bias is None else (x, weight, bias)
    return apply(op_name, f, ops)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCL", name=None):
    fmt = "NCW" if data_format in ("NCL", "NCW") else "NWC"
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, fmt, 1, "conv1d_transpose", output_size)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, data_format, 2, "conv2d_transpose", output_size)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCDHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, data_format, 3, "conv3d_transpose", output_size)
