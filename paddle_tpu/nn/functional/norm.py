"""Normalization functional ops.

Reference parity: `python/paddle/nn/functional/norm.py` over PHI
batch_norm/layer_norm/group_norm/instance_norm kernels. On TPU these are
plain jnp expressions that XLA fuses into one kernel; running-stat updates
happen outside the traced computation (the layer owns the buffers).
"""
from __future__ import annotations

import jax.numpy as jnp

from ...framework.core import Tensor
from ...ops.dispatch import apply
from ...autograd.tape import no_grad


def batch_norm(
    x, running_mean, running_var, weight=None, bias=None, training=False,
    momentum=0.9, epsilon=1e-5, data_format="NCHW", use_global_stats=None, name=None,
):
    """Parity: paddle.nn.functional.batch_norm. In training mode computes
    batch statistics and (eagerly, outside the graph) updates the running
    buffers in place with paddle's convention:
    running = momentum * running + (1 - momentum) * batch."""
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    use_batch_stats = training and not use_global_stats
    # capture the caller's activation dtype BEFORE dispatch: the AMP hook
    # (batch_norm is black-listed) casts the traced input to fp32, so
    # `a.dtype` inside the kernel is fp32 under autocast — the cast-back
    # must target the original dtype for bf16 nets to stay bf16
    orig_dtype = (x._data if hasattr(x, "_data") else jnp.asarray(x)).dtype

    def stats_axes(a):
        if channel_last:
            return tuple(range(a.ndim - 1))
        return (0,) + tuple(range(2, a.ndim))

    def ch_shape(a):
        s = [1] * a.ndim
        s[-1 if channel_last else (1 if a.ndim > 1 else 0)] = -1
        return s

    if use_batch_stats:
        # eager running-stat update (buffer mutation, no grad)
        with no_grad():
            axes = stats_axes(x._data)
            bm = jnp.mean(x._data, axis=axes)
            bv = jnp.var(x._data, axis=axes)
            if running_mean is not None:
                running_mean._data = (
                    momentum * running_mean._data + (1 - momentum) * bm
                ).astype(running_mean._data.dtype)
            if running_var is not None:
                n = x._data.size // bm.size
                unbiased = bv * n / max(n - 1, 1)
                running_var._data = (
                    momentum * running_var._data + (1 - momentum) * unbiased
                ).astype(running_var._data.dtype)

    operands = [x]
    has_w = weight is not None
    has_b = bias is not None
    if not use_batch_stats:
        operands += [running_mean, running_var]
    if has_w:
        operands.append(weight)
    if has_b:
        operands.append(bias)

    def f(a, *rest):
        i = 0
        if not use_batch_stats:
            mean, var = rest[0], rest[1]
            i = 2
        else:
            axes = stats_axes(a)
            mean = jnp.mean(a, axis=axes)
            var = jnp.var(a, axis=axes)
        shape = ch_shape(a)
        out = (a - mean.reshape(shape)) / jnp.sqrt(var.reshape(shape) + epsilon)
        if has_w:
            out = out * rest[i].reshape(shape)
            i += 1
        if has_b:
            out = out + rest[i].reshape(shape)
        # normalize in promoted precision, return the CALLER's dtype:
        # under AMP O2 the running buffers stay fp32 while activations
        # are bf16; without the cast-back a bf16 network leaks fp32
        # activations out of every BN (the reference's O2 batch_norm
        # kernel computes in fp32 and emits the input dtype)
        return out.astype(orig_dtype)

    return apply("batch_norm", f, tuple(operands))


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5, name=None):
    """Parity: paddle.nn.functional.layer_norm
    (`phi/kernels/gpu/layer_norm_kernel.cu`). Normalizes over the trailing
    `normalized_shape` dims; XLA fuses mean/var/scale into one pass."""
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    nd = len(tuple(normalized_shape))

    operands = [x]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        operands.append(weight)
    if has_b:
        operands.append(bias)

    def f(a, *rest):
        axes = tuple(range(a.ndim - nd, a.ndim))
        mean = jnp.mean(a, axis=axes, keepdims=True)
        var = jnp.var(a, axis=axes, keepdims=True)
        out = (a - mean) / jnp.sqrt(var + epsilon)
        i = 0
        if has_w:
            out = out * rest[i].reshape(a.shape[a.ndim - nd:])
            i += 1
        if has_b:
            out = out + rest[i].reshape(a.shape[a.ndim - nd:])
        return out

    return apply("layer_norm", f, tuple(operands))


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    """RMSNorm (no reference equivalent op — used by the Llama family;
    reference models implement it ad hoc). Normalizes the last dim."""
    operands = [x] if weight is None else [x, weight]
    has_w = weight is not None

    def f(a, *rest):
        # compute in fp32 for stability, cast back (matches common practice)
        a32 = a.astype(jnp.float32)
        ms = jnp.mean(a32 * a32, axis=-1, keepdims=True)
        out = (a32 * jnp.reciprocal(jnp.sqrt(ms + epsilon))).astype(a.dtype)
        if has_w:
            out = out * rest[0]
        return out

    return apply("rms_norm", f, tuple(operands))


def group_norm(x, num_groups, weight=None, bias=None, epsilon=1e-5,
               data_format="NCHW", name=None):
    channel_last = not data_format.startswith("NC")
    operands = [x]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        operands.append(weight)
    if has_b:
        operands.append(bias)

    def f(a, *rest):
        if channel_last:
            a_ncx = jnp.moveaxis(a, -1, 1)
        else:
            a_ncx = a
        n, c = a_ncx.shape[0], a_ncx.shape[1]
        spatial = a_ncx.shape[2:]
        g = a_ncx.reshape(n, num_groups, c // num_groups, *spatial)
        axes = tuple(range(2, g.ndim))
        mean = jnp.mean(g, axis=axes, keepdims=True)
        var = jnp.var(g, axis=axes, keepdims=True)
        out = ((g - mean) / jnp.sqrt(var + epsilon)).reshape(a_ncx.shape)
        shape = (1, c) + (1,) * len(spatial)
        i = 0
        if has_w:
            out = out * rest[i].reshape(shape)
            i += 1
        if has_b:
            out = out + rest[i].reshape(shape)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out

    return apply("group_norm", f, tuple(operands))


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW", name=None):
    channel_last = not data_format.startswith("NC")
    operands = [x]
    has_stats = not use_input_stats and running_mean is not None
    has_w = weight is not None
    has_b = bias is not None
    if has_stats:
        operands += [running_mean, running_var]
    if has_w:
        operands.append(weight)
    if has_b:
        operands.append(bias)

    def f(a, *rest):
        a_ncx = jnp.moveaxis(a, -1, 1) if channel_last else a
        i = 0
        if has_stats:
            c = a_ncx.shape[1]
            sh = (1, c) + (1,) * (a_ncx.ndim - 2)
            mean = rest[0].reshape(sh)
            var = rest[1].reshape(sh)
            i = 2
        else:
            axes = tuple(range(2, a_ncx.ndim))
            mean = jnp.mean(a_ncx, axis=axes, keepdims=True)
            var = jnp.var(a_ncx, axis=axes, keepdims=True)
        out = (a_ncx - mean) / jnp.sqrt(var + eps)
        shape = (1, a_ncx.shape[1]) + (1,) * (a_ncx.ndim - 2)
        if has_w:
            out = out * rest[i].reshape(shape)
            i += 1
        if has_b:
            out = out + rest[i].reshape(shape)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out

    return apply("instance_norm", f, tuple(operands))


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    def f(a):
        channel_last = not data_format.startswith("NC")
        ax = a.ndim - 1 if channel_last else 1
        sq = a * a
        # sum over a window of `size` channels centered at each channel
        pad_lo = (size - 1) // 2
        pad_hi = size - 1 - pad_lo
        pads = [(0, 0)] * a.ndim
        pads[ax] = (pad_lo, pad_hi)
        padded = jnp.pad(sq, pads)
        import jax as _jax
        dims = [1] * a.ndim
        dims[ax] = size
        strides = [1] * a.ndim
        window_sum = _jax.lax.reduce_window(
            padded, jnp.asarray(0, a.dtype), _jax.lax.add,
            tuple(dims), tuple(strides), "VALID",
        )
        return a / (k + alpha * window_sum) ** beta
    return apply("local_response_norm", f, (x,))
