"""`paddle.nn.functional` namespace (parity:
`python/paddle/nn/functional/__init__.py`)."""
from .activation import *  # noqa: F401,F403
from .activation import (  # noqa: F401
    relu, relu6, sigmoid, log_sigmoid, tanh, gelu, silu, swish, mish,
    leaky_relu, prelu, rrelu, elu, selu, celu, hardtanh, hardshrink,
    softshrink, tanhshrink, hardsigmoid, hardswish, softplus, softsign,
    softmax, log_softmax, gumbel_softmax, maxout, glu,
)
from .common import (  # noqa: F401
    linear, dropout, dropout2d, dropout3d, alpha_dropout, embedding, one_hot,
    label_smooth, pad, zeropad2d, normalize, cosine_similarity,
    pairwise_distance, pixel_shuffle, pixel_unshuffle, channel_shuffle,
    interpolate, upsample, unfold, fold, bilinear, grid_sample, affine_grid,
    sequence_mask, class_center_sample, gather_tree, temporal_shift,
    diag_embed, sparse_attention,
)
from .conv import (  # noqa: F401
    conv1d, conv2d, conv3d, conv1d_transpose, conv2d_transpose,
    conv3d_transpose,
)
from .pooling import (  # noqa: F401
    max_pool1d, max_pool2d, max_pool3d, avg_pool1d, avg_pool2d, avg_pool3d,
    adaptive_avg_pool1d, adaptive_avg_pool2d, adaptive_avg_pool3d,
    adaptive_max_pool1d, adaptive_max_pool2d, adaptive_max_pool3d, lp_pool2d,
    max_unpool1d, max_unpool2d, max_unpool3d,
)
from .norm import (  # noqa: F401
    batch_norm, layer_norm, rms_norm, group_norm, instance_norm,
    local_response_norm,
)
from .loss import (  # noqa: F401
    cross_entropy, softmax_with_cross_entropy, nll_loss, mse_loss, l1_loss,
    smooth_l1_loss, binary_cross_entropy, binary_cross_entropy_with_logits,
    kl_div, margin_ranking_loss, hinge_embedding_loss, cosine_embedding_loss,
    triplet_margin_loss, multi_label_soft_margin_loss, soft_margin_loss,
    square_error_cost, log_loss, ctc_loss, sigmoid_focal_loss, huber_loss,
    edit_distance, hsigmoid_loss, poisson_nll_loss, gaussian_nll_loss,
    multi_margin_loss, triplet_margin_with_distance_loss, dice_loss,
    npair_loss, rnnt_loss, margin_cross_entropy,
    chunked_softmax_cross_entropy,
)
from .attention import (  # noqa: F401
    scaled_dot_product_attention, flash_attention, ring_flash_attention,
    ulysses_attention, sliding_window_attention,
)
