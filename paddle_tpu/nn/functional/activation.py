"""Activation functions.

Reference parity: `python/paddle/nn/functional/activation.py` over PHI
activation kernels (`paddle/phi/kernels/funcs/activation_functor.h`).
All are single fused XLA elementwise ops — no custom functors needed.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...ops.dispatch import apply
from ...framework import random as rng


def relu(x, name=None):
    return apply("relu", jax.nn.relu, (x,))


def relu_(x, name=None):
    from ...tensor.manipulation import _adopt_inplace
    return _adopt_inplace(x, relu(x))


def relu6(x, name=None):
    return apply("relu6", jax.nn.relu6, (x,))


def sigmoid(x, name=None):
    return apply("sigmoid", jax.nn.sigmoid, (x,))


def log_sigmoid(x, name=None):
    return apply("log_sigmoid", jax.nn.log_sigmoid, (x,))


def tanh(x, name=None):
    return apply("tanh", jnp.tanh, (x,))


def gelu(x, approximate=False, name=None):
    return apply(
        "gelu", lambda a: jax.nn.gelu(a, approximate=approximate), (x,)
    )


def silu(x, name=None):
    return apply("silu", jax.nn.silu, (x,))


def swish(x, name=None):
    return silu(x)


def mish(x, name=None):
    return apply("mish", lambda a: a * jnp.tanh(jax.nn.softplus(a)), (x,))


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply(
        "leaky_relu", lambda a: jax.nn.leaky_relu(a, negative_slope), (x,)
    )


def prelu(x, weight, data_format="NCHW", name=None):
    def f(a, w):
        if w.size == 1:
            return jnp.where(a >= 0, a, w.reshape(()) * a)
        shape = [1] * a.ndim
        ch_axis = 1 if data_format == "NCHW" else a.ndim - 1
        shape[ch_axis] = w.size
        return jnp.where(a >= 0, a, w.reshape(shape) * a)
    return apply("prelu", f, (x, weight))


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=True, name=None):
    if not training:
        return apply(
            "rrelu", lambda a: jnp.where(a >= 0, a, (lower + upper) / 2 * a), (x,)
        )
    key = rng.next_key()
    def f(a):
        slope = jax.random.uniform(key, a.shape, jnp.float32, lower, upper).astype(a.dtype)
        return jnp.where(a >= 0, a, slope * a)
    return apply("rrelu", f, (x,))


def elu(x, alpha=1.0, name=None):
    return apply("elu", lambda a: jax.nn.elu(a, alpha), (x,))


def elu_(x, alpha=1.0, name=None):
    from ...tensor.manipulation import _adopt_inplace
    return _adopt_inplace(x, elu(x, alpha))


def selu(
    x,
    scale=1.0507009873554804934193349852946,
    alpha=1.6732632423543772848170429916717,
    name=None,
):
    return apply(
        "selu",
        lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)),
        (x,),
    )


def celu(x, alpha=1.0, name=None):
    return apply("celu", lambda a: jax.nn.celu(a, alpha), (x,))


def hardtanh(x, min=-1.0, max=1.0, name=None):  # noqa: A002
    return apply("hardtanh", lambda a: jnp.clip(a, min, max), (x,))


def hardshrink(x, threshold=0.5, name=None):
    return apply(
        "hardshrink",
        lambda a: jnp.where(jnp.abs(a) > threshold, a, jnp.zeros((), a.dtype)),
        (x,),
    )


def softshrink(x, threshold=0.5, name=None):
    return apply(
        "softshrink",
        lambda a: jnp.where(
            a > threshold, a - threshold,
            jnp.where(a < -threshold, a + threshold, jnp.zeros((), a.dtype)),
        ),
        (x,),
    )


def tanhshrink(x, name=None):
    return apply("tanhshrink", lambda a: a - jnp.tanh(a), (x,))


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return apply(
        "hardsigmoid", lambda a: jnp.clip(slope * a + offset, 0.0, 1.0), (x,)
    )


def hardswish(x, name=None):
    return apply(
        "hardswish", lambda a: a * jnp.clip(a + 3.0, 0.0, 6.0) / 6.0, (x,)
    )


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return apply(
        "softplus",
        lambda a: jnp.where(
            beta * a > threshold, a, jax.nn.softplus(beta * a) / beta
        ),
        (x,),
    )


def softsign(x, name=None):
    return apply("softsign", jax.nn.soft_sign, (x,))


def softmax(x, axis=-1, dtype=None, name=None):
    def f(a):
        if dtype is not None:
            from ...framework.dtype import convert_dtype
            a = a.astype(convert_dtype(dtype))
        return jax.nn.softmax(a, axis=axis)
    return apply("softmax", f, (x,))


def softmax_(x, axis=-1, dtype=None, name=None):
    from ...tensor.manipulation import _adopt_inplace
    return _adopt_inplace(x, softmax(x, axis, dtype))


def log_softmax(x, axis=-1, dtype=None, name=None):
    def f(a):
        if dtype is not None:
            from ...framework.dtype import convert_dtype
            a = a.astype(convert_dtype(dtype))
        return jax.nn.log_softmax(a, axis=axis)
    return apply("log_softmax", f, (x,))


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    key = rng.next_key()
    def f(a):
        g = jax.random.gumbel(key, a.shape, a.dtype)
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            onehot = jnp.zeros_like(y)
            onehot = jnp.put_along_axis(
                onehot, idx, jnp.ones((), y.dtype), axis=axis, inplace=False
            ) if hasattr(jnp, "put_along_axis") else onehot.at[
                tuple(
                    idx if i == (axis % y.ndim) else ind
                    for i, ind in enumerate(jnp.indices(idx.shape))
                )
            ].set(1.0)
            y = jax.lax.stop_gradient(onehot - y) + y
        return y
    return apply("gumbel_softmax", f, (x,))


def maxout(x, groups, axis=1, name=None):
    def f(a):
        ax = axis % a.ndim
        c = a.shape[ax]
        new_shape = a.shape[:ax] + (c // groups, groups) + a.shape[ax + 1:]
        return jnp.max(a.reshape(new_shape), axis=ax + 1)
    return apply("maxout", f, (x,))


def glu(x, axis=-1, name=None):
    return apply("glu", lambda a: jax.nn.glu(a, axis=axis), (x,))


def tanh_(x, name=None):
    from ...tensor.manipulation import _adopt_inplace
    return _adopt_inplace(x, tanh(x))


def thresholded_relu(x, threshold=1.0, name=None):
    """x if x > threshold else 0 (parity: F.thresholded_relu, ref
    `nn/functional/activation.py:1465`, `thresholded_relu` op)."""
    return apply("thresholded_relu",
                 lambda a: jnp.where(a > threshold, a, jnp.zeros((), a.dtype)),
                 (x,))


def hardtanh_(x, min=-1.0, max=1.0, name=None):  # noqa: A002
    """In-place variant of hardtanh."""
    from ...tensor.manipulation import _adopt_inplace

    return _adopt_inplace(x, hardtanh(x, min, max))


def leaky_relu_(x, negative_slope=0.01, name=None):
    """In-place variant of leaky_relu."""
    from ...tensor.manipulation import _adopt_inplace

    return _adopt_inplace(x, leaky_relu(x, negative_slope))


def thresholded_relu_(x, threshold=1.0, name=None):
    """In-place variant of thresholded_relu."""
    from ...tensor.manipulation import _adopt_inplace

    return _adopt_inplace(x, thresholded_relu(x, threshold))
