"""Attention functional ops.

Reference parity: `paddle.nn.functional.scaled_dot_product_attention` and the
flash-attention PHI kernel (`paddle/phi/kernels/gpu/flash_attn_kernel.cu`,
external `cmake/external/flashattn.cmake`).

TPU-first design: the default implementation is plain jnp (XLA fuses it
well at short seq-len); the op name "flash_attention" is a Pallas override
point — `paddle_tpu.ops.pallas.flash_attention` registers a fused
tiled-softmax kernel for TPU via the kernel registry, exactly how the
reference swaps in the flashattn CUDA library.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...framework.core import Tensor
from ...ops.dispatch import apply


def _sdpa_reference(q, k, v, *rest, causal=False, dropout=0.0, scale=None,
                    dropout_key=None):
    """q,k,v: [batch, seq, heads, head_dim] (paddle flash-attn layout).
    GQA/MQA: kv_heads may divide q heads — KV is repeated here (the
    Pallas kernel instead streams shared KV blocks without the repeat)."""
    hd = q.shape[-1]
    if k.shape[2] != q.shape[2]:
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    s = scale if scale is not None else 1.0 / math.sqrt(hd)
    # [b, h, sq, sk]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * s
    if rest:
        mask = rest[0]
        if mask.dtype == jnp.bool_:
            # paddle attn_mask semantics: bool True = KEEP (an additive
            # 0/1 cast would be silently wrong)
            logits = jnp.where(mask, logits,
                               jnp.asarray(-1e30, logits.dtype))
        else:
            logits = logits + mask.astype(logits.dtype)
    row_valid = None
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(cm, logits, jnp.asarray(-1e30, logits.dtype))
        row_valid = cm.any(-1)  # rows with no visible key (sq > sk head rows)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    if row_valid is not None:
        # flash-attn >= 2.1: a query row that attends to nothing outputs 0
        probs = jnp.where(row_valid[..., None], probs,
                          jnp.zeros((), probs.dtype))
    if dropout > 0.0 and dropout_key is not None:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout), jnp.zeros((), probs.dtype))
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def scaled_dot_product_attention(
    query, key, value, attn_mask=None, dropout_p=0.0,
    is_causal=False, training=True, name=None,
):
    """Inputs [batch, seq, num_heads, head_dim] — same layout as the
    reference's flash_attn op. Routed through op name "flash_attention" so a
    Pallas kernel can take over on TPU."""
    import jax

    from ...framework import random as rng

    operands = [query, key, value]
    if attn_mask is not None:
        operands.append(attn_mask)
    p = dropout_p if training else 0.0
    has_key = p > 0.0
    if has_key:
        # the key rides as an OPERAND (raw uint32 words) so the Pallas
        # kernel can seed its in-kernel dropout mask under jit tracing;
        # the composite fallback re-wraps it into a typed key
        operands.append(jax.random.key_data(rng.next_key()))

    def default(*arrs, causal=False, dropout=0.0, has_key=False):
        dkey = None
        if has_key:
            *arrs, kd = arrs
            dkey = jax.random.wrap_key_data(kd)
        return _sdpa_reference(*arrs, causal=causal, dropout=dropout,
                               dropout_key=dkey)

    return apply(
        "flash_attention",
        default,
        tuple(operands),
        causal=is_causal,
        dropout=p,
        has_key=has_key,
    )


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, name=None):
    """Parity: paddle.nn.functional.flash_attention.flash_attention."""
    out = scaled_dot_product_attention(
        query, key, value, None, dropout, causal
    )
    if return_softmax:
        return out, None
    return out, None


def sliding_window_attention(query, key, value, window_size, name=None):
    """Mistral-style causal local attention: query row r attends keys in
    ``(r - window_size, r]``. EXCEEDS the reference (its flash_attn
    binding has no windowing in this snapshot). Runs the Pallas flash
    kernel with the band mask — fully-masked tiles skip their MXU work,
    so cost is O(seq·window) — and falls back to the banded XLA
    composite where the kernel's shape contract fails. GQA/MQA
    supported (kv heads divide q heads).

    A dedicated dispatch entry rather than a kwarg on the registered
    'flash_attention' kernel: scaled_dot_product_attention (that
    registry's consumer) has no window parameter, so threading one
    through would dead-end; the shape contract below mirrors
    flash_attention_kernel's."""
    if not isinstance(window_size, int) or window_size <= 0:
        raise ValueError(
            f"window_size must be a positive int, got {window_size!r}")
    from ...ops.pallas import autotune as _tune
    from ...ops.pallas import flash_attention as fa

    def fn(q, k, v):
        b, sq, h, d = q.shape
        sk, h_kv = k.shape[1], k.shape[2]
        scale = 1.0 / math.sqrt(d)
        bq, bk = fa._pick_block(sq), fa._pick_block(sk)
        ok_blocks = (bq == sq or bq % 8 == 0) and (bk == sk or bk % 8 == 0)
        kernel_ok = (sq >= 16 and sk >= 16 and d % 8 == 0
                     and h % h_kv == 0 and v.shape[2] == h_kv
                     and ok_blocks)
        if kernel_ok:
            interpret = jax.default_backend() not in ("tpu", "axon")
            bq_t = bk_t = None
            if not interpret:  # measured block sizes transfer here too
                bq_t, bk_t = _tune.best_blocks(sq, sk, d, True)
            qt = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
            kt = k.transpose(0, 2, 1, 3).reshape(b * h_kv, sk, d)
            vt = v.transpose(0, 2, 1, 3).reshape(b * h_kv, sk, d)
            out = fa._flash_bhsd(qt, kt, vt, True, scale, interpret,
                                 bq_t, bk_t, window_size)
            return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
        # banded composite (bottom-right aligned like _sdpa_reference;
        # GQA repeat + exact-zero rows with no visible key)
        if h_kv != h:
            rep = h // h_kv
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                            k.astype(jnp.float32)) * scale
        rows = jnp.arange(sq)[:, None] + (sk - sq)
        cols = jnp.arange(sk)[None, :]
        keep = (rows >= cols) & (cols > rows - window_size)
        logits = jnp.where(keep[None, None], logits, -1e30)
        p = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
        row_valid = keep.any(-1)  # [sq]
        out = out * row_valid[None, :, None, None]
        return out.astype(q.dtype)

    return apply("sliding_window_attention", fn, (query, key, value))


_seq_parallel_cache: dict = {}


def _seq_parallel_attention(op_name, make_fn, query, key, value, axis,
                            causal):
    """Shared wiring for the sequence-parallel attention variants: mesh
    lookup, degree-1 fallback to the single-device attention path, and a
    per-(mesh, axis, causal) cache of the built shard_map program."""
    from ...distributed import env as env_mod

    e = env_mod.ensure_env()
    if e.degree(axis) <= 1:
        return scaled_dot_product_attention(query, key, value,
                                            is_causal=causal)
    key_ = (op_name, e.mesh, axis, causal)
    fn = _seq_parallel_cache.get(key_)
    if fn is None:
        fn = make_fn(e.mesh, axis=axis, causal=causal)
        _seq_parallel_cache[key_] = fn
    return apply(op_name, fn, (query, key, value))


def ring_flash_attention(query, key, value, axis="sep", causal=True,
                         name=None):
    """Context-parallel exact attention: sequence sharded over mesh ``axis``,
    KV blocks rotating on the ICI ring (`ops/ring_attention.py`). Exceeds the
    reference (SURVEY §5.7: no ring/context parallelism in the snapshot).
    Degree-1 axes fall back to the single-device attention path."""
    from ...ops.ring_attention import make_ring_attention

    return _seq_parallel_attention("ring_flash_attention",
                                   make_ring_attention, query, key, value,
                                   axis, causal)


def ulysses_attention(query, key, value, axis="sep", causal=True,
                      name=None):
    """DeepSpeed-Ulysses sequence parallelism: two all-to-alls re-shard
    heads across ``axis`` so each device attends over the FULL sequence
    with h/n heads (`ops/ulysses_attention.py`). Exceeds the reference
    (SURVEY §2.6 lists Ulysses as absent). Complements
    :func:`ring_flash_attention`: prefer Ulysses when heads are
    plentiful, the ring at extreme sequence lengths. Degree-1 axes fall
    back to the single-device attention path."""
    from ...ops.ulysses_attention import make_ulysses_attention

    return _seq_parallel_attention("ulysses_attention",
                                   make_ulysses_attention, query, key,
                                   value, axis, causal)
