"""Attention functional ops.

Reference parity: `paddle.nn.functional.scaled_dot_product_attention` and the
flash-attention PHI kernel (`paddle/phi/kernels/gpu/flash_attn_kernel.cu`,
external `cmake/external/flashattn.cmake`).

TPU-first design: the default implementation is plain jnp (XLA fuses it
well at short seq-len); the op name "flash_attention" is a Pallas override
point — `paddle_tpu.ops.pallas.flash_attention` registers a fused
tiled-softmax kernel for TPU via the kernel registry, exactly how the
reference swaps in the flashattn CUDA library.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...framework.core import Tensor
from ...ops.dispatch import apply


def _sdpa_reference(q, k, v, *rest, causal=False, dropout=0.0, scale=None,
                    dropout_key=None):
    """q,k,v: [batch, seq, heads, head_dim] (paddle flash-attn layout).
    GQA/MQA: kv_heads may divide q heads — KV is repeated here (the
    Pallas kernel instead streams shared KV blocks without the repeat)."""
    hd = q.shape[-1]
    if k.shape[2] != q.shape[2]:
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    s = scale if scale is not None else 1.0 / math.sqrt(hd)
    # [b, h, sq, sk]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * s
    if rest:
        mask = rest[0]
        logits = logits + mask.astype(logits.dtype)
    row_valid = None
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(cm, logits, jnp.asarray(-1e30, logits.dtype))
        row_valid = cm.any(-1)  # rows with no visible key (sq > sk head rows)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    if row_valid is not None:
        # flash-attn >= 2.1: a query row that attends to nothing outputs 0
        probs = jnp.where(row_valid[..., None], probs,
                          jnp.zeros((), probs.dtype))
    if dropout > 0.0 and dropout_key is not None:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout), jnp.zeros((), probs.dtype))
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def scaled_dot_product_attention(
    query, key, value, attn_mask=None, dropout_p=0.0,
    is_causal=False, training=True, name=None,
):
    """Inputs [batch, seq, num_heads, head_dim] — same layout as the
    reference's flash_attn op. Routed through op name "flash_attention" so a
    Pallas kernel can take over on TPU."""
    from ...framework import random as rng

    operands = (query, key, value) if attn_mask is None else (
        query, key, value, attn_mask
    )
    p = dropout_p if training else 0.0
    dk = rng.next_key() if p > 0.0 else None

    def default(*arrs, causal=False, dropout=0.0):
        return _sdpa_reference(*arrs, causal=causal, dropout=dropout,
                               dropout_key=dk)

    return apply(
        "flash_attention",
        default,
        operands,
        causal=is_causal,
        dropout=p,
    )


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, name=None):
    """Parity: paddle.nn.functional.flash_attention.flash_attention."""
    out = scaled_dot_product_attention(
        query, key, value, None, dropout, causal
    )
    if return_softmax:
        return out, None
    return out, None


_ring_cache: dict = {}


def ring_flash_attention(query, key, value, axis="sep", causal=True,
                         name=None):
    """Context-parallel exact attention: sequence sharded over mesh ``axis``,
    KV blocks rotating on the ICI ring (`ops/ring_attention.py`). Exceeds the
    reference (SURVEY §5.7: no ring/context parallelism in the snapshot).
    Degree-1 axes fall back to the regular flash_attention path."""
    from ...distributed import env as env_mod
    from ...ops.ring_attention import make_ring_attention

    e = env_mod.ensure_env()
    if e.degree(axis) <= 1:
        return scaled_dot_product_attention(query, key, value,
                                            is_causal=causal)

    ring = _ring_cache.get((e.mesh, axis, causal))
    if ring is None:
        ring = make_ring_attention(e.mesh, axis=axis, causal=causal)
        _ring_cache[(e.mesh, axis, causal)] = ring
    return apply("ring_flash_attention", ring, (query, key, value))
