"""`paddle.nn` namespace (parity: `python/paddle/nn/__init__.py`)."""
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from .clip import (  # noqa: F401
    ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm,
)
from .layer.layers import (  # noqa: F401
    Layer, Sequential, LayerList, ParameterList, LayerDict, Identity,
    ParamAttr,
)
from .layer.common import (  # noqa: F401
    Linear, Embedding, Dropout, Dropout2D, Dropout3D, AlphaDropout, Flatten,
    Unflatten,
    Upsample, UpsamplingBilinear2D, UpsamplingNearest2D, Pad1D, Pad2D, Pad3D,
    ZeroPad2D, PixelShuffle, PixelUnshuffle, ChannelShuffle, Bilinear,
    CosineSimilarity, PairwiseDistance, Unfold, Fold,
)
from .layer.conv import (  # noqa: F401
    Conv1D, Conv2D, Conv3D, Conv1DTranspose, Conv2DTranspose, Conv3DTranspose,
)
from .layer.norm import (  # noqa: F401
    BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D, SyncBatchNorm,
    LayerNorm, RMSNorm, GroupNorm, InstanceNorm1D, InstanceNorm2D,
    InstanceNorm3D, LocalResponseNorm, SpectralNorm,
)
from .layer.pooling import (  # noqa: F401
    MaxPool1D, MaxPool2D, MaxPool3D, AvgPool1D, AvgPool2D, AvgPool3D,
    AdaptiveAvgPool1D, AdaptiveAvgPool2D, AdaptiveAvgPool3D,
    AdaptiveMaxPool1D, AdaptiveMaxPool2D, AdaptiveMaxPool3D, LPPool2D,
    MaxUnPool1D, MaxUnPool2D, MaxUnPool3D,
)
from .layer.activation import (  # noqa: F401
    ReLU, ReLU6, Sigmoid, LogSigmoid, Tanh, Tanhshrink, GELU, SiLU, Swish,
    Mish, LeakyReLU, ELU, SELU, CELU, Hardtanh, Hardshrink, Softshrink,
    Hardsigmoid, Hardswish, Softplus, Softsign, Softmax, LogSoftmax, Maxout,
    GLU, RReLU, PReLU, Silu, ThresholdedReLU, Softmax2D,
)
from .layer.loss import (  # noqa: F401
    CrossEntropyLoss, MSELoss, L1Loss, NLLLoss, BCELoss, BCEWithLogitsLoss,
    SmoothL1Loss, KLDivLoss, MarginRankingLoss, HingeEmbeddingLoss,
    CosineEmbeddingLoss, TripletMarginLoss, MultiLabelSoftMarginLoss,
    SoftMarginLoss, CTCLoss, PoissonNLLLoss, GaussianNLLLoss,
    MultiMarginLoss, TripletMarginWithDistanceLoss, RNNTLoss, HSigmoidLoss,
)
from .layer.transformer import (  # noqa: F401
    MultiHeadAttention, TransformerEncoderLayer, TransformerEncoder,
    TransformerDecoderLayer, TransformerDecoder, Transformer,
)
from .layer.rnn import (  # noqa: F401
    SimpleRNNCell, LSTMCell, GRUCell, SimpleRNN, LSTM, GRU, RNN, BiRNN,
    RNNCellBase, BeamSearchDecoder, dynamic_decode,
)

from . import quant  # noqa: F401
from . import utils  # noqa: F401
