"""Gradient clipping (parity: `python/paddle/nn/clip.py` — ClipGradByValue,
ClipGradByNorm, ClipGradByGlobalNorm, applied inside optimizer.step)."""
from __future__ import annotations

import jax.numpy as jnp

from ..framework.core import Tensor


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):  # noqa: A002
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or (hasattr(p, "need_clip") and not p.need_clip):
                out.append((p, g))
                continue
            out.append((p, Tensor(jnp.clip(g._data, self.min, self.max))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or (hasattr(p, "need_clip") and not p.need_clip):
                out.append((p, g))
                continue
            norm = jnp.linalg.norm(g._data.astype(jnp.float32).reshape(-1))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((p, Tensor((g._data * scale).astype(g._data.dtype))))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    """The hybrid-parallel default clip. Under sharded training the global
    norm is computed over the full (sharded) gradient set; inside pjit the
    sum is a global reduction XLA handles across the mesh."""

    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def __call__(self, params_grads):
        sq_sum = None
        for p, g in params_grads:
            if g is None or (hasattr(p, "need_clip") and not p.need_clip):
                continue
            s = jnp.sum(g._data.astype(jnp.float32) ** 2)
            sq_sum = s if sq_sum is None else sq_sum + s
        if sq_sum is None:
            return params_grads
        global_norm = jnp.sqrt(sq_sum)
        scale = jnp.minimum(self.clip_norm / jnp.maximum(global_norm, 1e-12), 1.0)
        out = []
        for p, g in params_grads:
            if g is None or (hasattr(p, "need_clip") and not p.need_clip):
                out.append((p, g))
                continue
            out.append((p, Tensor((g._data * scale).astype(g._data.dtype))))
        return out


def clip_grad_norm_(parameters, max_norm, norm_type=2.0, error_if_nonfinite=False):
    """torch-style utility paddle also ships
    (`python/paddle/nn/utils/clip_grad_norm_.py`)."""
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p.grad for p in parameters if p.grad is not None]
    if not grads:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g._data)) for g in grads]))
    else:
        total = jnp.sum(
            jnp.stack([jnp.sum(jnp.abs(g._data.astype(jnp.float32)) ** norm_type) for g in grads])
        ) ** (1.0 / norm_type)
    scale = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    for p in parameters:
        if p.grad is not None:
            p.grad._data = (p.grad._data * scale).astype(p.grad._data.dtype)
    return Tensor(total)


def clip_grad_value_(parameters, clip_value):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    for p in parameters:
        if p.grad is not None:
            p.grad._data = jnp.clip(p.grad._data, -clip_value, clip_value)
