"""Activation layers (parity: `python/paddle/nn/layer/activation.py`)."""
from __future__ import annotations

from .. import functional as F
from .. import initializer as I
from .layers import Layer


def _act_layer(name, fn, **defaults):
    class _Act(Layer):
        def __init__(self, *args, **kwargs):
            super().__init__()
            merged = dict(defaults)
            # positional args map onto the declared default keys in order
            for k, v in zip(defaults.keys(), args):
                merged[k] = v
            for k, v in kwargs.items():
                if k in merged:
                    merged[k] = v
            self._kwargs = merged

        def forward(self, x):
            return fn(x, **self._kwargs)

    _Act.__name__ = _Act.__qualname__ = name
    return _Act


ReLU = _act_layer("ReLU", F.relu)
ReLU6 = _act_layer("ReLU6", F.relu6)
Sigmoid = _act_layer("Sigmoid", F.sigmoid)
LogSigmoid = _act_layer("LogSigmoid", F.log_sigmoid)
Tanh = _act_layer("Tanh", F.tanh)
Tanhshrink = _act_layer("Tanhshrink", F.tanhshrink)
GELU = _act_layer("GELU", F.gelu, approximate=False)
SiLU = _act_layer("SiLU", F.silu)
Swish = _act_layer("Swish", F.swish)
Mish = _act_layer("Mish", F.mish)
LeakyReLU = _act_layer("LeakyReLU", F.leaky_relu, negative_slope=0.01)
ELU = _act_layer("ELU", F.elu, alpha=1.0)
SELU = _act_layer("SELU", F.selu)
CELU = _act_layer("CELU", F.celu, alpha=1.0)
Hardtanh = _act_layer("Hardtanh", F.hardtanh, min=-1.0, max=1.0)
Hardshrink = _act_layer("Hardshrink", F.hardshrink, threshold=0.5)
Softshrink = _act_layer("Softshrink", F.softshrink, threshold=0.5)
Hardsigmoid = _act_layer("Hardsigmoid", F.hardsigmoid)
Hardswish = _act_layer("Hardswish", F.hardswish)
Softplus = _act_layer("Softplus", F.softplus, beta=1.0, threshold=20.0)
Softsign = _act_layer("Softsign", F.softsign)
Softmax = _act_layer("Softmax", F.softmax, axis=-1)
LogSoftmax = _act_layer("LogSoftmax", F.log_softmax, axis=-1)
Maxout = _act_layer("Maxout", F.maxout, groups=2, axis=1)
GLU = _act_layer("GLU", F.glu, axis=-1)


class RReLU(Layer):
    """Randomized leaky ReLU: random slope in training, mean slope in eval
    (parity: paddle.nn.RReLU)."""

    def __init__(self, lower=1.0 / 8.0, upper=1.0 / 3.0, name=None):
        super().__init__()
        self.lower, self.upper = lower, upper

    def forward(self, x):
        return F.rrelu(x, self.lower, self.upper, training=self.training)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter(
            [num_parameters], attr=weight_attr,
            default_initializer=I.Constant(init),
        )

    def forward(self, x):
        return F.prelu(x, self.weight, self._data_format)


# reference exports both spellings; ThresholdedReLU rides the factory
Silu = SiLU
ThresholdedReLU = _act_layer("ThresholdedReLU", F.thresholded_relu,
                             threshold=1.0)


class Softmax2D(Layer):
    """Softmax over the channel axis of NCHW input (parity:
    paddle.nn.Softmax2D)."""

    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        if x.ndim not in (3, 4):
            raise ValueError(
                f"Softmax2D expects 3D/4D input, got ndim={x.ndim}")
        return F.softmax(x, axis=-3)
