"""Loss layers (parity: `python/paddle/nn/layer/loss.py`)."""
from __future__ import annotations

from .. import functional as F
from .layers import Layer


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 soft_label=False, axis=-1, use_softmax=True,
                 label_smoothing=0.0, name=None):
        super().__init__()
        self._weight = weight
        self._args = dict(
            ignore_index=ignore_index, reduction=reduction,
            soft_label=soft_label, axis=axis, use_softmax=use_softmax,
            label_smoothing=label_smoothing,
        )

    def forward(self, input, label):  # noqa: A002
        return F.cross_entropy(input, label, weight=self._weight, **self._args)


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.mse_loss(input, label, self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.l1_loss(input, label, self.reduction)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean", name=None):
        super().__init__()
        self._weight = weight
        self._ignore_index = ignore_index
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.nll_loss(input, label, self._weight, self._ignore_index,
                          self.reduction)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self._weight = weight
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.binary_cross_entropy(input, label, self._weight, self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction="mean", pos_weight=None, name=None):
        super().__init__()
        self._weight = weight
        self._pos_weight = pos_weight
        self.reduction = reduction

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(
            logit, label, self._weight, self.reduction, self._pos_weight)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self.reduction = reduction
        self.delta = delta

    def forward(self, input, label):  # noqa: A002
        return F.smooth_l1_loss(input, label, self.reduction, self.delta)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean", log_target=False):
        super().__init__()
        self.reduction = reduction
        self.log_target = log_target

    def forward(self, input, label):  # noqa: A002
        return F.kl_div(input, label, self.reduction, self.log_target)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, other, label):  # noqa: A002
        return F.margin_ranking_loss(input, other, label, self.margin,
                                     self.reduction)


class HingeEmbeddingLoss(Layer):
    def __init__(self, margin=1.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.hinge_embedding_loss(input, label, self.margin, self.reduction)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input1, input2, label):
        return F.cosine_embedding_loss(input1, input2, label, self.margin,
                                       self.reduction)


class TripletMarginLoss(Layer):
    def __init__(self, margin=1.0, p=2.0, epsilon=1e-6, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.args = (margin, p, epsilon, swap, reduction)

    def forward(self, input, positive, negative):  # noqa: A002
        return F.triplet_margin_loss(input, positive, negative, *self.args)


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self._weight = weight
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.multi_label_soft_margin_loss(input, label, self._weight,
                                              self.reduction)


class SoftMarginLoss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.soft_margin_loss(input, label, self.reduction)


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank = blank
        self.reduction = reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          self.blank, self.reduction, norm_by_times)


class PoissonNLLLoss(Layer):
    def __init__(self, log_input=True, full=False, epsilon=1e-8,
                 reduction="mean", name=None):
        super().__init__()
        self.log_input = log_input
        self.full = full
        self.epsilon = epsilon
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.poisson_nll_loss(input, label, self.log_input, self.full,
                                  self.epsilon, self.reduction)


class GaussianNLLLoss(Layer):
    def __init__(self, full=False, epsilon=1e-6, reduction="mean",
                 name=None):
        super().__init__()
        self.full = full
        self.epsilon = epsilon
        self.reduction = reduction

    def forward(self, input, label, variance):  # noqa: A002
        return F.gaussian_nll_loss(input, label, variance, self.full,
                                   self.epsilon, self.reduction)


class MultiMarginLoss(Layer):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean",
                 name=None):
        super().__init__()
        self.p = p
        self.margin = margin
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.multi_margin_loss(input, label, self.p, self.margin,
                                   self.weight, self.reduction)


class TripletMarginWithDistanceLoss(Layer):
    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.distance_function = distance_function
        self.margin = margin
        self.swap = swap
        self.reduction = reduction

    def forward(self, input, positive, negative):  # noqa: A002
        return F.triplet_margin_with_distance_loss(
            input, positive, negative, self.distance_function, self.margin,
            self.swap, self.reduction)


class RNNTLoss(Layer):
    def __init__(self, blank=0, fastemit_lambda=0.001, reduction="mean",
                 name=None):
        super().__init__()
        self.blank = blank
        self.fastemit_lambda = fastemit_lambda
        self.reduction = reduction

    def forward(self, input, label, input_lengths, label_lengths):  # noqa: A002
        return F.rnnt_loss(input, label, input_lengths, label_lengths,
                           self.blank, self.fastemit_lambda, self.reduction)


class HSigmoidLoss(Layer):
    """Hierarchical sigmoid (parity: paddle.nn.HSigmoidLoss over
    F.hsigmoid_loss)."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        self.num_classes = num_classes
        n_nodes = num_classes - 1
        self.weight = self.create_parameter([n_nodes, feature_size],
                                            attr=weight_attr)
        self.bias = self.create_parameter([n_nodes], attr=bias_attr,
                                          is_bias=True)

    def forward(self, input, label, path_table=None, path_code=None):  # noqa: A002
        return F.hsigmoid_loss(input, label, self.num_classes, self.weight,
                               self.bias, path_table, path_code)
