"""`nn.Layer` — the module base class.

Reference parity: `python/paddle/nn/layer/layers.py` (`Layer`): parameter /
sublayer / buffer registries, forward hooks, `state_dict` /
`set_state_dict`, `train`/`eval`, `apply`, `to`. The semantics (e.g.
``create_parameter`` with ParamAttr, name scoping) follow the reference; the
storage is plain Tensors over jax arrays.
"""
from __future__ import annotations

import collections

import numpy as np

import jax

from ...framework import dtype as dtype_mod
from ...framework.core import EagerParamBase, Tensor
from .. import initializer as I


class ParamAttr:
    """Parity: `python/paddle/fluid/param_attr.py` (ParamAttr)."""

    def __init__(
        self,
        name=None,
        initializer=None,
        learning_rate=1.0,
        regularizer=None,
        trainable=True,
        need_clip=True,
    ):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None:
            return ParamAttr()
        if isinstance(attr, ParamAttr):
            return attr
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        if attr is False:
            return False
        if isinstance(attr, I.Initializer):
            return ParamAttr(initializer=attr)
        raise TypeError(f"cannot convert {attr!r} to ParamAttr")


class HookRemoveHelper:
    def __init__(self, hooks, idx):
        self._hooks, self._idx = hooks, idx

    def remove(self):
        self._hooks.pop(self._idx, None)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self.training = True
        self._dtype = dtype
        self._full_name = name_scope or type(self).__name__.lower()
        self._parameters = collections.OrderedDict()
        self._sub_layers = collections.OrderedDict()
        self._buffers = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self._hook_id = 0

    # ---- construction helpers ----
    def create_parameter(
        self,
        shape,
        attr=None,
        dtype=None,
        is_bias=False,
        default_initializer=None,
    ):
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = dtype or self._dtype
        init = attr.initializer or default_initializer
        if init is None:
            init = I.Constant(0.0) if is_bias else I.XavierNormal()
        if I._GLOBAL[0] is not None and attr.initializer is None and default_initializer is None:
            init = I._GLOBAL[1 if is_bias else 0] or init
        data = init(shape, dtype)
        p = EagerParamBase(data, name=attr.name, trainable=attr.trainable)
        p.optimize_attr = {"learning_rate": attr.learning_rate}
        p.regularizer = attr.regularizer
        p.need_clip = attr.need_clip
        return p

    def add_parameter(self, name, parameter):
        if parameter is not None and not isinstance(parameter, Tensor):
            raise TypeError("parameter must be a Tensor/Parameter")
        self._parameters[name] = parameter
        object.__setattr__(self, name, parameter)
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        object.__setattr__(self, name, tensor)
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # ---- attribute routing ----
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, EagerParamBase) or (
            isinstance(value, Tensor) and getattr(value, "is_parameter", False)
        ):
            if params is None:
                raise RuntimeError("call super().__init__() first")
            params[name] = value
            if buffers and name in buffers:
                del buffers[name]
            object.__setattr__(self, name, value)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call super().__init__() first")
            layers[name] = value
            object.__setattr__(self, name, value)
        else:
            if params is not None and name in params and value is None:
                params[name] = None
            if isinstance(value, Tensor) and buffers is not None and name in buffers:
                buffers[name] = value
            object.__setattr__(self, name, value)

    def __delattr__(self, name):
        self._parameters.pop(name, None)
        self._sub_layers.pop(name, None)
        self._buffers.pop(name, None)
        object.__delattr__(self, name)

    # ---- iteration ----
    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        for name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            if not include_sublayers and layer is not self:
                continue
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (f"{name}.{pname}" if name else pname), p

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        for name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            if not include_sublayers and layer is not self:
                continue
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (f"{name}.{bname}" if name else bname), b

    def children(self):
        for _, layer in self.named_children():
            yield layer

    def named_children(self):
        seen = set()
        for name, layer in self._sub_layers.items():
            if layer is not None and id(layer) not in seen:
                seen.add(id(layer))
                yield name, layer

    def sublayers(self, include_self=False):
        return [layer for _, layer in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix="", include_self=False):
        if include_self:
            yield prefix, self
        for name, layer in self.named_children():
            if layer is None:
                continue
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield from layer.named_sublayers(prefix=sub_prefix, include_self=True)

    # ---- state dict ----
    def state_dict(self, destination=None, include_sublayers=True, use_hook=True):
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(include_sublayers=include_sublayers):
            dest[name] = p
        for name, layer in self.named_sublayers(include_self=True):
            for bname, b in layer._buffers.items():
                if b is None or bname in layer._non_persistable_buffer_names:
                    continue
                key = f"{name}.{bname}" if name else bname
                dest[key] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        """Load values into existing parameters/buffers by name. Returns
        (missing_keys, unexpected_keys) like the reference."""
        own = self.state_dict()
        missing, matched = [], set()
        for name, target in own.items():
            if name not in state_dict:
                missing.append(name)
                continue
            value = state_dict[name]
            arr = value.numpy() if isinstance(value, Tensor) else np.asarray(value)
            if tuple(arr.shape) != tuple(target.shape):
                raise ValueError(
                    f"shape mismatch for {name}: loaded {arr.shape} vs "
                    f"expected {tuple(target.shape)}"
                )
            target._data = jax.device_put(
                arr.astype(np.dtype(target.dtype), copy=False)
                if arr.dtype != np.dtype(target.dtype) else arr,
                next(iter(target._data.devices())) if hasattr(target._data, "devices") else None,
            )
            matched.add(name)
        unexpected = [k for k in state_dict if k not in own]
        return missing, unexpected

    load_dict = set_state_dict

    # ---- modes ----
    def train(self):
        self.training = True
        for layer in self.sublayers():
            layer.training = True
        return self

    def eval(self):
        self.training = False
        for layer in self.sublayers():
            layer.training = False
        return self

    def apply(self, fn):
        for layer in self.sublayers(include_self=True):
            fn(layer)
        return self

    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            d = dtype_mod.convert_dtype(dtype)
            for p in self.parameters():
                if dtype_mod.is_floating_point(p.dtype):
                    p._data = p._data.astype(d)
            for b in self.buffers():
                if dtype_mod.is_floating_point(b.dtype):
                    b._data = b._data.astype(d)
        if device is not None:
            if isinstance(device, str):
                from ...framework.device import _PLATFORM_ALIASES, _available_platforms

                plat = device.split(":")[0]
                idx = int(device.split(":")[1]) if ":" in device else 0
                plats = _available_platforms()
                dev = None
                for cand in _PLATFORM_ALIASES.get(plat, (plat,)):
                    if cand in plats:
                        dev = plats[cand][idx]
                        break
                if dev is None:
                    raise ValueError(
                        f"device {device!r} not available; present: {sorted(plats)}"
                    )
            else:
                dev = device
            for t in list(self.parameters()) + list(self.buffers()):
                t._data = jax.device_put(t._data, dev)
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def half(self):
        return self.to(dtype="float16")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    # ---- hooks ----
    def register_forward_pre_hook(self, hook):
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook):
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # ---- call ----
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            result = hook(self, inputs, outputs)
            if result is not None:
                outputs = result
        return outputs

    def full_name(self):
        return self._full_name

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = [f"{type(self).__name__}({extra}"]
        for name, child in self.named_children():
            child_repr = repr(child).replace("\n", "\n  ")
            lines.append(f"  ({name}): {child_repr}")
        if len(lines) == 1:
            return lines[0] + ")"
        return "\n".join(lines) + "\n)"

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_gradient()


class Sequential(Layer):
    """Parity: `paddle.nn.Sequential`."""

    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], collections.OrderedDict):
            for name, layer in layers[0].items():
                self.add_sublayer(name, layer)
        else:
            for i, item in enumerate(layers):
                if isinstance(item, tuple):
                    self.add_sublayer(item[0], item[1])
                else:
                    self.add_sublayer(str(i), item)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return Sequential(*list(self._sub_layers.values())[idx])
        return list(self._sub_layers.values())[idx]

    def __len__(self):
        return len(self._sub_layers)

    def forward(self, x):
        for layer in self._sub_layers.values():
            x = layer(x)
        return x


class LayerList(Layer):
    """Parity: `paddle.nn.LayerList`."""

    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            for i, layer in enumerate(sublayers):
                self.add_sublayer(str(i), layer)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return LayerList(list(self._sub_layers.values())[idx])
        return list(self._sub_layers.values())[int(idx)]

    def __setitem__(self, idx, layer):
        self._sub_layers[str(idx)] = layer

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def append(self, layer):
        self.add_sublayer(str(len(self._sub_layers)), layer)
        return self

    def insert(self, index, layer):
        layers = list(self._sub_layers.values())
        layers.insert(index, layer)
        self._sub_layers.clear()
        for i, l in enumerate(layers):
            self._sub_layers[str(i)] = l

    def extend(self, layers):
        for layer in layers:
            self.append(layer)
        return self


class ParameterList(Layer):
    """Parity: `paddle.nn.ParameterList`."""

    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)
                object.__setattr__(self, str(i), p)

    def __getitem__(self, idx):
        return list(self._parameters.values())[idx]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())

    def append(self, parameter):
        self.add_parameter(str(len(self._parameters)), parameter)
        return self


class LayerDict(Layer):
    """Parity: `paddle.nn.LayerDict`."""

    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            self.update(sublayers)

    def __getitem__(self, key):
        return self._sub_layers[key]

    def __setitem__(self, key, layer):
        self.add_sublayer(key, layer)

    def __delitem__(self, key):
        del self._sub_layers[key]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers)

    def __contains__(self, key):
        return key in self._sub_layers

    def keys(self):
        return self._sub_layers.keys()

    def items(self):
        return self._sub_layers.items()

    def values(self):
        return self._sub_layers.values()

    def update(self, sublayers):
        items = sublayers.items() if isinstance(sublayers, (dict, LayerDict)) else sublayers
        for key, layer in items:
            self.add_sublayer(key, layer)
        return self

    def pop(self, key):
        layer = self._sub_layers.pop(key)
        return layer

    def clear(self):
        self._sub_layers.clear()


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, x):
        return x
