"""Norm layers (parity: `python/paddle/nn/layer/norm.py`)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ...framework.core import Tensor
from .. import functional as F
from .. import initializer as I
from .layers import Layer


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                [num_features], attr=weight_attr,
                default_initializer=I.Constant(1.0),
            )
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                [num_features], attr=bias_attr, is_bias=True,
            )
        self.register_buffer("_mean", Tensor(np.zeros(num_features, np.float32)))
        self.register_buffer("_variance", Tensor(np.ones(num_features, np.float32)))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum,
            epsilon=self._epsilon, data_format=self._data_format,
            use_global_stats=self._use_global_stats,
        )

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}"


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class SyncBatchNorm(_BatchNormBase):
    """On TPU, batch norm under GSPMD data parallelism already reduces over
    the global batch when the stats computation is sharded; this subclass
    exists for API parity (reference `python/paddle/nn/layer/norm.py`
    SyncBatchNorm + `sync_batch_norm` op)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        for name, sub in list(layer._sub_layers.items()):
            converted = cls.convert_sync_batchnorm(sub)
            if converted is not sub:
                # setattr keeps both the registry AND the parent's attribute
                # reference consistent
                setattr(layer, name, converted)
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, SyncBatchNorm):
            new = SyncBatchNorm(
                layer._num_features, layer._momentum, layer._epsilon,
                data_format=layer._data_format,
            )
            new.weight = layer.weight
            new.bias = layer.bias
            # adopt the trained running-stat tensors (register_buffer keeps
            # the attribute and the _buffers registry in sync)
            new.register_buffer("_mean", layer._mean)
            new.register_buffer("_variance", layer._variance)
            return new
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                self._normalized_shape, attr=weight_attr,
                default_initializer=I.Constant(1.0),
            )
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                self._normalized_shape, attr=bias_attr, is_bias=True,
            )

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias,
                            self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}"


class RMSNorm(Layer):
    """TPU-first addition used by Llama-family models."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            [hidden_size], attr=weight_attr, default_initializer=I.Constant(1.0),
        )

    def forward(self, x):
        return F.rms_norm(x, self.weight, self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = (
            None if weight_attr is False else self.create_parameter(
                [num_channels], attr=weight_attr,
                default_initializer=I.Constant(1.0))
        )
        self.bias = (
            None if bias_attr is False else self.create_parameter(
                [num_channels], attr=bias_attr, is_bias=True)
        )

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self.weight, self.bias,
                            self._epsilon, self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = (
            None if weight_attr is False else self.create_parameter(
                [num_features], attr=weight_attr,
                default_initializer=I.Constant(1.0))
        )
        self.bias = (
            None if bias_attr is False else self.create_parameter(
                [num_features], attr=bias_attr, is_bias=True)
        )

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self._epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self.args)


class SpectralNorm(Layer):
    """Power-iteration spectral norm of a weight (parity:
    paddle.nn.SpectralNorm / `phi/kernels/.../spectral_norm_kernel`)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 dtype="float32"):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._eps = eps
        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        self.register_buffer("weight_u", Tensor(np.random.randn(h).astype(np.float32)))
        self.register_buffer("weight_v", Tensor(np.random.randn(w).astype(np.float32)))

    def forward(self, weight):
        from ...ops.dispatch import apply
        from ...autograd.tape import no_grad
        u0, v0 = self.weight_u._data, self.weight_v._data
        dim, iters, eps = self._dim, self._power_iters, self._eps
        # advance the persistent power-iteration vectors (no grad)
        with no_grad():
            mat = jnp.moveaxis(weight._data, dim, 0).reshape(
                weight._data.shape[dim], -1
            )
            u, v = u0, v0
            for _ in range(iters):
                v = mat.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = mat @ v
                u = u / (jnp.linalg.norm(u) + eps)
            self.weight_u._data = u
            self.weight_v._data = v
        uf, vf = u, v
        def f(w):
            m = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
            sigma = uf @ m @ vf
            return w / sigma
        return apply("spectral_norm", f, (weight,))
