"""Recurrent layers via `lax.scan` (compiler-friendly TPU control flow),
replacing the reference's cuDNN RNN kernels
(`python/paddle/nn/layer/rnn.py`, `phi/kernels/gpu/rnn_kernel.cu`).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.core import Tensor
from ...ops.dispatch import apply
from .. import initializer as I
from .layers import Layer


class _RNNCellBase(Layer):
    def __init__(self, input_size, hidden_size, gates, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            [gates * hidden_size, input_size], attr=weight_ih_attr,
            default_initializer=u)
        self.weight_hh = self.create_parameter(
            [gates * hidden_size, hidden_size], attr=weight_hh_attr,
            default_initializer=u)
        self.bias_ih = self.create_parameter(
            [gates * hidden_size], attr=bias_ih_attr, is_bias=True,
            default_initializer=u)
        self.bias_hh = self.create_parameter(
            [gates * hidden_size], attr=bias_hh_attr, is_bias=True,
            default_initializer=u)


class SimpleRNNCell(_RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh", **kwargs):
        super().__init__(input_size, hidden_size, 1, **kwargs)
        self.activation = activation

    def forward(self, inputs, states=None):
        from ...tensor import creation
        if states is None:
            states = creation.zeros([inputs.shape[0], self.hidden_size], inputs.dtype)
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu
        def f(x, h, wih, whh, bih, bhh):
            out = act(x @ wih.T + bih + h @ whh.T + bhh)
            return out
        h = apply("simple_rnn_cell", f, (inputs, states, self.weight_ih,
                                         self.weight_hh, self.bias_ih, self.bias_hh))
        return h, h


class LSTMCell(_RNNCellBase):
    def __init__(self, input_size, hidden_size, **kwargs):
        super().__init__(input_size, hidden_size, 4, **kwargs)

    def forward(self, inputs, states=None):
        from ...tensor import creation
        if states is None:
            z = creation.zeros([inputs.shape[0], self.hidden_size], inputs.dtype)
            states = (z, z)
        h_prev, c_prev = states
        def f(x, h, c, wih, whh, bih, bhh):
            gates = x @ wih.T + bih + h @ whh.T + bhh
            i, fg, g, o = jnp.split(gates, 4, axis=-1)
            i = jax.nn.sigmoid(i)
            fg = jax.nn.sigmoid(fg)
            g = jnp.tanh(g)
            o = jax.nn.sigmoid(o)
            c_new = fg * c + i * g
            h_new = o * jnp.tanh(c_new)
            return h_new, c_new
        h, c = apply("lstm_cell", f, (inputs, h_prev, c_prev, self.weight_ih,
                                      self.weight_hh, self.bias_ih, self.bias_hh))
        return h, (h, c)


class GRUCell(_RNNCellBase):
    def __init__(self, input_size, hidden_size, **kwargs):
        super().__init__(input_size, hidden_size, 3, **kwargs)

    def forward(self, inputs, states=None):
        from ...tensor import creation
        if states is None:
            states = creation.zeros([inputs.shape[0], self.hidden_size], inputs.dtype)
        def f(x, h, wih, whh, bih, bhh):
            gi = x @ wih.T + bih
            gh = h @ whh.T + bhh
            ir, iz, ic = jnp.split(gi, 3, axis=-1)
            hr, hz, hc = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(ir + hr)
            z = jax.nn.sigmoid(iz + hz)
            c = jnp.tanh(ic + r * hc)
            return (1 - z) * c + z * h
        h = apply("gru_cell", f, (inputs, states, self.weight_ih,
                                  self.weight_hh, self.bias_ih, self.bias_hh))
        return h, h


def _scan_layer(cell_kind, x, h0, c0, wih, whh, bih, bhh, reverse=False,
                activation="tanh"):
    """One directional RNN layer as a lax.scan over time. x: [T, B, I]."""
    act = jax.nn.relu if activation == "relu" else jnp.tanh

    def step(carry, x_t):
        if cell_kind == "lstm":
            h, c = carry
            gates = x_t @ wih.T + bih + h @ whh.T + bhh
            i, fg, g, o = jnp.split(gates, 4, axis=-1)
            c_new = jax.nn.sigmoid(fg) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
            h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
            return (h_new, c_new), h_new
        if cell_kind == "gru":
            h = carry
            gi = x_t @ wih.T + bih
            gh = h @ whh.T + bhh
            ir, iz, ic = jnp.split(gi, 3, axis=-1)
            hr, hz, hc = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(ir + hr)
            z = jax.nn.sigmoid(iz + hz)
            c = jnp.tanh(ic + r * hc)
            h_new = (1 - z) * c + z * h
            return h_new, h_new
        h = carry
        h_new = act(x_t @ wih.T + bih + h @ whh.T + bhh)
        return h_new, h_new

    init = (h0, c0) if cell_kind == "lstm" else h0
    carry, outs = jax.lax.scan(step, init, x, reverse=reverse)
    return carry, outs


class _RNNBase(Layer):
    """Multi-layer (optionally bidirectional) RNN
    (parity: paddle.nn.{SimpleRNN,LSTM,GRU})."""

    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.activation = activation
        self.bidirectional = direction in ("bidirect", "bidirectional")
        self.num_directions = 2 if self.bidirectional else 1
        gates = {"lstm": 4, "gru": 3, "rnn": 1}[mode]
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self._weights = []
        for layer in range(num_layers):
            for direction in range(self.num_directions):
                in_sz = input_size if layer == 0 else hidden_size * self.num_directions
                suffix = f"l{layer}" + ("_reverse" if direction else "")
                wih = self.create_parameter(
                    [gates * hidden_size, in_sz], default_initializer=u,
                    attr=weight_ih_attr)
                whh = self.create_parameter(
                    [gates * hidden_size, hidden_size], default_initializer=u,
                    attr=weight_hh_attr)
                bih = self.create_parameter(
                    [gates * hidden_size], is_bias=True, default_initializer=u,
                    attr=bias_ih_attr)
                bhh = self.create_parameter(
                    [gates * hidden_size], is_bias=True, default_initializer=u,
                    attr=bias_hh_attr)
                self.add_parameter(f"weight_ih_{suffix}", wih)
                self.add_parameter(f"weight_hh_{suffix}", whh)
                self.add_parameter(f"bias_ih_{suffix}", bih)
                self.add_parameter(f"bias_hh_{suffix}", bhh)
                self._weights.append((wih, whh, bih, bhh))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...framework import random as rng
        mode = self.mode
        nl, nd, hs = self.num_layers, self.num_directions, self.hidden_size
        time_major = self.time_major
        # inter-layer dropout (paddle applies it to the outputs of every
        # layer except the last, training mode only)
        drop_p = self.dropout if (self.training and self.dropout > 0) else 0.0
        drop_keys = [rng.next_key() for _ in range(nl - 1)] if drop_p else []
        operands = [inputs]
        has_init = initial_states is not None
        if has_init:
            if mode == "lstm":
                operands += [initial_states[0], initial_states[1]]
            else:
                operands.append(initial_states)
        flat_weights = [w for ws in self._weights for w in ws]
        operands += flat_weights

        def f(x, *rest):
            i = 0
            if has_init:
                if mode == "lstm":
                    h0_all, c0_all = rest[0], rest[1]
                    i = 2
                else:
                    h0_all = rest[0]
                    i = 1
            else:
                h0_all = None
            weights = rest[i:]
            xt = x if time_major else jnp.swapaxes(x, 0, 1)  # [T, B, I]
            B = xt.shape[1]
            final_h, final_c = [], []
            out = xt
            for layer in range(nl):
                dir_outs = []
                for d in range(nd):
                    wi = (layer * nd + d) * 4
                    wih, whh, bih, bhh = weights[wi: wi + 4]
                    idx = layer * nd + d
                    h0 = (
                        h0_all[idx] if h0_all is not None
                        else jnp.zeros((B, hs), xt.dtype)
                    )
                    c0 = (
                        c0_all[idx] if (mode == "lstm" and has_init)
                        else jnp.zeros((B, hs), xt.dtype)
                    )
                    carry, outs = _scan_layer(
                        mode, out, h0, c0, wih, whh, bih, bhh,
                        reverse=(d == 1), activation=self.activation,
                    )
                    if mode == "lstm":
                        final_h.append(carry[0])
                        final_c.append(carry[1])
                    else:
                        final_h.append(carry)
                    dir_outs.append(outs)
                out = (
                    jnp.concatenate(dir_outs, axis=-1) if nd == 2 else dir_outs[0]
                )
                if drop_p and layer < nl - 1:
                    keep = jax.random.bernoulli(
                        drop_keys[layer], 1.0 - drop_p, out.shape
                    )
                    out = jnp.where(keep, out / (1.0 - drop_p),
                                    jnp.zeros((), out.dtype))
            result = out if time_major else jnp.swapaxes(out, 0, 1)
            h_stack = jnp.stack(final_h)
            if mode == "lstm":
                return result, h_stack, jnp.stack(final_c)
            return result, h_stack

        outs = apply(f"{mode}_forward", f, tuple(operands))
        if mode == "lstm":
            out, h, c = outs
            return out, (h, c)
        out, h = outs
        return out, h


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kwargs):
        super().__init__("rnn", input_size, hidden_size, num_layers, direction,
                         time_major, dropout, activation, **kwargs)


class LSTM(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kwargs):
        super().__init__("lstm", input_size, hidden_size, num_layers, direction,
                         time_major, dropout, **kwargs)


class GRU(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kwargs):
        super().__init__("gru", input_size, hidden_size, num_layers, direction,
                         time_major, dropout, **kwargs)


class RNN(Layer):
    """Wraps a cell into a scan over time (parity: paddle.nn.RNN)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...tensor import manipulation as M
        xs = inputs if self.time_major else M.transpose(
            inputs, [1, 0] + list(range(2, inputs.ndim)))
        T = xs.shape[0]
        order = range(T - 1, -1, -1) if self.is_reverse else range(T)
        states = initial_states
        outs = []
        for ti in order:
            out, states = self.cell(xs[ti], states)
            outs.append(out)
        if self.is_reverse:
            outs = outs[::-1]
        stacked = M.stack(outs, axis=0)
        if not self.time_major:
            stacked = M.transpose(stacked, [1, 0] + list(range(2, stacked.ndim)))
        return stacked, states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...tensor import manipulation as M
        fw_states, bw_states = (None, None) if initial_states is None else initial_states
        out_fw, st_fw = self.rnn_fw(inputs, fw_states)
        out_bw, st_bw = self.rnn_bw(inputs, bw_states)
        return M.concat([out_fw, out_bw], axis=-1), (st_fw, st_bw)


# public alias (parity: paddle.nn.RNNCellBase)
RNNCellBase = _RNNCellBase


class BeamSearchDecoder(Layer):
    """Beam-search decoding over an RNN cell (parity:
    paddle.nn.BeamSearchDecoder, ref `nn/decode.py`).

    The decoder contract is initialize() -> (inputs, states, finished) and
    step(time, inputs, states) -> (outputs, states, next_inputs, finished),
    driven by :func:`dynamic_decode`. Beams ride the batch axis ([B*K, ...])
    so every step is one batched matmul on the MXU.
    """

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        super().__init__()
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    # -- tree helpers over (possibly nested) cell states --
    @staticmethod
    def _tree_map(fn, obj):
        if isinstance(obj, (tuple, list)):
            return tuple(BeamSearchDecoder._tree_map(fn, o) for o in obj)
        return fn(obj)

    def _tile_beam(self, t):
        # [B, ...] -> [B*K, ...]
        def f(a):
            k = self.beam_size
            return jnp.repeat(a, k, axis=0)

        return apply("beam_tile", f, (t,))

    def _gather_beam(self, t, parent):
        # t: [B*K, ...], parent: [B, K] beam ids -> regathered [B*K, ...]
        def f(a, p):
            bk = a.shape[0]
            b = p.shape[0]
            k = self.beam_size
            flat = (jnp.arange(b)[:, None] * k + p).reshape(-1)
            del bk
            return a[flat]

        return apply("beam_gather", f, (t, parent))

    def initialize(self, initial_states, batch_size=None, dtype="float32"):
        from ...tensor import creation

        states = self._tree_map(self._tile_beam, initial_states)
        flat = states
        while isinstance(flat, (tuple, list)):
            flat = flat[0]
        bk = flat.shape[0]
        b = bk // self.beam_size
        ids = creation.full([bk], self.start_token, "int64")
        # log-prob state: beam 0 live, the rest muted so step 1 expands
        # only one start beam per batch row
        lp = np.full((b, self.beam_size), -1e9, np.float32)
        lp[:, 0] = 0.0
        self._log_probs = Tensor(jnp.asarray(lp))
        self._seqs = None
        finished = creation.zeros([b, self.beam_size], "bool")
        return ids, states, finished

    def step(self, time, inputs, states, **kwargs):
        from ...tensor import creation  # noqa: F401

        emb = self.embedding_fn(inputs) if self.embedding_fn else inputs
        cell_out, next_states = self.cell(emb, states)
        logits = self.output_fn(cell_out) if self.output_fn else cell_out

        k = self.beam_size
        end = self.end_token

        def f(lg, lp, fin):
            bk, v = lg.shape
            b = bk // k
            logp = jax.nn.log_softmax(lg.astype(jnp.float32), axis=-1)
            logp = logp.reshape(b, k, v)
            # a finished beam only extends with end_token at no cost
            end_oh = jnp.where(jnp.arange(v) == end, 0.0, -1e30)
            logp = jnp.where(fin[:, :, None], end_oh[None, None, :], logp)
            scores = lp[:, :, None] + logp
            top, idx = jax.lax.top_k(scores.reshape(b, k * v), k)
            parent = (idx // v).astype(jnp.int32)
            token = (idx % v).astype(jnp.int64)
            fin_next = jnp.take_along_axis(fin, parent, axis=1) \
                | (token == end)
            return top, parent, token, fin_next

        top, parent, token, fin_next = apply(
            "beam_step", f, (logits, self._log_probs, kwargs["finished"]))
        self._log_probs = top
        next_states = self._tree_map(
            lambda s: self._gather_beam(s, parent), next_states)
        # sequence bookkeeping: regather history by parent, append token
        def app(seq_or_none):
            def g(tok, par, *rest):
                tk = tok.reshape(-1, k)
                if rest:
                    prev = jnp.take_along_axis(rest[0], par[:, :, None],
                                               axis=1)
                    return jnp.concatenate([prev, tk[:, :, None]], axis=2)
                return tk[:, :, None]

            ops = (token, parent) + (() if seq_or_none is None
                                     else (seq_or_none,))
            return apply("beam_append", g, ops)

        self._seqs = app(self._seqs)
        next_inputs = token.reshape([-1])
        return token, next_states, next_inputs, fin_next

    def finalize(self):
        """Returns predicted ids [B, T, K] (beam-major last, paddle
        layout) and their scores [B, K]."""
        from ...tensor import manipulation as M

        return M.transpose(self._seqs, [0, 2, 1]), self._log_probs


def _tree_map2(fn, a, b):
    """Pairwise tree-map over the (tuple/list/namedtuple/dict/Tensor)
    state pytrees dynamic_decode sees.  Structure-changing states (a
    decoder growing its state list or re-keying a dict between steps)
    fall back to the new value — a partial freeze, never a silent
    truncation."""
    if isinstance(a, tuple) and hasattr(a, "_fields"):  # namedtuple
        if type(b) is not type(a):
            return b
        return type(a)(*(_tree_map2(fn, x, y) for x, y in zip(a, b)))
    if isinstance(a, (list, tuple)):
        if not isinstance(b, (list, tuple)) or len(a) != len(b):
            return b
        return type(a)(_tree_map2(fn, x, y) for x, y in zip(a, b))
    if isinstance(a, dict):
        if not isinstance(b, dict) or set(a) != set(b):
            return b
        return {k: _tree_map2(fn, a[k], b[k]) for k in a}
    if a is None or b is None:
        return b
    return fn(a, b)


def dynamic_decode(decoder, inits=None, max_step_num=None,
                   output_time_major=False, impute_finished=False,
                   is_test=False, return_length=False, **kwargs):
    """Drive a decoder until every beam finishes or ``max_step_num``
    (parity: paddle.nn.dynamic_decode). Decoding is autoregressive and
    length-dynamic, so the loop is host-driven; each step body is one
    compiled batched program.

    ``impute_finished=True`` freezes the states of already-finished beams
    (the step still runs, its state updates are masked out), matching the
    reference semantics. ``is_test`` is advisory here: the decode loop
    itself records no training state, so test mode changes nothing.
    """
    from ...tensor import logic as tlogic

    max_steps = int(max_step_num or 100)
    inputs, states, finished = decoder.initialize(inits)
    lengths = None
    for t in range(max_steps):
        prev_states, prev_finished = states, finished
        _, states, inputs, finished = decoder.step(t, inputs, states,
                                                   finished=finished)
        if impute_finished:
            def freeze(old, new):
                def f(o, n, fin):
                    m = jnp.asarray(fin).reshape([-1]).astype(bool)
                    if (n.ndim == 0 or o.shape != n.shape
                            or m.shape[0] != n.shape[0]):
                        return n  # scalar/shape-changing: nothing to freeze
                    m = m.reshape((m.shape[0],) + (1,) * (n.ndim - 1))
                    return jnp.where(m, o, n)

                return apply("impute_finished", f, (old, new, prev_finished))

            states = _tree_map2(freeze, prev_states, states)
        if bool(tlogic.all(finished.reshape([-1])).numpy()):
            break
    ids, scores = decoder.finalize()
    if output_time_major:
        from ...tensor import manipulation as M

        ids = M.transpose(ids, [1, 0, 2])
    if return_length:
        def f(s):
            # time axis: 1 in [B, T, K] batch-major, 0 in [T, B, K]
            return jnp.sum((s != decoder.end_token).astype(jnp.int32),
                           axis=1 if not output_time_major else 0)

        lengths = apply("beam_lengths", f, (ids,))
        return ids, scores, lengths
    return ids, scores
