"""paddle.nn.quant parity (reference `python/paddle/nn/quant/stub.py`)."""
from __future__ import annotations

from ..layer.layers import Layer

__all__ = ["Stub"]


class Stub(Layer):
    """Placeholder layer swapped for an observer/quanter before PTQ/QAT
    (parity: paddle.nn.quant.Stub). Until the quantizer replaces it, the
    forward is the identity; QAT/PTQ (`paddle.quantization`) substitutes
    the configured quanter here the way it swaps Linear/Conv layers."""

    def __init__(self, observer=None):
        super().__init__()
        self._observer = observer
        self._layer = None  # set by the quantizer

    def forward(self, x):
        if self._layer is not None:
            return self._layer(x)
        return x
