from .tape import (  # noqa: F401
    no_grad, enable_grad, is_grad_enabled, set_grad_enabled, grad, run_backward,
)
from .py_layer import PyLayer, PyLayerContext, once_differentiable  # noqa: F401
from . import functional  # noqa: F401
from .functional import Jacobian, hessian, jacobian, jvp, vhp, vjp  # noqa: F401
