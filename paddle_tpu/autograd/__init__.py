from .tape import (  # noqa: F401
    no_grad, enable_grad, is_grad_enabled, set_grad_enabled, grad, run_backward,
)
from .py_layer import PyLayer, PyLayerContext, once_differentiable  # noqa: F401
from . import functional  # noqa: F401
from .functional import Jacobian, hessian, jacobian, jvp, vhp, vjp  # noqa: F401


def backward(tensors, grad_tensors=None, retain_graph=False):
    """Parity: paddle.autograd.backward — run backward from several roots
    in ONE sweep (roots sharing intermediates must not consume the graph
    twice), accumulating into .grad."""
    from .tape import run_backward

    run_backward(tensors, grad_tensors, retain_graph)


class saved_tensors_hooks:  # noqa: N801 — reference spelling
    """Parity: paddle.autograd.saved_tensors_hooks(pack, unpack) — rewrite
    tensors as the tape saves them for backward (e.g. offload/compress).

    The tape stores forward operands on each GradNode; inside this scope
    every saved operand is passed through ``pack_hook`` at save time and
    ``unpack_hook`` when the backward pass reads it.
    """

    def __init__(self, pack_hook, unpack_hook):
        self.pack_hook = pack_hook
        self.unpack_hook = unpack_hook

    def __enter__(self):
        from . import tape

        tape._saved_tensor_hooks.append(
            (self.pack_hook, self.unpack_hook))
        return self

    def __exit__(self, *exc):
        from . import tape

        tape._saved_tensor_hooks.pop()
        return False
