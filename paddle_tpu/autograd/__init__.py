from .tape import (
    no_grad, enable_grad, is_grad_enabled, set_grad_enabled, grad, run_backward,
)
