"""PyLayer: user-defined autograd functions.

Reference parity: `paddle.autograd.PyLayer`
(`python/paddle/autograd/py_layer.py:269`) and the C++ side
`fluid/pybind/eager_py_layer.cc` — `forward`/`backward` staticmethods with a
ctx carrying `save_for_backward`.

TPU-first design: the user's backward plugs into the tape as the recorded
node's pullback directly (no C++ PyLayerNode): forward runs under no_grad,
then a GradNode is created whose vjp_fn invokes `backward(ctx, *grads)`.
Because the tape executes pullbacks with plain arrays/tracers, a PyLayer
works identically in eager mode and inside a compiled TrainStep trace.
"""
from __future__ import annotations

import jax.numpy as jnp

from .tape import GradNode, is_grad_enabled, no_grad
from ..framework.core import Tensor


class PyLayerContext:
    """Parity: `PyLayerContext` (save_for_backward / saved_tensor /
    not_inplace-style attrs are free-form)."""

    def __init__(self):
        self._saved = ()
        self.needs_input_grad = ()

    def save_for_backward(self, *tensors):
        self._saved = tuple(tensors)

    def saved_tensor(self):
        return self._saved


class PyLayerMeta(type):
    def __init__(cls, name, bases, attrs):
        super().__init__(name, bases, attrs)


class PyLayer(metaclass=PyLayerMeta):
    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        tensor_in = [a for a in args if isinstance(a, Tensor)]
        requires = [isinstance(a, Tensor) and not a.stop_gradient
                    for a in args]
        ctx.needs_input_grad = tuple(requires)
        with no_grad():
            out = cls.forward(ctx, *args, **kwargs)
        multi = isinstance(out, (tuple, list))
        outs = tuple(out) if multi else (out,)

        record = is_grad_enabled() and any(requires)
        if not record:
            return out

        n_args = len(args)

        def vjp_fn(cts):
            cts = cts if isinstance(cts, tuple) else (cts,)
            ct_tensors = [Tensor(c) for c in cts]
            grads = cls.backward(ctx, *ct_tensors)
            grads = grads if isinstance(grads, (tuple, list)) else (grads,)
            grad_arrays = []
            gi = iter(grads)
            for a, req in zip(args, requires):
                if not isinstance(a, Tensor):
                    grad_arrays.append(None)
                    continue
                g = next(gi, None)
                grad_arrays.append(
                    g._data if isinstance(g, Tensor)
                    else (jnp.asarray(g) if g is not None else None))
            # tape contract: one cotangent per recorded operand
            return tuple(
                g if g is not None else jnp.zeros(a._data.shape, a._data.dtype)
                for a, g in zip(args, grad_arrays) if isinstance(a, Tensor)
            )

        in_tensors = [a for a in args if isinstance(a, Tensor)]
        in_requires = [not t.stop_gradient for t in in_tensors]
        out_avals = [(o._data.shape, o._data.dtype) for o in outs]
        node = GradNode(cls.__name__, vjp_fn, in_tensors, in_requires,
                        out_avals, multi=len(outs) > 1)

        import weakref

        results = []
        for i, o in enumerate(outs):
            t = Tensor(o._data, stop_gradient=False)
            t._grad_node = node
            t._out_index = i
            node.out_tensor_refs[i] = weakref.ref(t)
            results.append(t)
        return tuple(results) if multi else results[0]


def once_differentiable(fn):  # decorator parity
    return fn
