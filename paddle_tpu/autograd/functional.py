"""Functional autograd transforms: jvp/vjp/jacobian/hessian/vhp.

Reference parity: `python/paddle/autograd` functional API (the incubate
autograd jvp/vjp/Jacobian/Hessian surface, `python/paddle/incubate/autograd`).

TPU-first design: these are direct jax transforms over a functionalized view
of the user function — no double-backward machinery needed (the reference
builds these from repeated tape passes; jax gives forward- and
reverse-mode natively, so `hessian` is one `jax.hessian`).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from .tape import no_grad


def _unwrap(x):
    if isinstance(x, Tensor):
        return x._data
    if isinstance(x, (list, tuple)):
        return type(x)(_unwrap(v) for v in x)
    return x


def _wrap(x):
    if isinstance(x, (list, tuple)):
        return type(x)(_wrap(v) for v in x)
    return Tensor(x) if hasattr(x, "dtype") else x


def _functionalize(func):
    """Tensor-in/Tensor-out python fn -> array fn (runs the eager code under
    no_grad on traced arrays; the outer jax transform provides the grads)."""

    def fn(*arrays):
        with no_grad():
            out = func(*[Tensor(a) for a in arrays])
        return _unwrap(out)

    return fn


def vjp(func, xs, v=None):
    """Parity: `paddle.incubate.autograd.vjp(func, xs, v)` ->
    (func_out, vjp_result)."""
    single = not isinstance(xs, (list, tuple))
    xs_t = [xs] if single else list(xs)
    arrays = [_unwrap(x) for x in xs_t]
    out, pullback = jax.vjp(_functionalize(func), *arrays)
    if v is None:
        ct = jnp.ones_like(out) if not isinstance(out, tuple) else tuple(
            jnp.ones_like(o) for o in out)
    else:
        ct = _unwrap(v)
    grads = pullback(ct)
    grads = _wrap(list(grads))
    return _wrap(out), grads[0] if single else grads


def jvp(func, xs, v=None):
    """Parity: `paddle.incubate.autograd.jvp(func, xs, v)`."""
    single = not isinstance(xs, (list, tuple))
    xs_t = [xs] if single else list(xs)
    arrays = [_unwrap(x) for x in xs_t]
    if v is None:
        tangents = [jnp.ones_like(a) for a in arrays]
    else:
        v_t = [v] if single else list(v)
        tangents = [_unwrap(t) for t in v_t]
    out, jv = jax.jvp(_functionalize(func), tuple(arrays), tuple(tangents))
    return _wrap(out), _wrap(jv)


class Jacobian:
    """Parity: `paddle.autograd.jacobian` / incubate `Jacobian` — lazy
    matrix view of d(func)/d(xs)."""

    def __init__(self, func, xs, is_batched=False):
        single = not isinstance(xs, (list, tuple))
        xs_t = [xs] if single else list(xs)
        arrays = [_unwrap(x) for x in xs_t]
        jac = jax.jacrev(_functionalize(func),
                         argnums=tuple(range(len(arrays))))(*arrays)
        self._jac = jac[0] if single else jac
        self._single = single

    def __getitem__(self, idx):
        return _wrap(self._jac[idx] if not self._single else self._jac[idx])

    @property
    def shape(self):
        j = self._jac if self._single else self._jac[0]
        return list(j.shape)

    def numpy(self):
        import numpy as np

        return np.asarray(self._jac if self._single else self._jac[0])


def jacobian(func, xs, create_graph=False, allow_unused=False):
    single = not isinstance(xs, (list, tuple))
    xs_t = [xs] if single else list(xs)
    arrays = [_unwrap(x) for x in xs_t]
    jac = jax.jacrev(_functionalize(func),
                     argnums=tuple(range(len(arrays))))(*arrays)
    out = _wrap(list(jac))
    return out[0] if single else out


def hessian(func, xs, create_graph=False, allow_unused=False):
    """Parity: `paddle.incubate.autograd.hessian` (scalar-output func)."""
    single = not isinstance(xs, (list, tuple))
    xs_t = [xs] if single else list(xs)
    arrays = [_unwrap(x) for x in xs_t]
    h = jax.hessian(_functionalize(func),
                    argnums=tuple(range(len(arrays))))(*arrays)
    if single:
        return _wrap(h[0][0])
    return _wrap([[c for c in row] for row in h])


def vhp(func, xs, v=None):
    """vector-Hessian product (parity: incubate autograd vhp)."""
    single = not isinstance(xs, (list, tuple))
    xs_t = [xs] if single else list(xs)
    arrays = [_unwrap(x) for x in xs_t]
    fn = _functionalize(func)

    grad_fn = jax.grad(fn, argnums=tuple(range(len(arrays))))
    if v is None:
        tangents = tuple(jnp.ones_like(a) for a in arrays)
    else:
        v_t = [v] if single else list(v)
        tangents = tuple(_unwrap(t) for t in v_t)
    out = fn(*arrays)
    _, hvp_val = jax.jvp(lambda *a: grad_fn(*a), tuple(arrays), tangents)
    hvp_w = _wrap(list(hvp_val))
    return _wrap(out), hvp_w[0] if single else hvp_w
