"""Eager autograd: a define-by-run tape over `jax.vjp`.

Reference parity: the eager autograd engine of the reference —
`GradNodeBase` (`paddle/fluid/eager/grad_node_info.h:168`),
`egr::Backward`/`RunBackward` (`paddle/fluid/eager/backward.cc:421,104`,
reverse-topological ready-queue), `GradTensorHolder` accumulation, and
`AutogradMeta` wiring.

TPU-first design: the reference generates a C++ ``GradNode`` class per op from
YAML, each re-implementing the backward kernel call. Here a single generic
:class:`GradNode` holds the `jax.vjp` pullback of the forward computation —
XLA already knows every op's VJP, residuals are saved on-device, and the
pullback is itself traceable (so a whole jit'd subgraph can be one node, the
way the reference runs a `RunProgramGradNode` for @to_static blocks).
Topological order falls out of monotonically increasing node ids (a Wengert
list), replacing the reference's in-degree bookkeeping.
"""
from __future__ import annotations

import contextlib
import itertools
import threading
import weakref

import jax
import jax.numpy as jnp
import numpy as np

_local = threading.local()
_node_counter = itertools.count()


def _tracing_flag():
    if not hasattr(_local, "grad_enabled"):
        _local.grad_enabled = True
    return _local.grad_enabled


def is_grad_enabled() -> bool:
    return _tracing_flag()


def set_grad_enabled(mode: bool):
    _tracing_flag()
    _local.grad_enabled = bool(mode)


class no_grad(contextlib.ContextDecorator):
    """Context manager / decorator that disables autograd recording.

    Mirrors ``paddle.no_grad`` (reference `python/paddle/fluid/dygraph/base.py`).
    """

    def __enter__(self):
        self._prev = is_grad_enabled()
        set_grad_enabled(False)
        return self

    def __exit__(self, *exc):
        set_grad_enabled(self._prev)
        return False


class enable_grad(contextlib.ContextDecorator):
    def __enter__(self):
        self._prev = is_grad_enabled()
        set_grad_enabled(True)
        return self

    def __exit__(self, *exc):
        set_grad_enabled(self._prev)
        return False


class InputRef:
    """Snapshot of an input tensor's autograd state at record time.

    Recording the producing node *by value* (instead of re-reading
    ``tensor._grad_node`` during backward) makes in-place mutation safe: if
    the tensor is later rebound by ``__setitem__``/``increment``, nodes
    recorded before the mutation still route cotangents into the graph that
    actually produced the value they consumed — the tape equivalent of the
    reference's TensorWrapper capture (`paddle/fluid/eager/tensor_wrapper.h`).
    """

    __slots__ = ("tensor", "node", "out_index", "requires")

    def __init__(self, tensor, requires):
        self.tensor = tensor
        self.node = tensor._grad_node if requires else None
        self.out_index = tensor._out_index if requires else 0
        self.requires = requires


class GradNode:
    """One recorded op: holds the vjp pullback and links to input snapshots.

    ``vjp_fn(out_cotangents) -> tuple(in_cotangents)`` — exactly `jax.vjp`'s
    pullback contract. Strong refs to input tensors keep the upstream graph
    alive while any consumer output lives (the reference's shared_ptr graph
    ownership).
    """

    __slots__ = (
        "id", "op_name", "vjp_fn", "inputs", "out_avals", "n_outputs",
        "out_tensor_refs", "multi",
    )

    def __init__(self, op_name, vjp_fn, input_tensors, requires, out_avals,
                 multi=None):
        self.id = next(_node_counter)
        self.op_name = op_name
        self.vjp_fn = vjp_fn
        self.inputs = [InputRef(t, r) for t, r in zip(input_tensors, requires)]
        self.out_avals = out_avals  # list[(shape, dtype)] per output
        self.n_outputs = len(out_avals)
        # whether the recorded fn returned a tuple (a 1-tuple output still
        # needs a 1-tuple cotangent for jax.vjp's pytree match)
        self.multi = len(out_avals) > 1 if multi is None else multi
        # weakrefs to output tensors; used to fire user hooks once per
        # backward on the fully-accumulated cotangent
        self.out_tensor_refs = [None] * len(out_avals)

    def __repr__(self):
        return f"<GradNode {self.op_name}#{self.id} nout={self.n_outputs}>"


def _accumulate(existing, new):
    if existing is None:
        return new
    return existing + new


def _zeros_for(aval):
    shape, dtype = aval
    if not jnp.issubdtype(dtype, jnp.inexact):
        # jax represents cotangents of integer/bool outputs as float0
        return np.zeros(shape, jax.dtypes.float0)
    return jnp.zeros(shape, dtype)


def _apply_hooks(tensor, ct):
    from ..framework.core import Tensor

    for hook in tensor._grad_hooks:
        out = hook(ct)
        if out is not None:
            ct = out._data if isinstance(out, Tensor) else out
    return ct


def _topo_nodes(roots):
    """All GradNodes reachable from the root tensors, sorted by id desc.

    Creation order is a valid topological order (a Wengert list), so id-desc
    processing guarantees every consumer runs before its producer."""
    seen = {}
    stack = [t._grad_node for t in roots if t._grad_node is not None]
    while stack:
        node = stack.pop()
        if node.id in seen:
            continue
        seen[node.id] = node
        for ref in node.inputs:
            if ref.requires and ref.node is not None and ref.node.id not in seen:
                stack.append(ref.node)
    return sorted(seen.values(), key=lambda n: n.id, reverse=True)


def _sweep(root_tensors, root_cts, retain_graph, on_leaf, on_retained=None):
    """Shared reverse-topological engine for `backward()` and `paddle.grad`.

    Accumulates output cotangents per node, fires output-tensor hooks once on
    the fully-accumulated cotangent, calls each node's vjp pullback once —
    the eager equivalent of `egr::RunBackward` (reference
    `eager/backward.cc:104` ready-queue + GradTensorHolder accumulation).

    ``on_leaf(tensor, ct)`` receives each contribution destined for a leaf
    (no producing node at record time). ``on_retained(tensor, ct)`` fires for
    non-leaf tensors with ``retain_grads()`` set.
    """
    pending: dict[int, list] = {}

    def route_ref(ref, ct):
        if ref.node is None:
            on_leaf(ref.tensor, ct)
            return
        if ref.tensor._retain_grad and on_retained is not None:
            on_retained(ref.tensor, ct)
        bucket = pending.setdefault(ref.node.id, [None] * ref.node.n_outputs)
        bucket[ref.out_index] = _accumulate(bucket[ref.out_index], ct)

    for t, ct in zip(root_tensors, root_cts):
        route_ref(InputRef(t, True), ct)

    nodes = _topo_nodes(root_tensors)
    with no_grad():
        for node in nodes:
            bucket = pending.pop(node.id, None)
            if bucket is None:
                continue
            out_cts = []
            for i, (ct, aval) in enumerate(zip(bucket, node.out_avals)):
                ct = ct if ct is not None else _zeros_for(aval)
                ref = node.out_tensor_refs[i]
                out_t = ref() if ref is not None else None
                if out_t is not None and out_t._grad_hooks:
                    ct = _apply_hooks(out_t, ct)
                out_cts.append(ct)
            if node.multi:
                in_cts = node.vjp_fn(tuple(out_cts))
            else:
                in_cts = node.vjp_fn(out_cts[0])
            for ref, ct in zip(node.inputs, in_cts):
                if not ref.requires:
                    continue
                if hasattr(ct, "dtype") and ct.dtype == jax.dtypes.float0:
                    continue
                route_ref(ref, ct)
            if not retain_graph:
                node.vjp_fn = _used_vjp_error


def run_backward(tensors, grad_tensors=None, retain_graph=False):
    """`tensor.backward()` engine: deposits into leaf ``.grad`` attributes.

    Like the reference Tensor.backward, a missing grad_tensor seeds ones of
    the output's shape (any shape, not just scalars).
    """
    from ..framework.core import Tensor

    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]

    root_cts = []
    for t, g in zip(tensors, grad_tensors):
        if g is None:
            root_cts.append(jnp.ones(t._data.shape, t._data.dtype))
        else:
            root_cts.append(g._data if isinstance(g, Tensor) else jnp.asarray(g))

    # accumulate each target's full gradient first so user hooks fire once
    # per backward with the final value (reference: the grad-accumulation
    # node runs hooks after fan-in completes)
    acc: dict[int, list] = {}

    def on_leaf(tensor, ct):
        if tensor.stop_gradient:
            return
        rec = acc.setdefault(id(tensor), [tensor, None])
        rec[1] = _accumulate(rec[1], ct)

    def on_retained(tensor, ct):
        rec = acc.setdefault(id(tensor), [tensor, None])
        rec[1] = _accumulate(rec[1], ct)

    _sweep(tensors, root_cts, retain_graph, on_leaf, on_retained)

    for tensor, ct in acc.values():
        ct = _apply_hooks(tensor, ct)
        if tensor.grad is None:
            tensor.grad = Tensor(ct, stop_gradient=True)
        else:
            tensor.grad = Tensor(tensor.grad._data + ct, stop_gradient=True)


def _used_vjp_error(*_):
    raise RuntimeError(
        "Trying to run backward through a graph a second time. "
        "Pass retain_graph=True to backward() to allow this."
    )


def grad(
    outputs,
    inputs,
    grad_outputs=None,
    retain_graph=None,
    create_graph=False,
    allow_unused=False,
):
    """Functional gradient query: `paddle.grad` parity
    (reference `fluid/eager/general_grad.h`).

    Computes d(outputs)/d(inputs) without touching any ``.grad`` attribute.
    ``create_graph`` is not supported on the eager tape — use
    :mod:`paddle_tpu.autograd.functional` (jax-native transforms) for
    higher-order derivatives.
    """
    from ..framework.core import Tensor

    if create_graph:
        raise NotImplementedError(
            "create_graph=True is not supported on the eager tape; use "
            "paddle_tpu.autograd.functional (vjp/jvp/hessian) for "
            "higher-order gradients."
        )
    single_in = isinstance(inputs, Tensor)
    if single_in:
        inputs = [inputs]
    if isinstance(outputs, Tensor):
        outputs = [outputs]
    retain_graph = bool(retain_graph)

    wanted = {id(t) for t in inputs}
    results = {id(t): None for t in inputs}

    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    elif isinstance(grad_outputs, Tensor):
        grad_outputs = [grad_outputs]

    root_cts = []
    for t, g in zip(outputs, grad_outputs):
        if g is None:
            root_cts.append(jnp.ones(t._data.shape, t._data.dtype))
        else:
            root_cts.append(g._data if isinstance(g, Tensor) else jnp.asarray(g))

    def collect(tensor, ct):
        if id(tensor) in wanted:
            results[id(tensor)] = (
                ct if results[id(tensor)] is None else results[id(tensor)] + ct
            )

    # deliver cotangents of wanted non-leaf tensors via the retain channel
    saved_retain = [(t, t._retain_grad) for t in inputs]
    for t in inputs:
        t._retain_grad = True
    try:
        _sweep(outputs, root_cts, retain_graph, collect, collect)
    finally:
        for t, r in saved_retain:
            t._retain_grad = r

    out = []
    for t in inputs:
        r = results[id(t)]
        if r is None:
            if not allow_unused:
                raise RuntimeError(
                    "One of the differentiated tensors appears to not have "
                    "been used in the graph. Set allow_unused=True if this "
                    "is the desired behavior."
                )
            out.append(None)
        else:
            out.append(Tensor(r, stop_gradient=True))
    return out[0] if single_in else out


# -- saved-tensor hooks (paddle.autograd.saved_tensors_hooks) --
_saved_tensor_hooks: list = []


def saved_tensor_hooks():
    """The active (pack, unpack) pair, or None (read by ops.dispatch at
    record time)."""
    return _saved_tensor_hooks[-1] if _saved_tensor_hooks else None
