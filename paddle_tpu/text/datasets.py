"""paddle.text.datasets — classic NLP datasets.

Reference parity: `python/paddle/text/datasets/` (imdb.py, imikolov.py,
uci_housing.py, movielens.py, conll05.py, wmt14.py, wmt16.py). The parsing
logic (tokenization, vocab build with frequency cutoff, NGRAM/SEQ modes,
train/test splits, normalization) is reproduced faithfully; the download
step is NOT — this environment has no egress, so every dataset takes a
local ``data_file`` path (the same archive the reference downloads) and
raises a structured `UnavailableError` naming the expected archive when it
is missing, instead of silently failing mid-parse.
"""
from __future__ import annotations

import collections
import re
import string
import tarfile
import zipfile

import numpy as np

from ..framework.errors import UnavailableError
from ..io import Dataset

__all__ = ["Imdb", "Imikolov", "UCIHousing", "Movielens", "Conll05st",
           "WMT14", "WMT16"]


def _require(data_file, archive_desc):
    if not data_file:
        raise UnavailableError(
            f"this environment has no network egress; pass data_file= "
            f"pointing at a local copy of {archive_desc} (the reference "
            f"downloads the same archive)")
    return data_file


class UCIHousing(Dataset):
    """Boston housing regression (parity: `uci_housing.py:42`): 14
    whitespace-separated floats per row; features min-max/avg normalized;
    80/20 train/test split."""

    def __init__(self, data_file=None, mode="train", download=False):
        if mode not in ("train", "test"):
            raise ValueError(f"mode must be 'train' or 'test', got {mode!r}")
        self.mode = mode
        self.data_file = _require(data_file, "the UCI housing data file "
                                             "('housing.data')")
        self._load_data()

    def _load_data(self, feature_num=14, ratio=0.8):
        data = np.fromfile(self.data_file, sep=" ")
        data = data.reshape(data.shape[0] // feature_num, feature_num)
        maximums = data.max(axis=0)
        minimums = data.min(axis=0)
        avgs = data.sum(axis=0) / data.shape[0]
        for i in range(feature_num - 1):
            data[:, i] = (data[:, i] - avgs[i]) / (maximums[i] - minimums[i])
        offset = int(data.shape[0] * ratio)
        self.data = data[:offset] if self.mode == "train" else data[offset:]

    def __getitem__(self, idx):
        row = self.data[idx]
        return (np.asarray(row[:-1], np.float32),
                np.asarray(row[-1:], np.float32))

    def __len__(self):
        return len(self.data)


class Imdb(Dataset):
    """IMDB sentiment (parity: `imdb.py:31`): aclImdb tarball; ad-hoc
    tokenization (punctuation stripped, lowercased), vocab sorted by
    (-freq, word) with ``cutoff``, labels pos=0 / neg=1."""

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 download=False):
        if mode not in ("train", "test"):
            raise ValueError(f"mode must be 'train' or 'test', got {mode!r}")
        self.mode = mode
        self.data_file = _require(data_file,
                                  "the aclImdb tarball (aclImdb_v1.tar.gz)")
        self.word_idx = self._build_word_dict(cutoff)
        self._load_anno()

    def _tokenize(self, pattern):
        data = []
        with tarfile.open(self.data_file) as tarf:
            tf = tarf.next()
            while tf is not None:
                if bool(pattern.match(tf.name)):
                    # reference quirk: py3 leaves these as bytes tokens;
                    # decode so the vocab is keyed by str
                    raw = (tarf.extractfile(tf).read().rstrip(b"\n\r")
                           .translate(None,
                                      string.punctuation.encode("latin-1"))
                           .lower())
                    data.append(raw.decode("latin-1").split())
                tf = tarf.next()
        return data

    def _build_word_dict(self, cutoff):
        pattern = re.compile(
            r"aclImdb/((train)|(test))/((pos)|(neg))/.*\.txt$")
        word_freq = collections.defaultdict(int)
        for doc in self._tokenize(pattern):
            for word in doc:
                word_freq[word] += 1
        kept = [x for x in word_freq.items() if x[1] > cutoff]
        dictionary = sorted(kept, key=lambda x: (-x[1], x[0]))
        word_idx = {w: i for i, (w, _) in enumerate(dictionary)}
        word_idx["<unk>"] = len(word_idx)
        return word_idx

    def _load_anno(self):
        unk = self.word_idx["<unk>"]
        self.docs = []
        self.labels = []
        for label, sent in ((0, "pos"), (1, "neg")):
            pattern = re.compile(rf"aclImdb/{self.mode}/{sent}/.*\.txt$")
            for doc in self._tokenize(pattern):
                self.docs.append([self.word_idx.get(w, unk) for w in doc])
                self.labels.append(label)

    def __getitem__(self, idx):
        return (np.asarray(self.docs[idx]),
                np.asarray([self.labels[idx]]))

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """PTB language-model dataset (parity: `imikolov.py:29`): NGRAM mode
    yields fixed windows, SEQ mode yields (src, trg) shifted sequences;
    vocab from the train split with min_word_freq."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=-1,
                 mode="train", min_word_freq=50, download=False):
        if data_type not in ("NGRAM", "SEQ"):
            raise ValueError(
                f"data_type must be 'NGRAM' or 'SEQ', got {data_type!r}")
        if mode not in ("train", "test"):
            raise ValueError(f"mode must be 'train' or 'test', got {mode!r}")
        self.data_type = data_type
        self.window_size = window_size
        self.mode = mode
        self.min_word_freq = min_word_freq
        self.data_file = _require(
            data_file, "the PTB simple-examples tarball "
                       "(simple-examples.tgz)")
        self.word_idx = self._build_vocab()
        self._load_anno()

    def _member(self, tf, name):
        # archives may store paths with or without the leading './'
        try:
            return tf.extractfile(name)
        except KeyError:
            return tf.extractfile(name.lstrip("./").lstrip("/"))

    def _build_vocab(self):
        word_freq = collections.defaultdict(int)
        with tarfile.open(self.data_file) as tf:
            f = self._member(tf, "./simple-examples/data/ptb.train.txt")
            for line in f:
                for w in line.strip().split():
                    word_freq[w.decode()] += 1
        word_freq["<s>"] = word_freq.get("<s>", 0) + 1
        word_freq["<e>"] = word_freq.get("<e>", 0) + 1
        word_freq = {w: c for w, c in word_freq.items()
                     if c >= self.min_word_freq or w in ("<s>", "<e>")}
        ordered = sorted(word_freq.items(), key=lambda x: (-x[1], x[0]))
        word_idx = {w: i for i, (w, _) in enumerate(ordered)}
        word_idx["<unk>"] = len(word_idx)
        return word_idx

    def _load_anno(self):
        self.data = []
        unk = self.word_idx["<unk>"]
        with tarfile.open(self.data_file) as tf:
            f = self._member(
                tf, f"./simple-examples/data/ptb.{self.mode}.txt")
            for line in f:
                if self.data_type == "NGRAM":
                    if self.window_size <= 0:
                        raise ValueError("NGRAM mode needs window_size > 0")
                    toks = (["<s>"] + line.decode().strip().split()
                            + ["<e>"])
                    if len(toks) >= self.window_size:
                        ids = [self.word_idx.get(w, unk) for w in toks]
                        for i in range(self.window_size, len(ids) + 1):
                            self.data.append(
                                tuple(ids[i - self.window_size:i]))
                else:
                    toks = line.decode().strip().split()
                    ids = [self.word_idx.get(w, unk) for w in toks]
                    src = [self.word_idx["<s>"]] + ids
                    trg = ids + [self.word_idx["<e>"]]
                    if 0 < self.window_size < len(src):
                        continue
                    self.data.append((src, trg))

    def __getitem__(self, idx):
        return tuple(np.asarray(d) for d in self.data[idx])

    def __len__(self):
        return len(self.data)


class Movielens(Dataset):
    """MovieLens-1M rating prediction (parity: `movielens.py`): ml-1m zip
    with '::'-separated ratings.dat/users.dat/movies.dat; yields
    (user_id, gender, age, job, movie_id, title_ids, category_ids,
    rating) with a 9:1 train/test split by rating row hash."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=False):
        if mode not in ("train", "test"):
            raise ValueError(f"mode must be 'train' or 'test', got {mode!r}")
        self.mode = mode
        self.test_ratio = test_ratio
        self.rand_seed = rand_seed
        self.data_file = _require(data_file, "the MovieLens-1M zip "
                                             "(ml-1m.zip)")
        self._load_meta()
        self._load_data()

    def _read(self, zf, name):
        for n in zf.namelist():
            if n.endswith(name):
                return zf.read(n).decode("latin-1").splitlines()
        raise UnavailableError(f"{name} not found inside {self.data_file}")

    def _load_meta(self):
        self.categories = {}
        self.titles = {}
        self.movie_info = {}
        self.user_info = {}
        with zipfile.ZipFile(self.data_file) as zf:
            for line in self._read(zf, "movies.dat"):
                mid, title, cats = line.split("::")
                for c in cats.split("|"):
                    self.categories.setdefault(c, len(self.categories))
                for w in title.split():
                    self.titles.setdefault(w, len(self.titles))
                self.movie_info[int(mid)] = {
                    "title": [self.titles[w] for w in title.split()],
                    "categories": [self.categories[c]
                                   for c in cats.split("|")],
                }
            ages = {}
            jobs = {}
            for line in self._read(zf, "users.dat"):
                uid, gender, age, job, _zip = line.split("::")
                ages.setdefault(age, len(ages))
                jobs.setdefault(job, len(jobs))
                self.user_info[int(uid)] = {
                    "gender": 0 if gender == "M" else 1,
                    "age": ages[age], "job": jobs[job],
                }

    def _load_data(self):
        rng = np.random.default_rng(self.rand_seed)
        self.data = []
        with zipfile.ZipFile(self.data_file) as zf:
            for line in self._read(zf, "ratings.dat"):
                uid, mid, rating, _ts = line.split("::")
                is_test = rng.random() < self.test_ratio
                if (self.mode == "test") != is_test:
                    continue
                u = self.user_info[int(uid)]
                m = self.movie_info[int(mid)]
                self.data.append((
                    int(uid), u["gender"], u["age"], u["job"], int(mid),
                    m["title"], m["categories"], float(rating)))

    def __getitem__(self, idx):
        row = self.data[idx]
        return tuple(np.asarray(d) for d in row)

    def __len__(self):
        return len(self.data)


class _GatedDataset(Dataset):
    """Datasets whose multi-file archives cannot be sourced in this
    environment: present and documented, never silent."""

    _DESC = ""

    def __init__(self, *args, **kwargs):
        raise UnavailableError(
            f"{type(self).__name__} requires {self._DESC}, which cannot be "
            f"fetched without network egress; the parsing pipeline is the "
            f"reference's (`python/paddle/text/datasets/`) — provide the "
            f"archives locally and file an issue to enable it")


class Conll05st(_GatedDataset):
    _DESC = ("the CoNLL-2005 SRL archives (conll05st-tests.tar.gz + "
             "separate word/verb/target dictionaries and embeddings)")


class WMT14(_GatedDataset):
    _DESC = "the WMT'14 en-fr tarball (wmt14.tgz, pre-tokenized splits)"


class WMT16(_GatedDataset):
    _DESC = "the WMT'16 en-de tarball (wmt16.tar.gz, BPE splits)"
