"""Text utilities (parity: `python/paddle/text/` — ViterbiDecoder plus the
dataset loaders; datasets require local files in the no-egress environment).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..nn.layer.layers import Layer
from ..ops.dispatch import apply_nondiff

__all__ = ["viterbi_decode", "ViterbiDecoder", "datasets", "Imdb",
           "Imikolov", "UCIHousing", "Movielens", "Conll05st", "WMT14",
           "WMT16"]


def __getattr__(name):
    # lazy: the dataset module pulls in io/tarfile machinery only on use
    if name in ("datasets", "Imdb", "Imikolov", "UCIHousing", "Movielens",
                "Conll05st", "WMT14", "WMT16"):
        import importlib

        # importlib (not `from . import`): the latter re-enters this
        # __getattr__ through the parent-package getattr and recurses
        _ds = importlib.import_module(".datasets", __name__)
        globals()["datasets"] = _ds
        for n in _ds.__all__:
            globals()[n] = getattr(_ds, n)
        return globals()[name]
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True, name=None):
    """Parity: `paddle.text.viterbi_decode` — CRF Viterbi over
    [batch, seq, n_tags] emissions with [n_tags, n_tags] transitions.
    Returns (scores [batch], paths [batch, seq])."""

    def decode(emis, trans):
        B, T, N = emis.shape

        def step(carry, e_t):
            score = carry  # [B, N]
            cand = score[:, :, None] + trans[None, :, :]  # [B, from, to]
            best = jnp.max(cand, axis=1) + e_t
            idx = jnp.argmax(cand, axis=1)
            return best, idx

        init = emis[:, 0, :]
        if include_bos_eos_tag:
            # bos: transition from tag N-2 ("start") per reference convention
            init = init + trans[None, N - 2, :]
        scores, backptrs = jax.lax.scan(
            step, init, jnp.moveaxis(emis[:, 1:, :], 1, 0))
        final = scores
        if include_bos_eos_tag:
            final = final + trans[None, :, N - 1]
        best_score = jnp.max(final, axis=-1)
        last_tag = jnp.argmax(final, axis=-1)

        def backtrack(carry, ptr_t):
            tag = carry
            prev = jnp.take_along_axis(ptr_t, tag[:, None], 1)[:, 0]
            return prev, prev  # ys[i] = tag at position i

        _, path_rev = jax.lax.scan(backtrack, last_tag, backptrs,
                                   reverse=True)
        path = jnp.concatenate(
            [jnp.moveaxis(path_rev, 0, 1),
             last_tag[:, None]], axis=1)
        return best_score, path.astype(jnp.int32)

    scores, paths = apply_nondiff(
        "viterbi_decode", decode, (potentials, transition_params))
    return scores, paths


class ViterbiDecoder(Layer):
    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions if isinstance(transitions, Tensor) \
            else Tensor(jnp.asarray(transitions))
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
