"""Multi-replica serving router: prefix-affinity dispatch, compile-free
scale-out, replica-failure drain (docs/SERVING.md "Replica router").

One :class:`ServingEngine` is one replica behind FCFS; the
millions-of-users path needs N of them behind one front door. This
module is that front door — a **jax-free** :class:`RouterEngine`
exposing the same ``submit`` / ``step`` / ``run`` / ``pop_finished``
surface as the engine, dispatching over N replicas:

- **In-process replicas** (default): N engines sharing one model. The
  AOT exec cache (``jit/exec_cache.py``) keys compiled programs on
  generation config, param avals, pool geometry, lanes and mesh — all
  identical across identically-configured replicas — so replica 1
  compiles the three phase programs and replicas 2..N ride the warm
  cache: process-wide fresh XLA compiles stay at 3 no matter how many
  replicas serve (tests/test_serving_router.py proves it). This is
  GSPMD's one-program-many-instances economics one level up: the
  compiled artifact is the shared unit, so scale-out is a scheduling
  problem, not a compiler one.
- **Worker replicas** (``mode="worker"``): one subprocess per replica
  (:mod:`.router_worker`, a JSON-lines pipe protocol), each building
  its model from a ``module:callable`` factory spec — the deployment
  shape, where a warm ``PT_EXEC_CACHE`` directory makes every worker's
  start compile-free too. The router side stays jax-free either way.

**Dispatch is prefix-affinity-first**: the router hashes each prompt
with the same chained blake2b keys the block pool's prefix index uses
(``kv_cache.prefix_keys``) and keeps a shadow map of which replicas
were sent which chains. A new request routes to the live replica whose
recorded coverage of its opening is longest — that replica's prefix
cache already holds (or is about to hold) those published blocks, so
the prefill is cheap there and cold everywhere else. No coverage (or
affinity off via ``PT_SERVE_AFFINITY=0``): least-loaded wins — fewest
resident requests (occupied lanes + queue depth), ties to the lowest
replica index. Every rule is deterministic (this module is in
PTL005's determinism scope), so a seeded trace replays byte-identically.

**Replica failure is drained, not fatal**: a replica whose ``step()``
raises is marked dead; every request the router had routed to it —
queued AND in-flight — drains back into the router queue and
re-dispatches to survivors. Re-dispatch restarts from the prompt
(partial output is discarded): greedy decode is deterministic and
token-identical to per-request ``generate()``, so the survivor
reproduces the exact same tokens — the same argument that makes
recompute-on-preemption token-correct inside one engine. The router
registers as a blackbox state provider (``monitor/blackbox.py``,
label ``serving_router``), so the postmortem artifact names the dead
replica and snapshots every survivor's scheduler/pool/lane state.

Monitor contract: ``router/*`` counters under the None-slot
zero-overhead-off contract (``monitor.INSTRUMENTED_MODULES``).
Always-on plain-int ``RouterEngine.counters`` feed the serving bench
(``PT_SERVE_BENCH_REPLICAS``) independently of the monitor.
"""
from __future__ import annotations

import collections
import itertools
import json
import os
import subprocess
import sys

import numpy as np

from ..monitor import _register as _monitor_register
from ..monitor import blackbox as _blackbox
from ..monitor import live as _live_telemetry
from .kv_cache import prefix_keys

__all__ = ["RouterConfig", "RouterEngine"]

# telemetry slots (paddle_tpu.monitor None-slot contract): None unless
# PT_MONITOR wired them. `_live` (monitor/live.py) additionally drives
# the per-step worker telemetry pull that closes the fleet-aggregation
# gap: worker-mode replica counters/sketches ship over the pipe and
# merge router-side, so /metrics reads the same totals either mode.
_monitor = None
_live = None

_auto_id = itertools.count()


def _env_int(name, default):
    v = os.environ.get(name)
    return int(v) if v else default


class RouterConfig:
    """Router policy knobs. Env defaults (CLAUDE.md knob table):

    - ``replicas`` (``PT_SERVE_REPLICAS``, 2): engines behind the
      router.
    - ``affinity`` (``PT_SERVE_AFFINITY``, on): prefix-affinity
      dispatch; ``0`` routes least-loaded only (the A/B lever the
      serving bench's affinity proof and ``perf_guard
      --affinity-drop`` rest on).
    - ``mode`` (``PT_SERVE_ROUTER_MODE``, ``inproc``): ``inproc`` =
      N engines in this process sharing one model; ``worker`` = one
      :mod:`.router_worker` subprocess per replica.
    - ``worker_factory`` (``PT_SERVE_WORKER_FACTORY``): worker mode's
      model source, a ``module:callable`` spec — each worker imports
      ``module`` and calls ``callable()`` for its model.
    """

    def __init__(self, replicas=None, affinity=None, mode=None,
                 worker_factory=None):
        self.replicas = replicas if replicas is not None \
            else _env_int("PT_SERVE_REPLICAS", 2)
        if self.replicas < 1:
            raise ValueError(
                f"replicas must be >= 1, got {self.replicas}")
        if affinity is None:
            affinity = os.environ.get(
                "PT_SERVE_AFFINITY", "1") not in ("0", "off")
        self.affinity = bool(affinity)
        self.mode = mode or os.environ.get(
            "PT_SERVE_ROUTER_MODE", "inproc")
        if self.mode not in ("inproc", "worker"):
            raise ValueError(
                f"mode must be 'inproc' or 'worker', got {self.mode!r}")
        self.worker_factory = worker_factory \
            or os.environ.get("PT_SERVE_WORKER_FACTORY")
        if self.mode == "worker" and not self.worker_factory:
            raise ValueError(
                "worker mode needs a model factory: pass "
                "worker_factory='module:callable' or set "
                "PT_SERVE_WORKER_FACTORY")


class _RouteRecord:
    """The router's own account of one live request — everything a
    re-dispatch after a replica death needs (the dead replica's state
    is untrusted and, in worker mode, unreachable)."""

    __slots__ = ("request_id", "prompt", "max_new_tokens",
                 "eos_token_id", "replica", "seq", "redispatches")

    def __init__(self, request_id, prompt, max_new_tokens, eos_token_id,
                 seq):
        self.request_id = request_id
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.eos_token_id = eos_token_id
        self.replica = None
        self.seq = seq
        self.redispatches = 0


class _InprocReplica:
    """One in-process :class:`ServingEngine` behind the handle protocol
    the router drives (submit / warmup / step / has_work / load /
    stats / debug_state / close)."""

    def __init__(self, index, model, config, drafter=None):
        # lazy: the router module itself must stay importable jax-free
        # (worker mode never pays the jax import on the router side)
        from .engine import ServingEngine

        self.index = index
        self._engine = ServingEngine(model, config, drafter=drafter)

    def submit(self, rec: _RouteRecord):
        return self._engine.submit(
            rec.prompt, max_new_tokens=rec.max_new_tokens,
            eos_token_id=rec.eos_token_id, request_id=rec.request_id)

    def warmup(self) -> None:
        self._engine.warmup()

    def step(self):
        worked = self._engine.step()
        return worked, self._engine.pop_finished()

    def has_work(self) -> bool:
        return self._engine.has_work()

    def load(self):
        sched = self._engine.scheduler
        return sched.lanes_occupied, len(sched.waiting)

    def stats(self) -> dict:
        return self._engine.stats()

    def telemetry(self):
        # in-process engines feed the process-local live collector
        # directly through their own `_live` slot — nothing to ship
        return None

    def debug_state(self) -> dict:
        return self._engine.scheduler.debug_state()

    def close(self) -> None:
        pass


class _WorkerReplica:
    """One :mod:`.router_worker` subprocess behind the same handle
    protocol: JSON-lines over stdin/stdout (replies ride a dedicated
    channel — the worker rebinds its own stdout to stderr so library
    chatter cannot corrupt the protocol). Load is modeled router-side
    from in-flight counts (submits minus finishes): exact enough for
    least-loaded, and it keeps dispatch decisions free of extra
    round-trips."""

    def __init__(self, index, factory, config_kwargs, max_lanes):
        self.index = index
        self._max_lanes = max_lanes
        self._inflight: dict = {}  # json rid key -> original rid
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env = dict(os.environ)
        env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
        # one exporter per fleet: the router process owns the metrics
        # port; workers collect (PT_LIVE_TELEMETRY) and ship their
        # telemetry over the pipe instead of binding their own server
        env.pop("PT_METRICS_PORT", None)
        if _live_telemetry.enabled():
            env["PT_LIVE_TELEMETRY"] = "1"
        self._proc = subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.serving.router_worker"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, env=env,
            text=True)
        self._call({"op": "init", "factory": factory,
                    "config": config_kwargs})

    def _call(self, msg: dict) -> dict:
        proc = self._proc
        if proc.poll() is not None:
            raise RuntimeError(
                f"router worker {self.index} exited "
                f"(rc={proc.returncode})")
        proc.stdin.write(json.dumps(msg) + "\n")
        proc.stdin.flush()
        line = proc.stdout.readline()
        if not line:
            raise RuntimeError(
                f"router worker {self.index} closed its pipe")
        reply = json.loads(line)
        if not reply.get("ok"):
            raise RuntimeError(
                f"router worker {self.index}: "
                f"{reply.get('error', 'unknown error')}")
        return reply

    def submit(self, rec: _RouteRecord):
        self._call({"op": "submit", "request_id": rec.request_id,
                    "prompt": [int(t) for t in rec.prompt],
                    "max_new_tokens": rec.max_new_tokens,
                    "eos_token_id": rec.eos_token_id})
        self._inflight[str(rec.request_id)] = rec.request_id
        return rec

    def warmup(self) -> None:
        self._call({"op": "warmup"})

    def step(self):
        reply = self._call({"op": "step"})
        fins = {}
        for key, toks in reply.get("finished", {}).items():
            rid = self._inflight.pop(key, key)
            fins[rid] = np.asarray(toks, np.int32)
        return bool(reply.get("worked")), fins

    def has_work(self) -> bool:
        return bool(self._inflight)

    def load(self):
        n = len(self._inflight)
        return min(n, self._max_lanes), max(0, n - self._max_lanes)

    def stats(self) -> dict:
        try:
            return self._call({"op": "stats"}).get("stats", {})
        except RuntimeError as exc:
            return {"worker_error": str(exc)}

    def telemetry(self):
        """The worker's cumulative monitor counters + live sketches
        (`live.export_local` shape) — cumulative, not deltas, so a
        missed pull self-heals and the router-side merge stays exact.
        None when the worker is unreachable (its last shipped payload
        stays merged)."""
        try:
            return self._call({"op": "telemetry"}).get("telemetry")
        except RuntimeError:
            return None

    def debug_state(self) -> dict:
        try:
            return self._call({"op": "debug_state"}).get("state", {})
        except RuntimeError as exc:
            return {"worker_error": str(exc)}

    def close(self) -> None:
        proc = self._proc
        if proc.poll() is None:
            try:
                proc.stdin.write(json.dumps({"op": "shutdown"}) + "\n")
                proc.stdin.flush()
                proc.wait(timeout=5)
            except Exception:
                proc.kill()
                proc.wait()


class RouterEngine:
    """Submit requests, call :meth:`step` (or :meth:`run`) — same
    driving contract as :class:`~paddle_tpu.serving.engine.ServingEngine`,
    over N replicas. See the module docstring for the dispatch and
    drain rules, docs/SERVING.md for the operational guide.

    ``config`` is the per-replica :class:`ServingConfig` (or a plain
    kwargs dict — worker mode ships it over the pipe without importing
    the jax-backed engine module router-side)."""

    def __init__(self, model=None, config=None, router_config=None,
                 drafter_factory=None):
        self.router_config = router_config or RouterConfig()
        rc = self.router_config
        self._config_kwargs = self._as_kwargs(config)
        self.block_size = self._config_kwargs.get(
            "block_size") or _env_int("PT_SERVE_BLOCK", 16)
        self.max_lanes = self._config_kwargs.get(
            "max_lanes") or _env_int("PT_SERVE_LANES", 8)
        if rc.mode == "inproc":
            if model is None:
                raise ValueError("inproc router mode needs a model")
            from .engine import ServingConfig

            cfg = config if isinstance(config, ServingConfig) \
                else ServingConfig(**self._config_kwargs)
            self._replicas = [
                _InprocReplica(
                    i, model, cfg,
                    drafter=drafter_factory() if drafter_factory
                    else None)
                for i in range(rc.replicas)]
        else:
            self._replicas = [
                _WorkerReplica(i, rc.worker_factory,
                               self._config_kwargs, self.max_lanes)
                for i in range(rc.replicas)]
        # shadow prefix index: chain key -> replicas that were routed a
        # request whose context publishes it, in dispatch order (a list,
        # never a set — dispatch is in PTL005's determinism scope)
        self._affinity: dict = {}
        self._records: dict = {}
        self._finished: dict = {}
        self._queue: collections.deque = collections.deque()
        self._dead: dict = {}  # replica index -> failure reason
        self._seq = itertools.count()
        # always-on plain-int accounting (the serving bench's source of
        # truth, like ServingEngine.counters)
        self.counters = {
            "dispatches": 0, "affinity_hits": 0, "affinity_misses": 0,
            "redispatches": 0, "dead_replicas": 0, "finished": 0,
        }
        self.dispatch_counts = [0] * rc.replicas
        _blackbox.register("serving_router", self._blackbox_state)
        # /healthz hook: the exporter reads per-replica dead/alive from
        # this weakly-held provider (monitor/live.py status registry)
        _live_telemetry.register_status("serving_router",
                                        self._health_state)

    @staticmethod
    def _as_kwargs(config) -> dict:
        if config is None:
            return {}
        if isinstance(config, dict):
            return dict(config)
        fields = ("max_lanes", "block_size", "num_blocks",
                  "prefill_chunk", "max_seq_len", "int8_weights",
                  "paged", "prefix_cache", "spec", "spec_k")
        return {f: getattr(config, f) for f in fields
                if getattr(config, f, None) is not None}

    # -- intake ---------------------------------------------------------------

    def submit(self, prompt_ids, max_new_tokens=32, eos_token_id=None,
               request_id=None):
        """Queue one request and dispatch it to a replica immediately.
        Returns the replica's :class:`Request` handle (in-process mode)
        or the router's own record (worker mode)."""
        if hasattr(prompt_ids, "numpy"):  # framework Tensor, jax-free
            prompt_ids = prompt_ids.numpy()
        prompt = np.asarray(prompt_ids, dtype=np.int32).reshape(-1)
        rid = request_id if request_id is not None else next(_auto_id)
        if rid in self._records or rid in self._finished:
            raise ValueError(
                f"duplicate request_id {rid!r} (live or finished-but-"
                f"uncollected — pop_finished() first)")
        rec = _RouteRecord(rid, prompt, int(max_new_tokens),
                           eos_token_id, next(self._seq))
        self._records[rid] = rec
        return self._dispatch(rec)

    def warmup(self) -> None:
        """Warm every replica's compiled programs. In-process replicas
        share the exec cache's in-memory tier, so replica 1 pays the
        compiles and 2..N load warm — the compile-free scale-out
        contract."""
        for i, rep in enumerate(self._replicas):
            if i not in self._dead:
                rep.warmup()

    # -- dispatch -------------------------------------------------------------

    def _live(self) -> list:
        live = [i for i in range(len(self._replicas))
                if i not in self._dead]
        if not live:
            raise RuntimeError(
                f"all {len(self._replicas)} router replicas are dead: "
                f"{self._dead}")
        return live

    def _lookup_keys(self, prompt) -> list:
        # the same cap admission uses (kv_cache.prefix_keys): at least
        # one token always prefills, so only ctx-1 tokens are
        # acquirable — scoring past that would reward unsharable keys
        return prefix_keys(prompt, self.block_size,
                           limit_tokens=prompt.size - 1)

    def _choose(self, rec: _RouteRecord):
        """Pick a live replica for ``rec``: longest recorded prefix
        coverage first, then least-loaded, then lowest index — every
        comparison deterministic."""
        live = self._live()
        loads = {i: sum(self._replicas[i].load()) for i in live}
        if self.router_config.affinity and rec.prompt.size > 1:
            keys = self._lookup_keys(rec.prompt)
            cov = {}
            for i in live:
                n = 0
                for key in keys:
                    owners = self._affinity.get(key)
                    if owners is None or i not in owners:
                        break
                    n += 1
                cov[i] = n
            best = max(cov.values(), default=0)
            if best > 0:
                pick = min((i for i in live if cov[i] == best),
                           key=lambda i: (loads[i], i))
                return pick, True
        pick = min(live, key=lambda i: (loads[i], i))
        return pick, False

    def _dispatch(self, rec: _RouteRecord, redispatch=False):
        idx, hit = self._choose(rec)
        rec.replica = idx
        handle = self._replicas[idx].submit(rec)
        self.counters["dispatches"] += 1
        self.counters["affinity_hits" if hit else "affinity_misses"] += 1
        self.dispatch_counts[idx] += 1
        if redispatch:
            rec.redispatches += 1
            self.counters["redispatches"] += 1
        if self.router_config.affinity:
            # record the keys this replica's prefill will publish (all
            # full prompt blocks) so later same-opening requests chase it
            for key in prefix_keys(rec.prompt, self.block_size):
                owners = self._affinity.setdefault(key, [])
                if idx not in owners:
                    owners.append(idx)
        m = _monitor
        if m is not None:
            m.on_router_dispatch(idx, hit, redispatch=redispatch)
        return handle

    # -- the step loop --------------------------------------------------------

    def step(self) -> bool:
        """One router round: re-dispatch anything a dead replica
        drained back, then step every live replica that has work,
        collecting finished outputs. A replica raise marks it dead and
        drains its requests (see :meth:`_mark_dead`); the raise is
        absorbed — survivors keep serving. Returns whether any work was
        done."""
        worked = False
        while self._queue:
            self._dispatch(self._queue.popleft(), redispatch=True)
            worked = True
        for i, rep in enumerate(self._replicas):
            if i in self._dead or not rep.has_work():
                continue
            try:
                w, fins = rep.step()
            except Exception as exc:  # noqa: BLE001 — drain, don't die
                self._mark_dead(i, exc)
                worked = True
                continue
            worked = worked or w
            for rid, toks in fins.items():
                self._records.pop(rid, None)
                self._finished[rid] = np.asarray(toks)
                self.counters["finished"] += 1
            m = _monitor
            if m is not None:
                occ, queued = rep.load()
                m.on_router_lanes(i, occ, queued)
            lv = _live
            if lv is not None:
                # fleet aggregation: pull the worker's cumulative
                # telemetry after its step so this round's finishes are
                # already in the payload (in-process replicas return
                # None — they feed the local collector directly)
                tel = rep.telemetry()
                if tel is not None:
                    lv.set_remote(str(i), tel)
        return worked

    def run(self) -> dict:
        """Drain: step until every submitted request finished, then
        collect-and-retire (the engine's :meth:`run` contract)."""
        while self.has_work():
            self.step()
        return self.pop_finished()

    def pop_finished(self) -> dict:
        out = {rid: np.asarray(toks)
               for rid, toks in self._finished.items()}
        self._finished.clear()
        return out

    def has_work(self) -> bool:
        return bool(self._records)

    # -- failure drain --------------------------------------------------------

    def _mark_dead(self, idx: int, exc: BaseException) -> None:
        """Replica ``idx`` raised: mark it dead, abandon its engine
        state (pool and all — nothing it holds is trusted), and drain
        every request routed to it back into the router queue in
        original submit order. Re-dispatch restarts each from its
        prompt on a survivor; greedy determinism reproduces the exact
        tokens. The blackbox postmortem lands before serving resumes,
        naming the dead replica."""
        self._dead[idx] = f"{type(exc).__name__}: {exc}"
        self.counters["dead_replicas"] += 1
        drained = sorted(
            (rec for rec in self._records.values()
             if rec.replica == idx), key=lambda r: r.seq)
        for rec in drained:
            rec.replica = None
            self._queue.append(rec)
        m = _monitor
        if m is not None:
            m.on_router_dead(idx)
        try:
            self._replicas[idx].close()
        except Exception:  # noqa: BLE001 — a dead worker can't object
            pass
        _blackbox.maybe_dump(reason="router_replica_dead", error=exc)

    def close(self) -> None:
        """Shut every replica down (worker subprocesses exit)."""
        for i, rep in enumerate(self._replicas):
            if i not in self._dead:
                rep.close()

    # -- introspection --------------------------------------------------------

    @property
    def _params(self):
        """The first live in-process replica's decode params — the
        serving bench's HBM byte model reads sizes from the engine's
        OWN arrays (benchmarks/serving_bench.py), and every in-process
        replica shares one copy. Worker-mode replicas hold theirs in
        another process."""
        for i in self._live():
            rep = self._replicas[i]
            if isinstance(rep, _InprocReplica):
                return rep._engine._params
        raise AttributeError(
            "_params unavailable: worker-mode replicas hold params "
            "out-of-process")

    _ADDITIVE_STATS = (
        "admits", "finished", "preemptions", "prefill_chunks",
        "decode_steps", "verify_steps", "decoded_tokens",
        "spec_proposed_tokens", "spec_accepted_tokens",
        "spec_bonus_tokens", "prefix_hit_tokens", "prefix_miss_tokens",
        "kv_read_tokens", "kv_dense_read_tokens", "decode_wall_s",
        "decode_rounds", "free_blocks", "allocatable_blocks",
        "shared_blocks", "cold_blocks", "indexed_blocks",
        "lanes_occupied", "waiting", "requests", "uncollected",
    )

    def stats(self) -> dict:
        """Aggregate engine stats summed across live replicas (the
        additive counters; geometry fields ride from the first live
        replica so bench code reads one dict either way), plus the
        router's own account."""
        live = [i for i in range(len(self._replicas))
                if i not in self._dead]
        out: dict = {}
        for n, i in enumerate(live):
            s = self._replicas[i].stats()
            if n == 0:
                out.update(s)
            else:
                for k in self._ADDITIVE_STATS:
                    if k in s:
                        out[k] = out.get(k, 0) + s[k]
        d = self.counters["dispatches"]
        out.update(
            replicas=len(self._replicas),
            live_replicas=len(live),
            dead_replicas=sorted(self._dead),
            affinity=self.router_config.affinity,
            router=dict(self.counters),
            affinity_hit_rate=(self.counters["affinity_hits"] / d
                               if d else 0.0),
            dispatches_per_replica=list(self.dispatch_counts),
            queued=len(self._queue),
        )
        return out

    def _health_state(self) -> dict:
        """/healthz provider: the light per-replica dead/alive ledger —
        plain ints and strings only, safe to read at scrape time (the
        heavyweight scheduler snapshots stay in `_blackbox_state`)."""
        return {
            "mode": self.router_config.mode,
            "queued": len(self._queue),
            "counters": dict(self.counters),
            "replicas": [
                {"replica": i, "dead": i in self._dead,
                 "reason": self._dead.get(i)}
                for i in range(len(self._replicas))],
        }

    def _blackbox_state(self) -> dict:
        """Blackbox provider (``monitor/blackbox.py``): router config +
        counters, the dead-replica ledger, the drain queue, every live
        request's routing record, and each surviving replica's
        scheduler/pool/lane snapshot. Read-only and exception-tolerant
        by the dump's contract."""
        per_replica = []
        for i, rep in enumerate(self._replicas):
            if i in self._dead:
                per_replica.append(
                    {"replica": i, "dead": True,
                     "reason": self._dead[i]})
            else:
                per_replica.append(
                    {"replica": i, "dead": False,
                     "scheduler": rep.debug_state()})
        return {
            "config": {
                "replicas": self.router_config.replicas,
                "affinity": self.router_config.affinity,
                "mode": self.router_config.mode,
                "block_size": self.block_size,
                "max_lanes": self.max_lanes,
            },
            "counters": dict(self.counters),
            "dispatches_per_replica": list(self.dispatch_counts),
            "dead": dict(self._dead),
            "queue": [rec.request_id for rec in self._queue],
            "records": [{
                "request_id": rec.request_id, "replica": rec.replica,
                "prompt_tokens": int(rec.prompt.size),
                "max_new_tokens": rec.max_new_tokens,
                "redispatches": rec.redispatches,
            } for rec in sorted(self._records.values(),
                                key=lambda r: r.seq)],
            "replicas": per_replica,
        }


_monitor_register(sys.modules[__name__])
