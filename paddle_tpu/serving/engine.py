"""Continuous-batching decode engine over the block KV pool.

The millions-of-users path (ROADMAP item 1): requests of unequal prompt
and output lengths share ONE compiled decode step — per-lane block
tables and valid lengths are runtime *data*, so admission, eviction, and
growth never retrace. Three compiled programs serve the whole lifetime:

- **prefill chunk** ``[1, C]``: one lane's context enters the pool C
  tokens at a time (padded tail chunks write only below the context
  length — pads are redirected to the null block), and the final chunk
  samples the first generated token from the last real position. With
  the prefix cache on (``PT_SERVE_PREFIX_CACHE``, default) prefill
  starts at the first token not covered by acquired shared blocks —
  a fully cached system prompt costs zero prefill chunks beyond its
  private tail.
- **decode step** ``[L, 1]``: every occupied lane advances one token —
  write the pending token's K/V at ``pool_len``, attend over the lane's
  gathered blocks masked to ``slot <= pos``, greedy-sample the next.
- **verify step** ``[L, k+1]`` (speculative decoding, ``PT_SERVE_SPEC``
  — docs/SERVING.md): when the host-side drafter
  (:mod:`.speculative`) proposed tokens for any lane, every lane's
  pending token plus its (possibly empty) draft is scored in one pass;
  the host accepts each lane's longest prefix matching the program's
  own greedy argmaxes, plus one bonus token. Draft length is DATA:
  short/empty drafts pad up to ``k`` with writes redirected to the
  null block (``wlimit``), so a no-draft lane verifies exactly one
  token and churn in draft lengths never retraces. Rejected positions
  roll back by rewinding ``pool_len`` only — the tail blocks are
  lane-private (shared prefix blocks are full + frozen), so
  over-written K/V was never shared and the next accepted write simply
  overwrites it.

All three compile through :func:`paddle_tpu.jit.exec_cache.get_or_compile`
(keyed on generation config, param avals, pool geometry, lane count and
mesh), so a warm ``PT_EXEC_CACHE`` server start pays zero fresh XLA
compiles. The attention/RoPE/MLP math reuses
``models/generation.py``'s helpers (``_rms``/``_mm``/``_rope_at``) and
mirrors its ``_attend`` line for line — engine outputs are
token-identical to per-request ``generate()`` calls
(tests/test_serving.py proves it, padding included, because masked
slots contribute exactly-zero softmax weight).

Reference lineage: the static-graph serving surface this replaces is
`paddle_infer.Predictor` (`paddle/fluid/inference/api/
analysis_predictor.h:94` — see ``paddle_tpu/inference``); request-level
continuous batching + block KV follow the Orca/vLLM iteration-level
scheduling + PagedAttention memory model (docs/SERVING.md).

Monitor contract: this module carries ``_monitor``/``_spans``
None-slots (``serving/*`` counters + request-lifecycle spans,
``monitor.INSTRUMENTED_MODULES``) — when monitoring is off no monitor
callable is ever invoked; the always-on plain-int
``ServingEngine.counters`` and per-request latency attribution
(``Request.queue_ms``/``prefill_ms``/``decode_ms``/``preempted_ms``,
telescoped at the phase boundaries the engine already timestamps) feed
the serving bench instead. With ``PT_MONITOR=1`` every request's
journey lands in the flight recorder on its own ``req/<trace_id>``
lane — queue/requeue waits (scheduler-side), prefill chunks with their
prefix-cache hit/miss split, decode/verify rounds with draft/accept
counts, preemptions, and a whole-journey finish span carrying the
attribution breakdown (docs/OBSERVABILITY.md). On an engine raise the
blackbox postmortem (``monitor/blackbox.py``) serializes the last
spans + scheduler state to ``serving_blackbox.json`` before the error
propagates.

Greedy decode only for now: per-request sampling params would ride as
traced lane vectors (same no-retrace discipline); left for a later PR.
"""
from __future__ import annotations

import collections
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor
from ..models.generation import (
    _GenCfg, _collect_params, _mm, _rms, _rope_at,
)
from ..monitor import _register as _monitor_register
from ..monitor import blackbox as _blackbox
from ..monitor import live as _live_telemetry
from .kv_cache import BlockPool, blocks_needed
from .scheduler import RUNNING, FCFSScheduler, Request
from .speculative import NgramDrafter

_EMPTY_DRAFT = np.zeros((0,), np.int32)

__all__ = ["ServingConfig", "ServingEngine"]

# telemetry slots (paddle_tpu.monitor None-slot contract): None unless
# PT_MONITOR wired them. `_live` is the streaming-SLO sibling
# (monitor/live.py): armed by PT_LIVE_TELEMETRY / PT_METRICS_PORT /
# PT_SLO_* independently of PT_MONITOR — its feeds ride the always-on
# Request attribution stamps, so arming it costs three guarded calls
# per step and nothing when off.
_monitor = None
_spans = None
_live = None


def _env_int(name, default):
    v = os.environ.get(name)
    return int(v) if v else default


class ServingConfig:
    """Engine geometry. Every field has a ``PT_SERVE_*`` env default so a
    server deploy tunes without code (CLAUDE.md knob table):

    - ``max_lanes`` (``PT_SERVE_LANES``, 8): decode-batch width — lanes
      are the compiled step's batch dimension.
    - ``block_size`` (``PT_SERVE_BLOCK``, 16): tokens per KV block.
    - ``num_blocks`` (``PT_SERVE_BLOCKS``): pool size incl. the reserved
      null block; default sizes every lane for ``max_seq_len`` (no
      preemption pressure — shrink it to trade HBM for requeues).
    - ``prefill_chunk`` (``PT_SERVE_PREFILL_CHUNK``, 32): prefill
      program width; prompts enter in ceil(len/chunk) calls.
    - ``max_seq_len`` (``PT_SERVE_MAX_LEN``): per-request prompt+output
      ceiling; defaults to the model's max_position_embeddings.
    - ``int8_weights`` (``PT_DECODE_INT8``): weight-only int8 matmuls,
      same lever as ``generate()``.
    - ``kv_int8`` (``PT_SERVE_KV_INT8``, off): int8 block pool — K/V
      quantize on write (per-position symmetric amax over head_dim,
      `quantization.quantize_kv`; fp32 scales ride in paired
      ``[layers, num_blocks, block_size, kv_heads]`` scale pools) and
      dequantize on read, halving pool HBM at fixed ``num_blocks``.
      Token-identical to ``generate(kv_int8=True)`` — the quantize-
      aware reference (tests/test_serving_kv_int8.py); dtype is a
      static exec-cache key, so churn still never retraces and a fleet
      still pays exactly 3 fresh compiles. Off = today's engine, byte
      for byte. docs/SERVING.md "int8 KV".
    - ``paged`` (``PT_SERVE_PAGED``): decode-attention read path —
      ``"auto"`` (default) engages the Pallas paged-attention kernel
      (``ops/pallas/paged_attention.py``) only on a measured-faster
      tune-table row for this geometry (measurement-first; no row =
      the dense gathered read), ``"1"``/True forces it on,
      ``"0"``/False off.
    - ``prefix_cache`` (``PT_SERVE_PREFIX_CACHE``, on): ref-counted
      prefix sharing in the block pool — requests whose context starts
      with already-cached full blocks (shared system prompts, few-shot
      headers, recompute re-admissions) skip prefilling them
      (docs/SERVING.md). ``0`` restores the share-nothing pool.
    - ``spec`` (``PT_SERVE_SPEC``, auto): speculative decoding —
      ``"auto"`` engages it for the greedy path (which is all the
      engine decodes today), ``0``/``off`` disables. ``spec_k``
      (``PT_SERVE_SPEC_K``, 4) caps tokens proposed per lane per
      round; ``spec_k=0`` degenerates to plain decode (no verify
      program is compiled). docs/SERVING.md.
    """

    def __init__(self, max_lanes=None, block_size=None, num_blocks=None,
                 prefill_chunk=None, max_seq_len=None, int8_weights=None,
                 paged=None, prefix_cache=None, spec=None, spec_k=None,
                 kv_int8=None):
        self.max_lanes = max_lanes if max_lanes is not None \
            else _env_int("PT_SERVE_LANES", 8)
        self.block_size = block_size if block_size is not None \
            else _env_int("PT_SERVE_BLOCK", 16)
        self.num_blocks = num_blocks if num_blocks is not None \
            else _env_int("PT_SERVE_BLOCKS", 0) or None
        self.prefill_chunk = prefill_chunk if prefill_chunk is not None \
            else _env_int("PT_SERVE_PREFILL_CHUNK", 32)
        self.max_seq_len = max_seq_len if max_seq_len is not None \
            else _env_int("PT_SERVE_MAX_LEN", 0) or None
        if int8_weights is None:
            int8_weights = os.environ.get("PT_DECODE_INT8") == "1"
        self.int8_weights = bool(int8_weights)
        if kv_int8 is None:
            kv_int8 = os.environ.get("PT_SERVE_KV_INT8") == "1"
        self.kv_int8 = bool(kv_int8)
        if paged is None:
            paged = os.environ.get("PT_SERVE_PAGED", "auto")
        if paged in (True, 1, "1", "on"):
            self.paged = "on"
        elif paged in (False, 0, "0", "off"):
            self.paged = "off"
        else:
            self.paged = "auto"
        if prefix_cache is None:
            prefix_cache = os.environ.get(
                "PT_SERVE_PREFIX_CACHE", "1") not in ("0", "off")
        self.prefix_cache = bool(prefix_cache)
        if spec is None:
            spec = os.environ.get("PT_SERVE_SPEC", "auto")
        # "auto" == on: the engine is greedy-only, and greedy is exactly
        # where verification preserves token identity for free
        self.spec = spec not in (False, 0, "0", "off")
        self.spec_k = spec_k if spec_k is not None \
            else _env_int("PT_SERVE_SPEC_K", 4)
        if self.spec_k < 0:
            raise ValueError(f"spec_k must be >= 0, got {self.spec_k}")
        if self.spec_k == 0:
            self.spec = False  # k=0 IS plain decode; skip the program
        for name in ("max_lanes", "block_size", "prefill_chunk"):
            if getattr(self, name) < 1:
                raise ValueError(f"{name} must be >= 1, "
                                 f"got {getattr(self, name)}")


# -- compiled phases ----------------------------------------------------------

def _attend_lanes(q, kc, vc, pos, nh, nkv, sliding_window=0):
    """``models/generation.py:_attend`` with PER-TOKEN positions: q
    [b, s, nh, d] against the gathered block slots kc/vc [b, L, nkv, d].
    Slot ``l`` is visible to the query at absolute position ``p =
    pos[b, t]`` iff ``l <= p`` — block tables lay a lane's positions out
    in order, so slot index == absolute position for every allocated
    slot, and unallocated/pad slots sit above every real ``p``. The math
    (fp32 einsum, 1/sqrt(d), -1e30 mask, fp32 softmax/AV) mirrors
    ``_attend`` exactly so masked slots carry exactly-zero weight and
    engine outputs stay token-identical to ``generate()``."""
    b, s, _, d = q.shape
    L = kc.shape[1]
    g = nh // nkv
    qg = q.reshape(b, s, nkv, g, d)
    logits = jnp.einsum("bskgd,blkd->bskgl", qg.astype(jnp.float32),
                        kc.astype(jnp.float32)) / np.sqrt(d)
    vis = jnp.arange(L)[None, None, :] <= pos[:, :, None]  # [b, s, L]
    if sliding_window > 0:
        vis &= jnp.arange(L)[None, None, :] > pos[:, :, None] \
            - sliding_window
    logits = jnp.where(vis[:, :, None, None, :], logits, -1e30)
    p = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bskgl,blkd->bskgd", p, vc.astype(jnp.float32))
    return out.reshape(b, s, nh, d).astype(q.dtype)


def _pool_forward(params, kpool, vpool, kscale, vscale, tables, ids,
                  pos, wlimit, cfg, paged=False, paged_dead="clamp"):
    """Forward ``ids`` [b, s] at absolute positions ``pos`` [b, s]
    against the block pool: per layer, write each token's K/V into its
    lane's block at ``pos`` (writes at positions >= ``wlimit[b]`` — pad
    tail of a final prefill chunk, idle decode lanes — are redirected to
    null block 0 so they can never clobber live KV), then attend over
    the lane's whole gathered table. Layer math is
    ``models/generation.py:_block`` on the pooled layout.

    ``kscale``/``vscale`` are the int8 mode's paired fp32 scale pools
    (``[layers, num_blocks, block_size, kv_heads]``; None in bf16 mode
    — None is an empty pytree, so the bf16 jaxpr is byte-identical to
    the pre-int8 program): writes quantize K/V per position through the
    shared `quantization.quantize_kv` (scale writes ride the same
    null-redirected ``blk``/``off``, null block included), reads
    dequantize the gathered blocks before the same fp32 attention —
    identical ops to ``generate(kv_int8=True)``'s round-trip, so the
    two paths stay bit-equal. Returns
    (x [b, s, hidden], kpool, vpool, kscale, vscale)."""
    b, s = ids.shape
    nh = cfg.num_attention_heads
    nkv = cfg.num_key_value_heads or nh
    d = cfg.hidden_size // nh
    B = kpool.shape[2]
    M = tables.shape[1]
    dt = jnp.dtype(cfg.dtype)
    quant = kscale is not None
    x = params["embed"][ids].astype(dt)
    idx = jnp.minimum(pos // B, M - 1)  # pad pos can run past the table
    blk = jnp.take_along_axis(tables, idx, axis=1)
    ok = pos < wlimit[:, None]
    blk = jnp.where(ok, blk, 0)
    off = jnp.where(ok, pos % B, 0)
    n_layers = params["ln1"].shape[0]

    def body(carry, li):
        if quant:
            x, kp, vp, ks, vs = carry
        else:
            x, kp, vp = carry
            ks = vs = None
        layer_p = {k: jax.tree_util.tree_map(lambda a: a[li], params[k])
                   for k in
                   ("ln1", "qkv", "o", "ln2", "gate_up", "down")}
        h = _rms(x, layer_p["ln1"], cfg.rms_norm_eps)
        qkv = _mm(h, layer_p["qkv"])
        q, k, v = jnp.split(qkv, [nh * d, nh * d + nkv * d], axis=-1)
        q = q.reshape(b, s, nh, d)
        k = k.reshape(b, s, nkv, d)
        v = v.reshape(b, s, nkv, d)
        q, k = _rope_at(q, k, pos, cfg.rope_theta)
        if quant:
            from ..quantization import quantize_kv

            k, k_s = quantize_kv(k)
            v, v_s = quantize_kv(v)
            ks = ks.at[li, blk, off].set(k_s)
            vs = vs.at[li, blk, off].set(v_s)
        kp = kp.at[li, blk, off].set(k)
        vp = vp.at[li, blk, off].set(v)
        if paged and s == 1:
            # Pallas paged read: gather straight from the pool via the
            # block table, touching only each lane's live prefix — the
            # dense kp[li][tables] gather below reads every table slot
            interp = jax.default_backend() not in ("tpu", "axon")
            # (axon = the tunneled TPU plugin, the registry's alias)
            if quant:
                from ..ops.pallas.paged_attention import \
                    paged_attend_int8

                out = paged_attend_int8(
                    q.reshape(b, nh, d), kp[li], vp[li], ks[li],
                    vs[li], tables, pos[:, 0],
                    window=cfg.sliding_window, dead=paged_dead,
                    interpret=interp)[:, None]
            else:
                from ..ops.pallas.paged_attention import paged_attend

                out = paged_attend(
                    q.reshape(b, nh, d), kp[li], vp[li], tables,
                    pos[:, 0], window=cfg.sliding_window,
                    dead=paged_dead, interpret=interp)[:, None]
        else:
            kc = kp[li][tables].reshape(b, M * B, nkv, d)
            vc = vp[li][tables].reshape(b, M * B, nkv, d)
            if quant:
                from ..quantization import dequantize_kv

                kc = dequantize_kv(
                    kc, ks[li][tables].reshape(b, M * B, nkv), dt)
                vc = dequantize_kv(
                    vc, vs[li][tables].reshape(b, M * B, nkv), dt)
            out = _attend_lanes(q, kc, vc, pos, nh, nkv,
                                sliding_window=cfg.sliding_window)
        x = x + _mm(out.reshape(b, s, nh * d), layer_p["o"])
        h2 = _rms(x, layer_p["ln2"], cfg.rms_norm_eps)
        gu = _mm(h2, layer_p["gate_up"])
        gate, up = jnp.split(gu, 2, axis=-1)
        x = x + _mm(jax.nn.silu(gate.astype(jnp.float32)).astype(x.dtype)
                    * up, layer_p["down"])
        if quant:
            return (x, kp, vp, ks, vs), None
        return (x, kp, vp), None

    if quant:
        (x, kpool, vpool, kscale, vscale), _ = jax.lax.scan(
            body, (x, kpool, vpool, kscale, vscale),
            jnp.arange(n_layers))
    else:
        (x, kpool, vpool), _ = jax.lax.scan(
            body, (x, kpool, vpool), jnp.arange(n_layers))
    return x, kpool, vpool, kscale, vscale


def _prefill_chunk(params, kpool, vpool, kscale, vscale, table, ids,
                   start, ctx_len, last_idx, *, cfg):
    """One lane's prefill chunk: ``ids`` [1, C] at positions
    [start, start+C); greedy-samples from position ``last_idx`` within
    the chunk (the overall last real token on the final chunk; ignored
    by the caller otherwise). Returns
    (tok [1], kpool, vpool, kscale, vscale)."""
    C = ids.shape[1]
    pos = (start + jnp.arange(C, dtype=jnp.int32))[None, :]
    x, kpool, vpool, kscale, vscale = _pool_forward(
        params, kpool, vpool, kscale, vscale, table, ids, pos,
        jnp.reshape(ctx_len, (1,)), cfg)
    x = _rms(x, params["norm"], cfg.rms_norm_eps)
    h = jax.lax.dynamic_index_in_dim(x, last_idx, axis=1, keepdims=False)
    logits = _mm(h, params["lm_head"]).astype(jnp.float32)
    return (jnp.argmax(logits, axis=-1).astype(jnp.int32), kpool, vpool,
            kscale, vscale)


def _decode_step(params, kpool, vpool, kscale, vscale, tables, cur_len,
                 last_tok, *, cfg, paged=False, paged_dead="clamp"):
    """The shared decode step: every lane feeds its pending token at
    position ``cur_len`` (write-then-attend, so the token sees itself
    like ``generate()``'s step does) and greedy-samples the next. Idle
    lanes (cur_len 0, table row 0) write to the null block and their
    outputs are ignored host-side. ``paged`` (static) swaps the dense
    gathered KV read for the Pallas paged-attention kernel. Returns
    (tok [L], kpool, vpool, kscale, vscale)."""
    pos = cur_len[:, None]
    x, kpool, vpool, kscale, vscale = _pool_forward(
        params, kpool, vpool, kscale, vscale, tables, last_tok[:, None],
        pos, cur_len + 1, cfg, paged=paged, paged_dead=paged_dead)
    x = _rms(x, params["norm"], cfg.rms_norm_eps)
    logits = _mm(x[:, -1], params["lm_head"]).astype(jnp.float32)
    return (jnp.argmax(logits, axis=-1).astype(jnp.int32), kpool, vpool,
            kscale, vscale)


def _verify_step(params, kpool, vpool, kscale, vscale, tables, cur_len,
                 toks, wlimit, *, cfg):
    """The speculative verify step: ``toks`` [L, k+1] holds each lane's
    pending token (column 0) followed by its draft, at absolute
    positions ``cur_len + j``. Writes at positions >= ``wlimit[b]`` (=
    ``cur_len + 1 + draft_len``: the pad tail of a short/empty draft,
    idle lanes) go to the null block, exactly like a prefill chunk's pad
    tail — draft length is data, never shape. Write-then-attend per
    layer means draft token ``j`` attends over slots ``<= cur_len + j``,
    the same causal view plain decode would give it, so the returned
    greedy argmaxes [L, k+1] are the tokens the decode step WOULD emit
    after each draft prefix — the host's acceptance rule compares
    drafts against them directly."""
    S = toks.shape[1]
    pos = cur_len[:, None] + jnp.arange(S, dtype=jnp.int32)[None, :]
    x, kpool, vpool, kscale, vscale = _pool_forward(
        params, kpool, vpool, kscale, vscale, tables, toks, pos, wlimit,
        cfg)
    x = _rms(x, params["norm"], cfg.rms_norm_eps)
    logits = _mm(x, params["lm_head"]).astype(jnp.float32)
    return (jnp.argmax(logits, axis=-1).astype(jnp.int32), kpool, vpool,
            kscale, vscale)


# -- the engine ---------------------------------------------------------------

class ServingEngine:
    """Submit requests, call :meth:`step` (or :meth:`run`) — the engine
    admits, prefills, decodes, and reclaims between steps. See the
    module docstring for the execution model and docs/SERVING.md for
    the operational guide."""

    def __init__(self, model, config: ServingConfig | None = None,
                 drafter=None):
        if getattr(model.config, "moe_num_experts", 0) > 1:
            from ..framework.errors import UnimplementedError

            raise UnimplementedError(
                "ServingEngine does not decode MoE Llama configs yet "
                "(same gap as models/generation.generate)")
        self.model = model
        self.config = config or ServingConfig()
        cfg = self.config
        self._gcfg = _GenCfg(model.config)
        self._params = _collect_params(model,
                                       int8_weights=cfg.int8_weights)
        self.max_seq_len = int(cfg.max_seq_len
                               or model.config.max_position_embeddings)
        self.blocks_per_lane = blocks_needed(self.max_seq_len,
                                             cfg.block_size)
        num_blocks = int(cfg.num_blocks
                         or cfg.max_lanes * self.blocks_per_lane + 1)
        nh = self._gcfg.num_attention_heads
        nkv = self._gcfg.num_key_value_heads or nh
        d = self._gcfg.hidden_size // nh
        layers = self._params["ln1"].shape[0]
        dt = jnp.int8 if cfg.kv_int8 else jnp.dtype(self._gcfg.dtype)
        self._kpool = jnp.zeros(
            (layers, num_blocks, cfg.block_size, nkv, d), dt)
        self._vpool = jnp.zeros_like(self._kpool)
        # int8 mode: paired per-position fp32 amax scales (null block
        # included — masked writes land there like K/V pad writes do);
        # None in bf16 mode so the compiled programs stay byte-identical
        # to the pre-int8 engine (None is an empty pytree operand)
        if cfg.kv_int8:
            self._kscale = jnp.zeros(
                (layers, num_blocks, cfg.block_size, nkv), jnp.float32)
            self._vscale = jnp.zeros_like(self._kscale)
        else:
            self._kscale = self._vscale = None
        self.kv_pool_bytes = int(
            self._kpool.nbytes + self._vpool.nbytes
            + (self._kscale.nbytes + self._vscale.nbytes
               if cfg.kv_int8 else 0))
        self.scheduler = FCFSScheduler(
            BlockPool(num_blocks, cfg.block_size), cfg.max_lanes,
            self.blocks_per_lane, self.max_seq_len,
            prefix_cache=cfg.prefix_cache)
        # live (waiting/running) requests only; finished ones move to
        # _finished until collected — a long-running server must not
        # grow with its request history
        self._requests: dict = {}
        self._finished: dict = {}
        # newest finished journeys for the blackbox postmortem —
        # independent of _finished, which pop_finished() clears
        self._journeys: collections.deque = collections.deque(maxlen=16)
        self._prefill_exec = None
        self._decode_exec = None
        self._verify_exec = None
        # speculative decoding (docs/SERVING.md): active iff configured
        # on AND k > 0; the drafter slot is pluggable (a draft model
        # would implement Drafter.propose) — default prompt-lookup
        self.spec_active = bool(cfg.spec and cfg.spec_k > 0)
        self.drafter = drafter if drafter is not None \
            else (NgramDrafter() if self.spec_active else None)
        self.paged_active = self._resolve_paged()
        # always-on plain-int accounting (the serving bench's source of
        # truth; independent of the monitor like exec_cache._stats).
        # kv_read_tokens counts the LIVE prefix (what the paged kernel
        # reads); kv_dense_read_tokens the full-table slots the dense
        # gather reads — the pair is the bench's hbm_util delta.
        # prefix_{hit,miss}_tokens split every (re-)prefilled context:
        # hit = tokens served by acquired shared blocks (no compute),
        # miss = tokens actually pushed through the prefill program —
        # the bench's prefix_hit_rate numerator/denominator.
        # spec_{proposed,accepted}_tokens are post-trim (what the verify
        # step actually speculated) so accepted/proposed IS the accept
        # rate; bonus counts the +1 token a drafted lane's verification
        # emitted on top of its accepted prefix.
        self.counters = {
            "admits": 0, "finished": 0, "preemptions": 0,
            "prefill_chunks": 0, "decode_steps": 0, "verify_steps": 0,
            "decoded_tokens": 0,
            "spec_proposed_tokens": 0, "spec_accepted_tokens": 0,
            "spec_bonus_tokens": 0,
            "prefix_hit_tokens": 0, "prefix_miss_tokens": 0,
            "kv_read_tokens": 0, "kv_dense_read_tokens": 0,
            "kv_quant_writes": 0, "kv_quant_tokens": 0,
            "decode_wall_s": 0.0,
        }
        # postmortem hook: on an engine raise (or an external crash
        # site) the blackbox dump snapshots scheduler + request state
        # through this weakly-held provider (monitor/blackbox.py)
        _blackbox.register("serving_engine", self._blackbox_state)
        # /statusz hook: same weak-provider pattern for the live
        # exporter's debug page (stats() is plain-int and read-only)
        _live_telemetry.register_status("serving_engine", self.stats)

    def _resolve_paged(self) -> bool:
        """Decode read-path selection (ServingConfig.paged): forced
        on/off, or ``auto`` = engaged only on a measured-faster
        tune-table row for this geometry on this device (the
        measurement-first convention — no row, no flip). Which FAMILY
        is consulted follows the pool dtype: ``paged_attention`` for
        bf16 pools, ``paged_attention_int8`` (the quantized-gather
        variant) when ``kv_int8`` — an int8 engine never engages on a
        bf16 row or vice versa (``self._paged_family`` is what the
        bench/guard surface reports). Also resolves
        ``self._paged_dead``: the row's WINNING dead-iteration strategy
        — engaging the measured configuration, not the default —
        falling back to ``"clamp"`` when forced on with no row."""
        from ..ops.pallas import paged_attention as _pa
        from ..ops.pallas import search as _ksearch

        nh = self._gcfg.num_attention_heads
        nkv = self._gcfg.num_key_value_heads or nh
        d = self._gcfg.hidden_size // nh
        key = _pa.family_key(self.config.block_size, nkv, nh // nkv, d,
                             window=self._gcfg.sliding_window)
        self._paged_family = ("paged_attention_int8"
                              if self.config.kv_int8
                              else "paged_attention")
        cfg_row = _ksearch.best_config(self._paged_family, key) or {}
        self._paged_dead = cfg_row.get("dead", "clamp")
        mode = self.config.paged
        if mode == "on":
            return True
        if mode == "off":
            return False
        return _ksearch.decide(self._paged_family, key)

    # -- intake --------------------------------------------------------------

    def submit(self, prompt_ids, max_new_tokens=32, eos_token_id=None,
               request_id=None) -> Request:
        """Queue one request (prompt as a 1-D int Tensor/array/list).
        Returns the :class:`Request`; drive it with :meth:`step` /
        :meth:`run`."""
        if isinstance(prompt_ids, Tensor):
            prompt_ids = prompt_ids.numpy()
        req = Request(prompt_ids, max_new_tokens=max_new_tokens,
                      eos_token_id=eos_token_id, request_id=request_id)
        if (req.request_id in self._requests
                or req.request_id in self._finished):
            raise ValueError(
                f"duplicate request_id {req.request_id!r} (live or "
                f"finished-but-uncollected — pop_finished() first)")
        req.t_submit = time.perf_counter()
        req._t_mark = req.t_submit  # attribution clock starts here
        self.scheduler.submit(req)
        self._requests[req.request_id] = req
        return req

    # -- compilation ---------------------------------------------------------

    def warmup(self) -> None:
        """Compile (or exec-cache-load) both phase programs now, so the
        first request — and the bench's timed window — pays no XLA
        compile."""
        self._ensure_compiled()

    def _ensure_compiled(self) -> None:
        if self._decode_exec is not None:
            return
        from ..jit import exec_cache

        cfgv = self.config
        L, M, C = cfgv.max_lanes, self.blocks_per_lane, cfgv.prefill_chunk
        i32 = jnp.int32
        # donation halves pool HBM traffic; XLA:CPU can't donate these
        # and would warn per call. int8 mode donates the scale pools too
        # — they churn write-for-write with the K/V pools.
        donate = jax.default_backend() != "cpu"
        kw = {"static_argnames": ("cfg",)}
        if donate:
            kw["donate_argnums"] = (1, 2, 3, 4) if cfgv.kv_int8 \
                else (1, 2)
        pspec = jax.ShapeDtypeStruct(self._kpool.shape, self._kpool.dtype)
        sspec = None if self._kscale is None else \
            jax.ShapeDtypeStruct(self._kscale.shape, self._kscale.dtype)

        def key(kind, **extra):
            if not exec_cache.enabled():
                return None
            k = {"kind": kind, "gen_cfg": self._gcfg._key(),
                 "params": [exec_cache.array_spec(a) for a in
                            jax.tree_util.tree_leaves(self._params)],
                 "pool": (tuple(int(x) for x in self._kpool.shape),
                          str(self._kpool.dtype)),
                 "donate": donate,
                 "mesh": exec_cache.mesh_spec(), **extra}
            if cfgv.kv_int8:
                # the pool dtype above already splits int8 from bf16
                # entries; the explicit marker + scale spec make the
                # cache key self-describing (meta sidecar, audits)
                k["kv_int8"] = True
                k["scale"] = (tuple(int(x) for x in self._kscale.shape),
                              str(self._kscale.dtype))
            return k

        dkw = dict(kw)
        dkw["static_argnames"] = ("cfg", "paged", "paged_dead")
        dec = jax.jit(_decode_step, **dkw)
        self._decode_exec = exec_cache.get_or_compile(
            key("serving_decode", lanes=L, m=M,
                paged=self.paged_active, paged_dead=self._paged_dead),
            lambda: dec.lower(
                self._params, pspec, pspec, sspec, sspec,
                jax.ShapeDtypeStruct((L, M), i32),
                jax.ShapeDtypeStruct((L,), i32),
                jax.ShapeDtypeStruct((L,), i32), cfg=self._gcfg,
                paged=self.paged_active,
                paged_dead=self._paged_dead),
            label="serving/decode")
        pre = jax.jit(_prefill_chunk, **kw)
        scal = jax.ShapeDtypeStruct((), i32)
        self._prefill_exec = exec_cache.get_or_compile(
            key("serving_prefill", m=M, chunk=C),
            lambda: pre.lower(
                self._params, pspec, pspec, sspec, sspec,
                jax.ShapeDtypeStruct((1, M), i32),
                jax.ShapeDtypeStruct((1, C), i32),
                scal, scal, scal, cfg=self._gcfg),
            label="serving/prefill")
        if self.spec_active:
            S = self.config.spec_k + 1
            ver = jax.jit(_verify_step, **kw)
            self._verify_exec = exec_cache.get_or_compile(
                key("serving_verify", lanes=L, m=M, k=self.config.spec_k),
                lambda: ver.lower(
                    self._params, pspec, pspec, sspec, sspec,
                    jax.ShapeDtypeStruct((L, M), i32),
                    jax.ShapeDtypeStruct((L,), i32),
                    jax.ShapeDtypeStruct((L, S), i32),
                    jax.ShapeDtypeStruct((L,), i32), cfg=self._gcfg),
                label="serving/verify")

    # -- the step loop -------------------------------------------------------

    def step(self) -> bool:
        """One scheduling round: admit + prefill newly admitted lanes
        (they join this same round's decode — continuous batching), run
        the shared decode step, emit/reclaim. Returns whether any work
        was done. Admission is one lane at a time with the prefill (and
        its prefix publish) in between, so burst arrivals sharing a
        prompt hit the cache from the second lane on.

        On a raise (pool double-free, invariant break, a bad drafter)
        the blackbox postmortem is written BEFORE the error propagates
        — the artifact, not the traceback, is what holds the request
        journeys and scheduler state that explain the crash."""
        try:
            return self._step()
        except Exception as exc:
            _blackbox.maybe_dump(reason="serving_engine_raise",
                                 error=exc)
            raise

    def _step(self) -> bool:
        self._ensure_compiled()
        worked = False
        while True:
            admitted = self.scheduler.admit(limit=1)
            if not admitted:
                break
            req = admitted[0]
            worked = True
            self.counters["admits"] += 1
            m = _monitor
            if m is not None:
                now = time.perf_counter()
                m.on_serving_admit(
                    (now - req.t_submit) * 1e3 if req.t_submit else 0.0)
            self._prefill(req)
        if self.scheduler.has_running():
            self._decode_round()
            worked = True
        lv = _live
        if lv is not None:
            # one engine step = one live window: roll + SLO watchdog
            lv.on_engine_step()
        return worked

    def run(self) -> dict:
        """Drain: step until no request is waiting or running, then
        collect-and-RETIRE — returns ``{request_id: np.ndarray(generated
        tokens)}`` for every request finished since the last collection,
        after which the engine drops its reference (callers keep the
        :class:`Request` handles :meth:`submit` returned). Drivers that
        call :meth:`step` directly get the same contract from
        :meth:`pop_finished`."""
        while self.scheduler.has_work():
            self.step()
        return self.pop_finished()

    def pop_finished(self) -> dict:
        """Collect + retire finished requests (see :meth:`run`) —
        the bound that keeps a continuously-fed engine's host memory
        flat."""
        out = {rid: np.asarray(r.output)
               for rid, r in self._finished.items()}
        self._finished.clear()
        return out

    def has_work(self) -> bool:
        return self.scheduler.has_work()

    # -- phases --------------------------------------------------------------

    def _table_row(self, req) -> np.ndarray:
        row = np.zeros((1, self.blocks_per_lane), np.int32)
        row[0, :len(req.blocks)] = req.blocks
        return row

    def _prefill(self, req) -> None:
        """Fill the lane's blocks chunk by chunk — starting at
        ``cached_len``, the span already covered by acquired prefix-
        cache blocks (block-aligned, capped at ctx-1, so at least one
        chunk always runs and every write lands in a private block) —
        and greedy-sample the first token on the final chunk. Once the
        context is in the pool its full blocks are published to the
        prefix index (they are frozen now: decode writes only positions
        >= ctx). A re-admitted (preempted) request only rebuilds the
        pool — its pending token is already known, and greedy recompute
        reproduces the continuation exactly as long as the prefill and
        decode programs round K/V identically (proven token-identical
        on the CPU tier in tests/test_serving.py; the two programs fuse
        differently, so a TPU near-tie argmax flip is possible —
        hardware recompute-parity A/B queued in ROADMAP)."""
        toks = req.prefill_tokens
        ctx = int(toks.size)
        cached = int(req.cached_len)
        C = self.config.prefill_chunk
        table = jnp.asarray(self._table_row(req))
        sp = _spans
        p_t0 = req._t_mark  # admission stamped it just before this call
        nchunks = 0
        tok = None
        for start in range(cached, ctx, C):
            c_t0 = time.perf_counter() if sp is not None else 0.0
            piece = toks[start:start + C]
            chunk = np.zeros((1, C), np.int32)
            chunk[0, :piece.size] = piece
            last_idx = ctx - 1 - start if start + C >= ctx else 0
            (tok, self._kpool, self._vpool, self._kscale,
             self._vscale) = self._prefill_exec(
                self._params, self._kpool, self._vpool, self._kscale,
                self._vscale, table, jnp.asarray(chunk),
                jnp.int32(start), jnp.int32(ctx), jnp.int32(last_idx))
            nchunks += 1
            if sp is not None:
                # enqueue wall only (no per-chunk host sync — the one
                # sync per admission stays the first-token fetch below)
                sp.record("serving/prefill_chunk", "serving_prefill",
                          c_t0, time.perf_counter(),
                          lane=f"req/{req.trace_id}",
                          args={"request": req.request_id,
                                "start": start,
                                "tokens": min(C, ctx - start)})
        req.pool_len = ctx
        self.scheduler.publish_prefix(req)
        self.counters["prefill_chunks"] += nchunks
        self.counters["prefix_hit_tokens"] += cached
        self.counters["prefix_miss_tokens"] += ctx - cached
        if self.config.kv_int8:
            # quantize-on-write accounting: program launches that
            # quantized + the real (non-pad) tokens they wrote
            self.counters["kv_quant_writes"] += nchunks
            self.counters["kv_quant_tokens"] += ctx - cached
        m = _monitor
        if m is not None:
            m.on_serving_prefill(nchunks)
            pool = self.scheduler.pool
            m.on_serving_prefix(cached, ctx - cached,
                                pool.shared_count, pool.cold_count)
            if self.config.kv_int8:
                m.on_serving_kv_quant(nchunks, ctx - cached,
                                      self.kv_pool_bytes)
        # recompute-refund: cached tokens on a re-admission are context
        # the preemption forced us to rebuild but the prefix cache
        # served back for free
        refund = cached if req.output else 0
        req.prefill_refunded_tokens += refund
        first_tok = None
        if not req.output:
            first_tok = int(np.asarray(tok)[0])  # the TTFT host sync
        end = time.perf_counter()
        if p_t0 is not None:
            req.prefill_ms += (end - p_t0) * 1e3
            req._t_mark = end
        if sp is not None:
            sp.record("serving/prefill", "serving_prefill",
                      p_t0 if p_t0 is not None else end, end,
                      lane=f"req/{req.trace_id}",
                      args={"request": req.request_id, "chunks": nchunks,
                            "hit_tokens": cached,
                            "miss_tokens": ctx - cached,
                            "refunded_tokens": refund,
                            "recompute": bool(req.output)})
        if req.output:
            return  # recompute path: the pending token is output[-1]
        self._emit(req, first_tok, end)

    def _decode_round(self) -> None:
        sched = self.scheduler
        # growth walks FCFS order so older requests claim blocks first;
        # a victim preempted mid-walk is skipped by the state check
        for req in sched.running():
            if req.state == RUNNING:
                sched.ensure_capacity(req, on_preempt=self._note_preempt)
        act = sched.running()
        if not act:
            return
        drafts = self._draft(act) if self.spec_active else {}
        if any(d.size for d in drafts.values()):
            self._verify_round(act, drafts)
        else:
            # no lane proposed anything: today's [L, 1] decode program
            # (and the k=0 / spec-off path, byte for byte)
            self._plain_decode_round(act)

    def _draft(self, act) -> dict:
        """Per-lane draft proposals for this round, keyed by ``id(req)``
        — trimmed to the request's remaining-token budget (drafting the
        final token is pointless: its verification could emit past
        ``max_new_tokens``) and to the blocks the pool can back WITHOUT
        preempting anyone (`scheduler.grow_for_draft`): speculation is
        opportunistic, it never evicts a runner."""
        k = self.config.spec_k
        drafts = {}
        for req in act:
            cap = min(k, req.max_new_tokens - len(req.output) - 1)
            d = _EMPTY_DRAFT
            if cap > 0:
                ctx = np.concatenate(
                    [req.prompt, np.asarray(req.output, np.int32)])
                d = np.asarray(self.drafter.propose(ctx, cap),
                               np.int32).reshape(-1)[:cap]
                if d.size:
                    d = d[:self.scheduler.grow_for_draft(
                        req, int(d.size))]
            drafts[id(req)] = d
        return drafts

    def _verify_round(self, act, drafts) -> None:
        """One [L, k+1] verify step for every occupied lane: score the
        pending token + draft, accept each lane's longest prefix that
        matches the program's own greedy picks plus one bonus token.
        Rejected positions roll back by REWINDING ``pool_len`` only:
        their K/V sits above the lane's valid length in lane-private
        blocks (masked out of every later attend) until the next
        accepted write overwrites it."""
        L, M = self.config.max_lanes, self.blocks_per_lane
        K = self.config.spec_k
        tables = np.zeros((L, M), np.int32)
        cur = np.zeros((L,), np.int32)
        toks = np.zeros((L, K + 1), np.int32)
        wlim = np.zeros((L,), np.int32)
        for req in act:
            d = drafts.get(id(req), _EMPTY_DRAFT)
            tables[req.lane, :len(req.blocks)] = req.blocks
            cur[req.lane] = req.pool_len
            toks[req.lane, 0] = req.output[-1]
            if d.size:
                toks[req.lane, 1:1 + d.size] = d
            wlim[req.lane] = req.pool_len + 1 + d.size
        t0 = time.perf_counter()
        (pred, self._kpool, self._vpool, self._kscale,
         self._vscale) = self._verify_exec(
            self._params, self._kpool, self._vpool, self._kscale,
            self._vscale, jnp.asarray(tables), jnp.asarray(cur),
            jnp.asarray(toks), jnp.asarray(wlim))
        preds = np.asarray(pred)  # the round's ONE host sync
        now = time.perf_counter()
        c = self.counters
        c["decode_wall_s"] += now - t0
        c["verify_steps"] += 1
        proposed = accepted = bonus = emitted = 0
        for req in act:
            # attribution: everything since the lane's last phase
            # boundary (prefill end / previous round) is decode time
            if req._t_mark is not None:
                req.decode_ms += (now - req._t_mark) * 1e3
                req._t_mark = now
            d = drafts.get(id(req), _EMPTY_DRAFT)
            n = int(d.size)
            row = preds[req.lane]
            a = 0
            while a < n and row[a] == d[a]:
                a += 1
            proposed += n
            accepted += a
            if n:
                req.spec_rounds += 1
                req.accepted_tokens += a
            if n:  # optional feedback hook (Drafter.observe)
                observe = getattr(self.drafter, "observe", None)
                if observe is not None:
                    observe(d, a)
            # emit the a accepted drafts (== row[:a]) + the bonus token
            # row[a]; stop early when max_new_tokens/eos finishes the
            # request mid-prefix (the cap in _draft makes overshoot
            # impossible — a+1 <= remaining)
            got = 0
            for j in range(a + 1):
                req.pool_len += 1
                got += 1
                self._emit(req, int(row[j]), now)
                if req.finished:
                    break
            if n and got == a + 1:
                bonus += 1
            emitted += got
            # rejected-draft blocks go straight back to the pool
            # (no-op for finished lanes, whose blocks are already
            # freed): a failed speculation must leave no allocation
            # pressure behind to preempt someone later
            if req.state == RUNNING:
                self.scheduler.release_draft_blocks(req)
        c["decoded_tokens"] += emitted
        c["spec_proposed_tokens"] += proposed
        c["spec_accepted_tokens"] += accepted
        c["spec_bonus_tokens"] += bonus
        # byte-model inputs (see _plain_decode_round): a verify round
        # performs the DENSE gather regardless of the paged engagement
        # (s > 1 — no paged verify kernel exists), so both byte models
        # bill the full table here; the paged-vs-dense delta the bench
        # reports comes from plain decode rounds alone, which keeps the
        # "what the chip actually moves" readout honest for spec-on
        # paged engines
        dense_slots = len(act) * M * self.config.block_size
        c["kv_read_tokens"] += dense_slots
        c["kv_dense_read_tokens"] += dense_slots
        if self.config.kv_int8:
            # every non-pad write this round quantized: each lane's
            # pending token + its (possibly rejected) draft — rejected
            # positions still wrote int8+scale before the rewind
            c["kv_quant_writes"] += 1
            c["kv_quant_tokens"] += len(act) + proposed
        m = _monitor
        if m is not None:
            m.on_serving_verify(len(act), self.scheduler.pool.allocatable,
                                emitted)
            m.on_serving_spec(proposed, accepted, bonus)
            if self.config.kv_int8:
                m.on_serving_kv_quant(1, len(act) + proposed,
                                      self.kv_pool_bytes)
        lv = _live
        if lv is not None and proposed:
            lv.on_accept_rate(proposed, accepted)
        sp = _spans
        if sp is not None:
            # recorded COMPLETE, after rollbacks/releases settled — a
            # rewound pool_len can never leave an open round span
            sp.record("serving/verify_round", "serving_decode", t0, now,
                      lane="serve/rounds",
                      args={"lanes": len(act), "proposed": proposed,
                            "accepted": accepted, "bonus": bonus,
                            "emitted": emitted})

    def _plain_decode_round(self, act) -> None:
        L, M = self.config.max_lanes, self.blocks_per_lane
        tables = np.zeros((L, M), np.int32)
        cur = np.zeros((L,), np.int32)
        last = np.zeros((L,), np.int32)
        for req in act:
            tables[req.lane, :len(req.blocks)] = req.blocks
            cur[req.lane] = req.pool_len
            last[req.lane] = req.output[-1]
        t0 = time.perf_counter()
        (tok, self._kpool, self._vpool, self._kscale,
         self._vscale) = self._decode_exec(
            self._params, self._kpool, self._vpool, self._kscale,
            self._vscale, jnp.asarray(tables), jnp.asarray(cur),
            jnp.asarray(last))
        toks = np.asarray(tok)  # the round's ONE host sync
        now = time.perf_counter()
        c = self.counters
        c["decode_wall_s"] += now - t0
        c["decode_steps"] += 1
        c["decoded_tokens"] += len(act)
        # live-prefix KV slots the paged kernel reads this round vs the
        # full-table slots the dense gather reads — the roofline byte
        # model's inputs (benchmarks/serving_bench.py hbm_util delta)
        c["kv_read_tokens"] += sum(r.pool_len + 1 for r in act)
        c["kv_dense_read_tokens"] += len(act) * M * self.config.block_size
        if self.config.kv_int8:
            c["kv_quant_writes"] += 1
            c["kv_quant_tokens"] += len(act)
        m = _monitor
        if m is not None:
            # allocatable = free list + revivable cold LRU — the
            # pre-sharing meaning of "free" (cold blocks are spare
            # capacity, not occupancy)
            m.on_serving_decode(len(act), self.scheduler.pool.allocatable)
            if self.config.kv_int8:
                m.on_serving_kv_quant(1, len(act), self.kv_pool_bytes)
        sp = _spans
        if sp is not None:
            sp.record("serving/decode_round", "serving_decode", t0, now,
                      lane="serve/rounds",
                      args={"lanes": len(act), "emitted": len(act)})
        for req in act:
            if req._t_mark is not None:
                req.decode_ms += (now - req._t_mark) * 1e3
                req._t_mark = now
            req.pool_len += 1
            self._emit(req, int(toks[req.lane]), now)

    def _emit(self, req, tok: int, now: float) -> None:
        req.output.append(tok)
        if req.t_first is None:
            req.t_first = now
        if (len(req.output) >= req.max_new_tokens
                or (req.eos_token_id is not None
                    and tok == req.eos_token_id)):
            req.t_done = now
            self.scheduler.finish(req)
            self._finished[req.request_id] = \
                self._requests.pop(req.request_id, req)
            self._journeys.append({
                "request_id": req.request_id, "trace_id": req.trace_id,
                "tokens": len(req.output),
                "preemptions": req.preemptions,
                "total_ms": round((now - req.t_submit) * 1e3, 3)
                if req.t_submit is not None else None,
                **req.attribution()})
            self.counters["finished"] += 1
            m = _monitor
            if m is not None:
                m.on_serving_evict()
            lv = _live
            if lv is not None:
                # the always-on attribution stamps ARE the SLO feed —
                # no PT_MONITOR needed for live percentiles
                lv.on_request_finished(
                    (req.t_first - req.t_submit) * 1e3
                    if req.t_submit is not None else None,
                    (req.t_done - req.t_first) * 1e3
                    / (len(req.output) - 1)
                    if len(req.output) > 1 else None,
                    req.queue_ms)
            sp = _spans
            if sp is not None and req.t_submit is not None:
                # the whole journey as ONE span on the request's trace
                # lane, args carrying the attribution breakdown — what
                # monitor_report's "requests" section renders and what
                # survives ring eviction of the per-phase spans
                sp.record(
                    "serving/request", "serving_finish",
                    req.t_submit, now, lane=f"req/{req.trace_id}",
                    args={"request": req.request_id,
                          "trace_id": req.trace_id,
                          "tokens": len(req.output),
                          "preemptions": req.preemptions,
                          "total_ms": round(
                              (now - req.t_submit) * 1e3, 3),
                          "ttft_ms": round(
                              (req.t_first - req.t_submit) * 1e3, 3)
                          if req.t_first is not None else None,
                          **{k: round(v, 3) if isinstance(v, float)
                             else v
                             for k, v in req.attribution().items()}})

    def _note_preempt(self, req) -> None:
        self.counters["preemptions"] += 1
        m = _monitor
        if m is not None:
            m.on_serving_preempt()

    # -- introspection -------------------------------------------------------

    def _blackbox_state(self) -> dict:
        """State provider for the blackbox postmortem dump
        (``monitor/blackbox.py``): geometry, lifetime counters, the
        scheduler snapshot (queue/lanes/pool/events tail + every LIVE
        request's partial journey), and the newest finished journeys —
        enough to reconstruct what the engine was doing when it died.
        Read-only and exception-tolerant by contract (the dump swallows
        provider errors), so it never worsens a crash."""
        return {
            "config": {
                "max_lanes": self.config.max_lanes,
                "block_size": self.config.block_size,
                "num_blocks": self.scheduler.pool.num_blocks,
                "prefill_chunk": self.config.prefill_chunk,
                "max_seq_len": self.max_seq_len,
                "spec": self.spec_active,
                "spec_k": self.config.spec_k,
                "prefix_cache": self.config.prefix_cache,
                "paged": self.paged_active,
                "kv_int8": self.config.kv_int8,
            },
            "counters": dict(self.counters),
            "scheduler": self.scheduler.debug_state(),
            "finished_tail": list(self._journeys),
        }

    def stats(self) -> dict:
        """Plain-int account of the engine's lifetime (always on)."""
        out = dict(self.counters)
        out.update(
            decode_rounds=(self.counters["decode_steps"]
                           + self.counters["verify_steps"]),
            spec=self.spec_active,
            spec_k=self.config.spec_k if self.spec_active else 0,
            lanes=self.config.max_lanes,
            block_size=self.config.block_size,
            num_blocks=self.scheduler.pool.num_blocks,
            free_blocks=self.scheduler.pool.free_count,
            allocatable_blocks=self.scheduler.pool.allocatable,
            blocks_per_lane=self.blocks_per_lane,
            max_seq_len=self.max_seq_len,
            prefill_chunk=self.config.prefill_chunk,
            int8_weights=self.config.int8_weights,
            kv_int8=self.config.kv_int8,
            kv_pool_bytes=self.kv_pool_bytes,
            paged_attention=self.paged_active,
            paged_family=self._paged_family,
            paged_dead=self._paged_dead,
            prefix_cache=self.config.prefix_cache,
            shared_blocks=self.scheduler.pool.shared_count,
            cold_blocks=self.scheduler.pool.cold_count,
            indexed_blocks=self.scheduler.pool.indexed_count,
            lanes_occupied=self.scheduler.lanes_occupied,
            waiting=len(self.scheduler.waiting),
            requests=len(self._requests),
            uncollected=len(self._finished),
        )
        return out


_monitor_register(sys.modules[__name__])
