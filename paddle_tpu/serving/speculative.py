"""Speculative-decoding drafters for the serving engine (ROADMAP 3c).

Host-side, jax-free token proposal: a :class:`Drafter` looks at one
lane's known context (prompt + every generated token, the pending one
included) and proposes up to ``k`` continuation tokens. The engine then
scores all lanes' proposals in ONE compiled verify step
(``engine._verify_step``, shape ``[lanes, k+1]``) and accepts each
lane's longest prefix that matches the model's own greedy choices, plus
one bonus token — the greedy output stream is byte-identical to plain
decode (tests/test_serving_spec.py), only the number of decode rounds
changes.

The default drafter is **prompt-lookup n-gram matching** (the
draft-model-free scheme of arXiv:2304.04487 / vLLM's
``[ngram]`` speculator): the lane's most recent tokens are matched
against its own earlier context, and the tokens that followed the most
recent earlier occurrence become the draft. No extra weights, no device
work — repetition in the workload (code, quoted context, chatty list
output, a model settling into a loop) is the entire win condition.

Determinism contract: drafting feeds the scheduler's replayable event
stream, so a drafter must be a pure function of the tokens it is shown
— no RNG, no clocks, no hash()-ordered iteration. This module is in
``pt-lint``'s PTL005 byte-identity scope (docs/STATIC_ANALYSIS.md) to
keep it that way.

Monitor contract: carries a ``_monitor`` None-slot
(``monitor.INSTRUMENTED_MODULES``) — when monitoring is off no monitor
callable is ever invoked; ``serving/spec_draft_calls`` counts propose()
invocations (the engine itself accounts proposed/accepted/bonus tokens,
post-trim — see ``engine._verify_round``).
"""
from __future__ import annotations

import sys

import numpy as np

from ..monitor import _register as _monitor_register

__all__ = ["Drafter", "NgramDrafter"]

# telemetry slot (paddle_tpu.monitor None-slot contract): None unless
# PT_MONITOR wired it
_monitor = None

_EMPTY = np.zeros((0,), np.int32)


class Drafter:
    """Draft-proposal protocol: subclass (or duck-type) with
    :meth:`propose`. The slot a learned draft model would fill — the
    engine only ever calls this one method, host-side, between compiled
    steps, so a model-backed drafter just runs its own (cheap) forward
    here and returns tokens."""

    def propose(self, tokens: np.ndarray, k: int) -> np.ndarray:
        """Up to ``k`` proposed continuation tokens for a lane whose
        known context is ``tokens`` (1-D int array: prompt + generated,
        pending token last). Return an empty array to skip speculation
        for this lane this round. MUST be deterministic in ``tokens``
        (see module docstring)."""
        raise NotImplementedError

    def observe(self, tokens: np.ndarray, accepted: int) -> None:
        """Optional feedback hook: the engine reports how many of the
        last proposal's tokens were accepted. Default: ignore."""


class NgramDrafter(Drafter):
    """Prompt-lookup drafting: propose the continuation of the most
    recent earlier occurrence of the context's tail n-gram.

    Longest n-gram first (``max_ngram`` down to ``min_ngram``): a longer
    match is stronger evidence the context is repeating. Among equal
    n-grams the MOST RECENT earlier occurrence wins — locality beats
    antiquity, and "last match" is as deterministic as "first". Pure
    numpy over a few-hundred-token array: microseconds per lane, far
    under one decode round.
    """

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1):
        if not 1 <= min_ngram <= max_ngram:
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram, got "
                f"({min_ngram}, {max_ngram})")
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)

    def propose(self, tokens, k: int) -> np.ndarray:
        m = _monitor
        if m is not None:
            m.on_spec_draft_call()
        toks = np.ascontiguousarray(np.asarray(tokens, np.int32)
                                    .reshape(-1))
        n = int(toks.size)
        if k <= 0 or n < 2:
            return _EMPTY
        for ng in range(min(self.max_ngram, n - 1),
                        self.min_ngram - 1, -1):
            pattern = toks[n - ng:]
            # candidate starts 0..n-ng-1: every window that ends before
            # the tail n-gram itself, so a match always has at least one
            # following token to propose
            windows = np.lib.stride_tricks.sliding_window_view(
                toks, ng)[:n - ng]
            hits = np.nonzero((windows == pattern).all(axis=1))[0]
            if hits.size:
                start = int(hits[-1]) + ng  # most recent occurrence
                return toks[start:start + int(k)].copy()
        return _EMPTY


_monitor_register(sys.modules[__name__])
