"""Block (paged) KV-cache bookkeeping for the serving runtime.

The device side is two pool arrays ``[layers, num_blocks, block_size,
kv_heads, head_dim]`` owned by the engine; this module owns the HOST
side: which fixed-size blocks belong to which request, and the per-lane
block tables the compiled step indexes through. Sequences of different
lengths share ONE compiled decode program because length lives in the
*data* (block-table rows + per-lane valid lengths), never in the shapes
(the vLLM/PagedAttention memory model, applied to a gathered-read TPU
step — docs/SERVING.md).

Block 0 is the NULL block: never allocated, it absorbs the compiled
step's masked writes (inactive decode lanes, prefill-chunk pad slots)
so they can never corrupt a live lane's KV. Allocation hands out
blocks 1..num_blocks-1.

The pool is also a **prefix cache** (ROADMAP item 3a): a block holding
a full, frozen chunk of context can be *published* under a chained
content hash (:func:`prefix_keys`) and later *acquired* by another
request whose context starts with the same tokens — the two lanes'
block tables then point at the SAME pool block, and the second request
prefills nothing for it. Sharing is ref-counted: ``free`` decrements
instead of freeing, and a block whose refcount hits zero while it is
still indexed parks on a **cold LRU** — its device K/V stays valid
(nothing writes an unowned block), so a future lookup revives it for
free — and is reclaimed, index entry evicted, only when the free list
runs dry. Only full blocks are ever published; the tail block of every
lane stays private, so decode writes never touch shared KV and no
copy-on-write device copy is ever needed.

Safety contract: every block tracks its holders, ``free`` validates
membership (a double-free or cross-request free raises instead of
silently aliasing two requests' KV — the bug class paged caches die
of), reclaim never touches a block with refs > 0, and
``free + used + cold == capacity`` always holds, disjointly
(tests/test_serving.py asserts it across admission/preemption/sharing
churn; without publishing, cold is empty and the identity reduces to
the original ``free + used == capacity``).

The tail-block privacy rule (only FULL blocks are ever published) is
also what makes speculative decoding's rollback free: the verify step
(``engine._verify_step``) writes draft K/V at positions past
``pool_len`` — always in the lane's private tail blocks — so rejecting
a draft is a ``pool_len`` rewind with no copy and no shared-state
repair (docs/SERVING.md speculative section).

**int8 KV mode** (``ServingConfig(kv_int8=True)`` — docs/SERVING.md
"int8 KV"): the engine's pools store int8 K/V plus paired fp32 amax
scale tensors ``[layers, num_blocks, block_size, kv_heads]`` indexed by
the SAME block ids this ledger hands out — one scale per (position,
kv_head), null block included. Nothing here changes: a block id means
"these pool slots AND their scale slots", so sharing shares scales
(they are content-derived, quantized once at write), preemption frees
them, cold revival revives them, and every invariant above — refcounts,
double-free raises, ``free + used + cold == capacity`` — carries over
to int8 pools untouched (tests/test_serving_kv_int8.py proves it).
Rollback stays free for the same tail-privacy reason: rejected draft
scales sit past ``pool_len`` in private tail blocks and are simply
overwritten next write.
"""
from __future__ import annotations

import collections
import hashlib

import numpy as np

__all__ = ["BlockPool", "blocks_needed", "prefix_keys"]


def blocks_needed(num_tokens: int, block_size: int) -> int:
    """Blocks covering positions ``0..num_tokens-1`` (0 tokens -> 0)."""
    return -(-int(num_tokens) // int(block_size))


def prefix_keys(tokens, block_size: int, limit_tokens: int | None = None):
    """Chained content keys for the FULL blocks of ``tokens``: key ``i``
    is ``blake2b(key_{i-1} || tokens[i*B:(i+1)*B])`` — so a key names
    the entire context up to and including its block, and two requests
    share block ``i`` iff their first ``(i+1)*B`` tokens are identical.
    ``limit_tokens`` caps the keyed span (admission passes ``ctx - 1``
    so at least one token is always left to prefill — the compiled
    final-chunk sampling needs a real position, and its K/V write must
    land in a private block). blake2b is deterministic across processes
    (unlike ``hash()``), keeping seeded-trace replays byte-identical."""
    toks = np.ascontiguousarray(np.asarray(tokens, dtype=np.int32))
    n = toks.size if limit_tokens is None else min(toks.size,
                                                  int(limit_tokens))
    keys = []
    prev = b""
    for i in range(int(n) // int(block_size)):
        chunk = toks[i * block_size:(i + 1) * block_size]
        prev = hashlib.blake2b(prev + chunk.tobytes(),
                               digest_size=16).digest()
        keys.append(prev)
    return keys


class BlockPool:
    """Free-list allocator + ref-counted prefix index over the pooled KV
    blocks (host bookkeeping).

    LIFO free list: a just-freed block is the next handed out, so under
    admission/eviction churn the working set stays compact (warm for
    any future locality-aware layout). The cold LRU is FIFO over
    release order: the longest-unreferenced cached prefix is reclaimed
    first.
    """

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (block 0 is the reserved null "
                f"block), got {num_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        # stack: pop() yields 1 first, then 2, ... — deterministic
        # allocation order is part of the replayable-scheduler contract
        self._free = list(range(self.num_blocks - 1, 0, -1))
        # block -> holder list; refcount == len (a holder appears once)
        self._holders: dict[int, list] = {}
        # prefix index: chained content key -> block id, and its inverse
        self._index: dict[bytes, int] = {}
        self._key_of: dict[int, bytes] = {}
        # unreferenced-but-indexed blocks, oldest release first
        self._cold: collections.OrderedDict = collections.OrderedDict()

    @property
    def capacity(self) -> int:
        """Allocatable blocks (the null block excluded)."""
        return self.num_blocks - 1

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        return len(self._holders)

    @property
    def cold_count(self) -> int:
        """Unreferenced-but-indexed blocks parked on the cold LRU."""
        return len(self._cold)

    @property
    def allocatable(self) -> int:
        """Blocks an :meth:`alloc` can hand out right now: the free
        list plus the reclaimable cold LRU — the pre-sharing meaning of
        "free" (cold blocks are spare capacity wearing a cache hat)."""
        return len(self._free) + len(self._cold)

    @property
    def indexed_count(self) -> int:
        """Blocks (live or cold) reachable through the prefix index."""
        return len(self._index)

    @property
    def shared_count(self) -> int:
        """Live blocks currently held by more than one request."""
        return sum(1 for h in self._holders.values() if len(h) > 1)

    def refcount(self, block: int) -> int:
        return len(self._holders.get(block, ()))

    def alloc(self, n: int, owner, reclaim_cold: bool = True) -> \
            list | None:
        """Allocate ``n`` PRIVATE blocks for ``owner``; None when the
        pool cannot satisfy the request (caller decides to wait or
        preempt — allocation itself never evicts a lane). The free list
        serves first; when it runs dry, cold blocks are reclaimed
        oldest-release-first, their index entries evicted. Blocks with
        refs > 0 are never touched. ``reclaim_cold=False`` draws from
        the free list ONLY — speculative draft growth must never evict
        a cached prefix to back a guess
        (``scheduler.grow_for_draft``)."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > (self.allocatable if reclaim_cold else len(self._free)):
            return None
        blocks = []
        for _ in range(n):
            if self._free:
                b = self._free.pop()
            else:
                b, _ = self._cold.popitem(last=False)  # oldest cold
                self._evict_index(b)
            blocks.append(b)
        for b in blocks:
            self._holders[b] = [owner]
        return blocks

    def free(self, blocks, owner) -> None:
        """Release ``owner``'s reference on each of ``blocks``. Raises on
        a double-free, on a block the pool never allocated, and on an
        owner that holds no reference — each is a lost-KV/aliased-KV bug
        upstream, never recoverable here. A block whose last reference
        drops returns to the free list, unless it is indexed — then it
        parks on the cold LRU with its device K/V intact, revivable by
        the next prefix hit."""
        for b in blocks:
            holders = self._holders.get(b)
            if holders is None:
                raise ValueError(
                    f"block {b} is not allocated (double-free, or never "
                    f"allocated) — freeing it would let two requests "
                    f"alias one KV block")
            if not any(h is owner for h in holders):
                raise ValueError(
                    f"block {b} is owned by {holders!r}, not {owner!r}")
        for b in blocks:
            holders = self._holders[b]
            for i, h in enumerate(holders):
                if h is owner:
                    del holders[i]
                    break
            if holders:
                continue  # other requests still reference the block
            del self._holders[b]
            if b in self._key_of:
                self._cold[b] = None  # newest-released = last reclaimed
            else:
                self._free.append(b)

    # -- prefix cache --------------------------------------------------------

    def lookup(self, keys) -> list:
        """Block ids for the longest indexed prefix of ``keys`` (chain
        keys from :func:`prefix_keys`). Read-only: refcounts and LRU
        order are untouched until :meth:`acquire`."""
        hits = []
        for key in keys:
            b = self._index.get(key)
            if b is None:
                break
            hits.append(b)
        return hits

    def acquire(self, blocks, owner) -> None:
        """Take a reference on each of ``blocks`` for ``owner`` — live
        shared blocks gain a holder, cold blocks revive off the LRU.
        Raises on a block that is no longer indexed or neither live nor
        cold (a STALE lookup result: an intervening alloc reclaimed and
        re-issued it, so acquiring now would alias another request's
        KV — :meth:`lookup` hits must be acquired before any
        reclaiming alloc) and on an owner that already holds the
        block."""
        for b in blocks:
            holders = self._holders.get(b)
            if holders is not None and any(h is owner for h in holders):
                raise ValueError(
                    f"block {b} is already held by {owner!r}")
            if b not in self._key_of or (holders is None
                                         and b not in self._cold):
                raise ValueError(
                    f"block {b} is not an indexed live/cold block — "
                    f"acquire must follow lookup before any reclaiming "
                    f"alloc")
        for b in blocks:
            if b in self._cold:
                del self._cold[b]
                self._holders[b] = [owner]
            else:
                self._holders[b].append(owner)

    def publish(self, key: bytes, block: int, owner) -> bool:
        """Index ``block`` — full and frozen, every slot written — under
        its chain ``key``. ``owner`` must hold the block (publishing KV
        you don't own is the aliasing bug class again). First publisher
        wins: a key already mapped to a DIFFERENT block is left alone
        (the newcomer's copy stays private) so an indexed block's
        content never changes under its readers. Returns whether the
        block is now (or already was) the key's indexed block."""
        holders = self._holders.get(block)
        if holders is None or not any(h is owner for h in holders):
            raise ValueError(
                f"publish: block {block} is not held by {owner!r}")
        have_key = self._key_of.get(block)
        if have_key is not None:
            if have_key != key:
                raise ValueError(
                    f"publish: block {block} is already indexed under a "
                    f"different key — content-keyed blocks are immutable")
            return True
        if key in self._index:
            return self._index[key] == block
        self._index[key] = block
        self._key_of[block] = key
        return True

    def _evict_index(self, block: int) -> None:
        key = self._key_of.pop(block, None)
        if key is not None and self._index.get(key) == block:
            del self._index[key]

    # -- introspection -------------------------------------------------------

    def owner_of(self, block: int):
        holders = self._holders.get(block)
        return holders[0] if holders else None

    def check_invariant(self) -> None:
        """free + used + cold == capacity, disjointly — the accounting
        identity the property tests drive through admission/preemption/
        sharing churn — plus the prefix-index consistency rules (every
        cold block indexed, every index entry live-or-cold, index and
        its inverse in bijection)."""
        if (len(self._free) + len(self._holders)
                + len(self._cold)) != self.capacity:
            raise AssertionError(
                f"block accounting broken: free {len(self._free)} + used "
                f"{len(self._holders)} + cold {len(self._cold)} != "
                f"capacity {self.capacity}")
        free, used, cold = (set(self._free), set(self._holders),
                            set(self._cold))
        for a, b, what in ((free, used, "free and owned"),
                           (free, cold, "free and cold"),
                           (used, cold, "owned and cold")):
            if a & b:
                raise AssertionError(f"blocks both {what}: {a & b}")
        if 0 in used or 0 in free or 0 in cold:
            raise AssertionError("null block 0 escaped reservation")
        if cold - set(self._key_of):
            raise AssertionError(
                f"cold blocks without an index entry: "
                f"{cold - set(self._key_of)}")
        for key, b in self._index.items():
            if self._key_of.get(b) != key:
                raise AssertionError(
                    f"index/inverse disagree on block {b}")
            if b not in used and b not in cold:
                raise AssertionError(
                    f"index names block {b} that is neither live nor cold")
        if set(self._key_of) - set(self._index.values()):
            raise AssertionError("inverse index carries unindexed blocks")
        for b, holders in self._holders.items():
            if not holders:
                raise AssertionError(f"block {b} held with zero holders")
