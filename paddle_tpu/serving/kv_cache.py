"""Block (paged) KV-cache bookkeeping for the serving runtime.

The device side is two pool arrays ``[layers, num_blocks, block_size,
kv_heads, head_dim]`` owned by the engine; this module owns the HOST
side: which fixed-size blocks belong to which request, and the per-lane
block tables the compiled step indexes through. Sequences of different
lengths share ONE compiled decode program because length lives in the
*data* (block-table rows + per-lane valid lengths), never in the shapes
(the vLLM/PagedAttention memory model, applied to a gathered-read TPU
step — docs/SERVING.md).

Block 0 is the NULL block: never allocated, it absorbs the compiled
step's masked writes (inactive decode lanes, prefill-chunk pad slots)
so they can never corrupt a live lane's KV. Allocation hands out
blocks 1..num_blocks-1.

Safety contract: every block has at most one owner, ``free`` validates
ownership (a double-free or cross-request free raises instead of
silently aliasing two requests' KV — the bug class paged caches die of),
and ``free_count + live == num_blocks - 1`` always holds
(tests/test_serving.py asserts it across admission/preemption churn).
"""
from __future__ import annotations

__all__ = ["BlockPool", "blocks_needed"]


def blocks_needed(num_tokens: int, block_size: int) -> int:
    """Blocks covering positions ``0..num_tokens-1`` (0 tokens -> 0)."""
    return -(-int(num_tokens) // int(block_size))


class BlockPool:
    """Free-list allocator over the pooled KV blocks (host bookkeeping).

    LIFO free list: a just-freed block is the next handed out, so under
    admission/eviction churn the working set stays compact (warm for
    any future locality-aware layout).
    """

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (block 0 is the reserved null "
                f"block), got {num_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        # stack: pop() yields 1 first, then 2, ... — deterministic
        # allocation order is part of the replayable-scheduler contract
        self._free = list(range(self.num_blocks - 1, 0, -1))
        self._owner: dict[int, object] = {}

    @property
    def capacity(self) -> int:
        """Allocatable blocks (the null block excluded)."""
        return self.num_blocks - 1

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        return len(self._owner)

    def alloc(self, n: int, owner) -> list | None:
        """Allocate ``n`` blocks for ``owner``; None when the pool cannot
        satisfy the request (caller decides to wait or preempt —
        allocation itself never evicts)."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        blocks = [self._free.pop() for _ in range(n)]
        for b in blocks:
            self._owner[b] = owner
        return blocks

    def free(self, blocks, owner) -> None:
        """Return ``blocks`` to the pool. Raises on a double-free, on a
        block the pool never allocated, and on an owner mismatch — each
        is a lost-KV/aliased-KV bug upstream, never recoverable here."""
        for b in blocks:
            have = self._owner.get(b)
            if have is None:
                raise ValueError(
                    f"block {b} is not allocated (double-free, or never "
                    f"allocated) — freeing it would let two requests "
                    f"alias one KV block")
            if have is not owner:
                raise ValueError(
                    f"block {b} is owned by {have!r}, not {owner!r}")
        for b in blocks:
            del self._owner[b]
            self._free.append(b)

    def owner_of(self, block: int):
        return self._owner.get(block)

    def check_invariant(self) -> None:
        """free + used == capacity, disjointly — the accounting identity
        the property tests drive through admission/preemption churn."""
        if len(self._free) + len(self._owner) != self.capacity:
            raise AssertionError(
                f"block accounting broken: free {len(self._free)} + used "
                f"{len(self._owner)} != capacity {self.capacity}")
        overlap = set(self._free) & set(self._owner)
        if overlap:
            raise AssertionError(f"blocks both free and owned: {overlap}")
        if 0 in self._owner or 0 in self._free:
            raise AssertionError("null block 0 escaped reservation")
