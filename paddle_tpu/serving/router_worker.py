"""One router replica as a subprocess: the ``mode="worker"`` half of
:mod:`paddle_tpu.serving.router` (docs/SERVING.md "Replica router").

Protocol: JSON lines on stdin, one JSON reply line per request on the
ORIGINAL stdout (this process rebinds ``sys.stdout`` to stderr right
away, so jax/XLA chatter can never corrupt the pipe). Ops:

- ``{"op": "init", "factory": "module:callable", "config": {...}}`` —
  import ``module``, call ``callable()`` for the model, build a
  :class:`ServingEngine` with ``ServingConfig(**config)``.
- ``{"op": "submit", "request_id", "prompt", "max_new_tokens",
  "eos_token_id"}``
- ``{"op": "step"}`` -> ``{"ok", "worked", "finished": {rid: [tok]}}``
- ``{"op": "telemetry"}`` -> ``{"ok", "telemetry": {...}}`` — this
  process's CUMULATIVE monitor counter totals + live sketch state
  (``monitor.live.export_local``; the router installs
  ``PT_LIVE_TELEMETRY=1`` in the worker env when its own live plane is
  armed). Cumulative so the router's merge is idempotent and the
  fleet's ``/metrics`` equals in-process mode exactly.
- ``{"op": "warmup" | "stats" | "debug_state" | "shutdown"}``

Any op failure replies ``{"ok": false, "error": ...}``; the router
treats a failed ``step`` (or a dead pipe) as a replica death and
drains. A warm ``PT_EXEC_CACHE`` directory (inherited env) makes this
worker's start compile-free — the deployment shape of the router's
scale-out contract.
"""
from __future__ import annotations

import importlib
import json
import os
import sys


def _build_engine(factory: str, config_kwargs: dict):
    import numpy as np  # noqa: F401  — model factories usually need it

    from .engine import ServingConfig, ServingEngine

    mod_name, _, fn_name = factory.partition(":")
    if not mod_name or not fn_name:
        raise ValueError(
            f"worker factory must be 'module:callable', got {factory!r}")
    model = getattr(importlib.import_module(mod_name), fn_name)()
    return ServingEngine(model, ServingConfig(**config_kwargs))


def main(argv=None) -> int:
    # replies own the real stdout; everything else (jax init banners,
    # library prints) goes to stderr so the pipe stays pure JSON
    reply_out = sys.stdout
    sys.stdout = sys.stderr

    def reply(obj: dict) -> None:
        reply_out.write(json.dumps(obj) + "\n")
        reply_out.flush()

    engine = None
    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        try:
            msg = json.loads(line)
            op = msg.get("op")
            if op == "init":
                engine = _build_engine(msg["factory"],
                                       msg.get("config") or {})
                reply({"ok": True, "pid": os.getpid()})
            elif op == "submit":
                engine.submit(
                    msg["prompt"],
                    max_new_tokens=msg.get("max_new_tokens", 32),
                    eos_token_id=msg.get("eos_token_id"),
                    request_id=msg["request_id"])
                reply({"ok": True})
            elif op == "step":
                worked = engine.step() if engine.has_work() else False
                fins = {str(rid): [int(t) for t in toks]
                        for rid, toks in engine.pop_finished().items()}
                reply({"ok": True, "worked": worked, "finished": fins})
            elif op == "warmup":
                engine.warmup()
                reply({"ok": True})
            elif op == "telemetry":
                from ..monitor import live as _live_telemetry

                reply({"ok": True,
                       "telemetry": _live_telemetry.export_local()})
            elif op == "stats":
                reply({"ok": True, "stats": engine.stats()})
            elif op == "debug_state":
                reply({"ok": True,
                       "state": engine.scheduler.debug_state()})
            elif op == "shutdown":
                reply({"ok": True})
                return 0
            else:
                reply({"ok": False, "error": f"unknown op {op!r}"})
        except Exception as exc:  # noqa: BLE001 — the router decides
            reply({"ok": False, "error": f"{type(exc).__name__}: {exc}"})
    return 0


if __name__ == "__main__":
    sys.exit(main())
