"""Continuous-batching scheduler: FCFS admission, finished-lane
reclamation, recompute-on-preemption.

Pure host logic over :class:`~paddle_tpu.serving.kv_cache.BlockPool` —
no jax import, so the scheduling policy is property-testable at full
speed (tests/test_serving.py replays seeded traces twice and compares
the event logs byte-for-byte).

Policy (the Orca/vLLM iteration-level discipline, recompute variant):

- **Admission** is FCFS from the waiting deque: the head request is
  admitted iff a lane is free AND the pool can cover its context plus
  the first decode write — with the prefix cache on, the longest
  block-aligned indexed prefix is acquired (shared, ref-counted)
  instead of allocated, and the engine prefills only from
  ``cached_len`` on. Admission never preempts — runners hold their
  blocks until they finish or growth forces eviction.
- **Growth**: each decode step may cross a block boundary;
  :meth:`ensure_capacity` allocates the next block, and when the pool is
  dry it preempts the MOST RECENTLY admitted runner (never an older one
  — the oldest request always progresses, which is the no-starvation
  argument). Speculative draft positions grow through
  :meth:`grow_for_draft` instead, which NEVER preempts: a dry pool
  trims the draft, and :meth:`release_draft_blocks` returns the unused
  tail after every verify round — so speculation can only add
  throughput, never evict a runner or squat on capacity (the
  no-starvation argument is untouched). A preempted request keeps its generated tokens, frees its
  blocks, and re-queues at the FRONT of the waiting deque in arrival
  order; on re-admission the engine re-prefills prompt+output (greedy
  decode is deterministic per program, so recompute continues exactly —
  proven on the CPU tier; see ``engine._prefill`` for the TPU caveat).
- **Reclamation**: a finished lane frees its blocks and its lane slot
  the moment its last token is emitted; the next admit() fills it —
  lanes never idle behind a static batch's stragglers.

Every decision lands in ``self.events`` as ``(event, request_id,
detail)`` — the deterministic-replay audit trail (a bounded ring:
newest ``events_cap`` decisions, 65536 by default, so the trail never
grows a long-running server's host memory).

Monitor contract: this module carries ``_monitor``/``_spans``
None-slots (``monitor.INSTRUMENTED_MODULES``) — with monitoring off no
monitor callable or span record ever runs here; with ``PT_MONITOR=1``
admission records each request's queue/requeue wait and preemption as
flight-recorder spans on the request's trace lane (``req/<trace_id>``;
docs/OBSERVABILITY.md). The per-request latency attribution
(``Request.queue_ms``/...) is ALWAYS on, like the engine's plain-int
counters — it costs one ``perf_counter`` read per admission and per
preemption, never a monitor call. The event ring stays byte-identical
either way — spans and attribution are observations, never decisions.
"""
from __future__ import annotations

import collections
import itertools
import sys
import time

import numpy as np

from ..monitor import _register as _monitor_register
from .kv_cache import BlockPool, blocks_needed, prefix_keys

__all__ = ["Request", "FCFSScheduler",
           "WAITING", "RUNNING", "FINISHED"]

# telemetry slots (paddle_tpu.monitor None-slot contract): None unless
# PT_MONITOR wired them
_monitor = None
_spans = None

WAITING = "waiting"
RUNNING = "running"
FINISHED = "finished"

_auto_id = itertools.count()


class Request:
    """One generation request and its full lifecycle state.

    ``output`` accumulates generated token ids (the LAST entry, while
    running, is the *pending* token — sampled but not yet written to the
    KV pool; the engine feeds it to the next decode step). ``blocks``
    is the lane's block table in position order. Timestamps
    (``t_submit``/``t_first``/``t_done``, engine clock seconds) carry
    the TTFT / per-token-latency facts the serving bench reports.

    Attribution (always on, plain float/int arithmetic like the
    engine's counters): the engine telescopes every request's wall
    time into ``queue_ms`` (submit -> first admission), ``prefill_ms``,
    ``decode_ms`` (on-lane time between prefill end and finish, incl.
    host scheduling between rounds), and ``preempted_ms`` (preempt ->
    re-admission), advancing ``_t_mark`` at each phase boundary — the
    four buckets sum to ``t_done - t_submit`` exactly, which is the
    serving bench's ``attribution`` sub-object contract. ``trace_id``
    is assigned at first admission and names the request's span lane
    (``req/<trace_id>``) in the flight recorder.
    """

    __slots__ = ("request_id", "prompt", "max_new_tokens", "eos_token_id",
                 "state", "output", "blocks", "lane", "pool_len",
                 "cached_len", "prefix_cached_tokens",
                 "ttft_cached_tokens", "_pkeys",
                 "t_submit", "t_first", "t_done", "preemptions",
                 "_admit_seq", "trace_id", "_t_mark",
                 "queue_ms", "prefill_ms", "decode_ms", "preempted_ms",
                 "prefill_refunded_tokens", "spec_rounds",
                 "accepted_tokens")

    def __init__(self, prompt_ids, max_new_tokens=32, eos_token_id=None,
                 request_id=None):
        prompt = np.asarray(prompt_ids, dtype=np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must hold at least one token")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        self.request_id = (request_id if request_id is not None
                           else next(_auto_id))
        self.prompt = prompt
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = (None if eos_token_id is None
                             else int(eos_token_id))
        self.state = WAITING
        self.output: list = []
        self.blocks: list = []
        self.lane = None
        # tokens whose K/V sit in the pool (= prefilled context while
        # running; the pending output token is NOT yet written)
        self.pool_len = 0
        # leading tokens covered by acquired prefix-cache blocks at the
        # CURRENT admission (prefill starts here; reset on preemption)
        self.cached_len = 0
        # lifetime cache credit across (re-)admissions (stats), and the
        # FIRST admission's credit alone — the admission whose prefill
        # sets t_first, so the serving bench's cached-vs-cold TTFT A/B
        # groups by it (a later recompute hit must not relabel a
        # cold-TTFT request as cached)
        self.prefix_cached_tokens = 0
        self.ttft_cached_tokens = None
        # chain-key cache for the current prefill context (ctx, keys):
        # a blocked admission retries every engine step, and rehashing
        # a long context per retry is pure repeated work. ctx alone
        # keys the cache — prefill_tokens only ever grows (recompute
        # appends kept output), so equal length implies equal content.
        self._pkeys = None
        self.t_submit = None
        self.t_first = None
        self.t_done = None
        self.preemptions = 0
        self._admit_seq = -1
        # per-request latency attribution (see class docstring): the
        # engine advances _t_mark at every phase boundary so the four
        # *_ms buckets telescope to exactly t_done - t_submit
        self.trace_id = None
        self._t_mark = None
        self.queue_ms = 0.0
        self.prefill_ms = 0.0
        self.decode_ms = 0.0
        self.preempted_ms = 0.0
        # recomputed-context tokens a re-admission's prefix-cache hit
        # refunded (served from shared blocks instead of re-prefilled)
        self.prefill_refunded_tokens = 0
        self.spec_rounds = 0
        self.accepted_tokens = 0

    def attribution(self) -> dict:
        """The finished request's latency breakdown — the serving
        bench's per-request record and the blackbox dump's journey
        entry. Phase buckets are ms on the engine clock; for a FINISHED
        request they sum to ``t_done - t_submit`` (within float
        rounding), the property the bench's ``attribution`` sub-object
        is judged on."""
        return {
            "queue_ms": self.queue_ms,
            "prefill_ms": self.prefill_ms,
            "decode_ms": self.decode_ms,
            "preempted_ms": self.preempted_ms,
            "prefill_refunded_tokens": self.prefill_refunded_tokens,
            "spec_rounds": self.spec_rounds,
            "accepted_tokens": self.accepted_tokens,
        }

    @property
    def prefill_tokens(self) -> np.ndarray:
        """The context a (re-)prefill must write to the pool: the prompt
        plus all generated tokens EXCEPT the pending last one."""
        if not self.output:
            return self.prompt
        return np.concatenate(
            [self.prompt, np.asarray(self.output[:-1], np.int32)])

    @property
    def finished(self) -> bool:
        return self.state == FINISHED


class FCFSScheduler:
    """Lane + block assignment between steps; see module docstring."""

    def __init__(self, pool: BlockPool, max_lanes: int,
                 blocks_per_lane: int, max_seq_len: int,
                 events_cap: int = 65536, prefix_cache: bool = True):
        if max_lanes < 1:
            raise ValueError(f"max_lanes must be >= 1, got {max_lanes}")
        self.pool = pool
        # prefix-cache policy switch (PT_SERVE_PREFIX_CACHE via
        # ServingConfig): off = the pre-sharing admission path, byte for
        # byte — no lookups, no publishes, cold LRU stays empty
        self.prefix_cache = bool(prefix_cache)
        self.max_lanes = int(max_lanes)
        self.blocks_per_lane = int(blocks_per_lane)
        self.max_seq_len = int(max_seq_len)
        self.waiting: collections.deque = collections.deque()
        self.lanes: list = [None] * self.max_lanes
        # audit trail as a bounded ring (the flight-recorder discipline):
        # newest events_cap decisions kept, so a long-running server's
        # host memory does not grow with its request history
        self.events: collections.deque = collections.deque(
            maxlen=events_cap)
        self._admit_counter = itertools.count()

    # -- intake --------------------------------------------------------------

    def submit(self, req: Request) -> Request:
        """Queue a request; validates it can EVER run (total length within
        the lane's block table and the pool) so an impossible request
        fails loudly at the door, not as a livelock mid-serve."""
        total = int(req.prompt.size) + req.max_new_tokens
        if total > self.max_seq_len:
            raise ValueError(
                f"request {req.request_id}: prompt {req.prompt.size} + "
                f"max_new_tokens {req.max_new_tokens} = {total} exceeds "
                f"max_seq_len {self.max_seq_len}")
        need = blocks_needed(total, self.pool.block_size)
        if need > min(self.pool.capacity, self.blocks_per_lane):
            raise ValueError(
                f"request {req.request_id} needs {need} KV blocks but the "
                f"pool holds {self.pool.capacity} "
                f"({self.blocks_per_lane}/lane) — raise PT_SERVE_BLOCKS "
                f"or shrink the request")
        req.state = WAITING
        self.waiting.append(req)
        self.events.append(("submit", req.request_id, None))
        return req

    # -- admission -----------------------------------------------------------

    def free_lane(self):
        for i, r in enumerate(self.lanes):
            if r is None:
                return i
        return None

    def admit(self, limit: int | None = None) -> list:
        """FCFS: move waiting-head requests onto free lanes while blocks
        cover each one's context + first decode write. With the prefix
        cache on, the head request's longest block-aligned indexed
        prefix is acquired (ref-counted, possibly reviving cold blocks)
        and only the remainder is privately allocated — the engine's
        prefill then starts at ``cached_len``. Returns the newly
        admitted requests (engine prefills them before the next decode
        round). The engine passes ``limit=1`` and prefills+publishes
        between admissions, so a BURST of same-prompt arrivals shares
        from the second request on — admitting a whole wave first would
        privately allocate every lane's copy before any prefix was
        published."""
        admitted = []
        while self.waiting and (limit is None or len(admitted) < limit):
            lane = self.free_lane()
            if lane is None:
                break
            req = self.waiting[0]
            ctx = len(req.prefill_tokens)
            hits = []
            if self.prefix_cache:
                # cap at ctx-1: at least one token always prefills, so
                # the final-chunk sampling position and its K/V write
                # stay in a lane-private block (no shared-block writes)
                hits = self.pool.lookup(self._chain_keys(req)[
                    :(ctx - 1) // self.pool.block_size])
            # context to prefill + the first decode write right after it
            need = blocks_needed(ctx + 1, self.pool.block_size)
            # acquire the hits FIRST: a cold hit revived here can no
            # longer be reclaimed by the private alloc below
            self.pool.acquire(hits, req)
            blocks = self.pool.alloc(need - len(hits), req)
            if blocks is None:
                self.pool.free(hits, req)  # back to the cold LRU
                break  # runners will free blocks as they finish
            self.waiting.popleft()
            req.blocks = hits + blocks
            req.lane = lane
            req.state = RUNNING
            req.pool_len = 0  # set by the engine's prefill
            req.cached_len = len(hits) * self.pool.block_size
            req.prefix_cached_tokens += req.cached_len
            if req.ttft_cached_tokens is None:  # first admission
                req.ttft_cached_tokens = req.cached_len
            req._admit_seq = next(self._admit_counter)
            if req.trace_id is None:  # one trace id per request lifetime
                req.trace_id = f"r{req.request_id}"
            self.lanes[lane] = req
            self.events.append(("admit", req.request_id, lane))
            if hits:
                self.events.append(
                    ("prefix_hit", req.request_id, req.cached_len))
            # latency attribution (always on; engine stamps _t_mark at
            # submit and preempt): the wait that just ended is queue
            # time on a first admission, preempted time on a requeue
            if req._t_mark is not None:
                now = time.perf_counter()
                t_wait0 = req._t_mark
                wait_ms = (now - t_wait0) * 1e3
                if req.preemptions:
                    req.preempted_ms += wait_ms
                else:
                    req.queue_ms += wait_ms
                req._t_mark = now
                sp = _spans
                if sp is not None:
                    sp.record(
                        "serving/requeue_wait" if req.preemptions
                        else "serving/queue_wait",
                        "serving_queue", t_wait0, now,
                        lane=f"req/{req.trace_id}",
                        args={"request": req.request_id, "lane": lane,
                              "wait_ms": round(wait_ms, 3),
                              "preemptions": req.preemptions,
                              "cached_tokens": req.cached_len})
            admitted.append(req)
        return admitted

    def _chain_keys(self, req: Request) -> list:
        """``prefix_keys`` over the request's CURRENT prefill context,
        memoized on the request (see ``Request._pkeys``): a blocked
        admission retrying every step, and the post-prefill publish,
        reuse one hash pass instead of rehashing per call."""
        ctx = len(req.prefill_tokens)
        if req._pkeys is None or req._pkeys[0] != ctx:
            req._pkeys = (ctx, prefix_keys(req.prefill_tokens,
                                           self.pool.block_size))
        return req._pkeys[1]

    def publish_prefix(self, req: Request) -> None:
        """Index ``req``'s full, frozen context blocks (engine calls
        this AFTER the lane's prefill wrote their K/V — publishing
        earlier would let a same-round admission read unwritten
        blocks). Blocks that arrived via the prefix cache re-publish as
        no-ops (same chain key, same block); on a key another lane
        published first, this lane's copy just stays private."""
        if not self.prefix_cache:
            return
        for i, key in enumerate(self._chain_keys(req)):
            self.pool.publish(key, req.blocks[i], req)

    # -- growth / preemption -------------------------------------------------

    def running(self) -> list:
        """Active requests in admission (FCFS) order — the order
        ensure_capacity must walk so older requests grab blocks first."""
        return sorted((r for r in self.lanes if r is not None),
                      key=lambda r: r._admit_seq)

    def ensure_capacity(self, req: Request, on_preempt=None) -> bool:
        """Grow ``req.blocks`` to cover its next decode write (position
        ``pool_len``). When the pool is dry, preempt the newest runner —
        possibly ``req`` itself when IT is the newest. Returns False iff
        ``req`` was preempted (caller drops it from this round)."""
        need = blocks_needed(req.pool_len + 1, self.pool.block_size)
        while len(req.blocks) < need:
            got = self.pool.alloc(need - len(req.blocks), req)
            if got is not None:
                req.blocks.extend(got)
                return True
            victims = [r for r in self.running() if r is not req]
            if victims and victims[-1]._admit_seq > req._admit_seq:
                self.preempt(victims[-1], on_preempt)
            else:
                self.preempt(req, on_preempt)
                return False
        return True

    def grow_for_draft(self, req: Request, n: int) -> int:
        """Best-effort block growth for ``n`` speculative draft
        positions beyond the next decode write (which
        :meth:`ensure_capacity` already covered). Returns how many
        draft positions are actually backed (0..n) after clamping to
        the lane's table / ``max_seq_len`` ceiling and to what the
        FREE LIST can hand out RIGHT NOW: speculation is opportunistic,
        so unlike ensure_capacity this never preempts a runner (a dry
        pool just trims the draft) and never reclaims a cold cached
        prefix (``reclaim_cold=False`` — evicting an index entry to
        back a guess would trade real prefill savings for speculative
        ones). The engine returns the unused tail via
        :meth:`release_draft_blocks` after every verify round. Engine
        calls walk requests in FCFS order, so older lanes claim draft
        headroom first — deterministic, like every other allocation
        decision."""
        if n <= 0:
            return 0
        bs = self.pool.block_size
        ceiling = min(self.blocks_per_lane * bs, self.max_seq_len)
        n = min(n, ceiling - (req.pool_len + 1))
        if n <= 0:
            return 0
        need = blocks_needed(req.pool_len + 1 + n, bs)
        grown = 0
        while len(req.blocks) < need:
            # free list only: a draft must never reclaim a COLD cached
            # prefix (evicting its index entry forever) to back a guess
            got = self.pool.alloc(1, req, reclaim_cold=False)
            if got is None:
                break
            req.blocks.extend(got)
            grown += 1
        if grown:
            self.events.append(("draft_grow", req.request_id, grown))
        return max(0, min(n, len(req.blocks) * bs - req.pool_len - 1))

    def release_draft_blocks(self, req: Request) -> int:
        """Return a lane's unused speculative tail blocks — anything
        past the next decode write — to the pool. The engine calls this
        after a verify round rewound ``pool_len`` past rejected drafts,
        which is what makes :meth:`grow_for_draft`'s no-harm contract
        real: a rejected draft leaves NO allocation pressure behind, so
        speculation can never cause a preemption plain decode wouldn't
        have. Tail blocks past the context are always lane-private
        (publish covers only full context blocks), so the free is a
        plain refcount-1 release. Returns the blocks freed."""
        need = blocks_needed(req.pool_len + 1, self.pool.block_size)
        extra = req.blocks[need:]
        if extra:
            self.pool.free(extra, req)
            del req.blocks[need:]
            self.events.append(
                ("draft_release", req.request_id, len(extra)))
        return len(extra)

    def preempt(self, req: Request, on_preempt=None) -> None:
        """Evict a runner: free its blocks, requeue at the waiting FRONT
        (it was admitted before everything behind it — FCFS is preserved
        because victims are always the newest runners, and multiple
        same-round victims re-enter newest-first, so appendleft restores
        arrival order)."""
        freed = len(req.blocks)
        self.pool.free(req.blocks, req)
        req.blocks = []
        lane = req.lane
        self.lanes[req.lane] = None
        req.lane = None
        req.pool_len = 0
        req.cached_len = 0
        req.state = WAITING
        req.preemptions += 1
        self.waiting.appendleft(req)
        self.events.append(("preempt", req.request_id, None))
        # attribution: on-lane time up to the eviction bills to decode
        # (the request was holding a lane); the preempt -> re-admission
        # wait that starts NOW bills to preempted_ms at the next admit
        if req._t_mark is not None:
            now = time.perf_counter()
            req.decode_ms += (now - req._t_mark) * 1e3
            req._t_mark = now
            sp = _spans
            if sp is not None:  # zero-length marker on the trace lane
                sp.record("serving/preempt", "serving_sched", now, now,
                          lane=f"req/{req.trace_id}",
                          args={"request": req.request_id, "lane": lane,
                                "blocks_freed": freed,
                                "preemptions": req.preemptions,
                                "kept_tokens": len(req.output)})
        if on_preempt is not None:
            on_preempt(req)

    # -- reclamation ---------------------------------------------------------

    def finish(self, req: Request) -> None:
        """Reclaim a finished lane: KV blocks and the lane slot return to
        the pool immediately (the eviction the admission loop feeds on)."""
        self.pool.free(req.blocks, req)
        req.blocks = []
        self.lanes[req.lane] = None
        req.lane = None
        req.state = FINISHED
        self.events.append(("finish", req.request_id, None))

    # -- state ---------------------------------------------------------------

    def has_running(self) -> bool:
        return any(r is not None for r in self.lanes)

    def has_work(self) -> bool:
        return bool(self.waiting) or self.has_running()

    @property
    def lanes_occupied(self) -> int:
        return sum(1 for r in self.lanes if r is not None)

    def debug_state(self) -> dict:
        """JSON-able scheduler snapshot for the blackbox postmortem
        dump (``monitor/blackbox.py``): queue/lane occupancy, pool
        accounting, the newest audit-trail events, and every live
        request's (possibly partial) journey. Read-only."""
        pool = self.pool
        return {
            "waiting": [r.request_id for r in self.waiting],
            "lanes": [None if r is None else r.request_id
                      for r in self.lanes],
            "pool": {"capacity": pool.capacity,
                     "free": pool.free_count, "used": pool.used_count,
                     "cold": pool.cold_count,
                     "shared": pool.shared_count,
                     "indexed": pool.indexed_count},
            "events_tail": [list(e) for e in
                            list(self.events)[-64:]],
            "requests": [{
                "request_id": r.request_id, "trace_id": r.trace_id,
                "state": r.state, "lane": r.lane,
                "pool_len": r.pool_len, "cached_len": r.cached_len,
                "tokens": len(r.output),
                "preemptions": r.preemptions,
                **r.attribution(),
            } for r in sorted(
                set(self.waiting)
                | {r for r in self.lanes if r is not None},
                key=lambda r: str(r.request_id))],
        }


_monitor_register(sys.modules[__name__])
