"""Continuous-batching decode serving runtime (docs/SERVING.md).

Composes the piecemeal serving levers — weight-only int8
(``PT_DECODE_INT8``), the compiled KV-cache decode loop
(``models/generation.py``), the AOT exec cache (``jit/exec_cache.py``)
— into one request-level engine: block/paged KV cache, FCFS continuous
batching with preemption, chunked prefill + shared decode step.

    from paddle_tpu.serving import ServingEngine, ServingConfig

    engine = ServingEngine(model, ServingConfig(max_lanes=8))
    req = engine.submit(prompt_ids, max_new_tokens=64)
    outputs = engine.run()   # {request_id: generated token ids}

Benchmark: ``python benchmarks/serving_bench.py [--smoke]`` replays a
seeded Poisson arrival trace and reports tokens/s + p50/p99 TTFT.
"""
from .engine import ServingConfig, ServingEngine  # noqa: F401
from .kv_cache import BlockPool, blocks_needed, prefix_keys  # noqa: F401
from .router import RouterConfig, RouterEngine  # noqa: F401
from .scheduler import (  # noqa: F401
    FINISHED, RUNNING, WAITING, FCFSScheduler, Request,
)
from .speculative import Drafter, NgramDrafter  # noqa: F401

__all__ = [
    "ServingConfig", "ServingEngine", "BlockPool", "blocks_needed",
    "prefix_keys", "FCFSScheduler", "Request", "WAITING", "RUNNING",
    "FINISHED", "Drafter", "NgramDrafter", "RouterConfig",
    "RouterEngine",
]
