"""Measurement-driven kernel search harness (ROADMAP item 3).

``autotune.py`` tunes ONE family (flash block sizes). This module is the
general harness grown out of it, in the spirit of automatic kernel
generation (PAPERS.md: 2006.12645) and learned tuning (CUDA-L2,
2512.02551), at Pallas scale:

- **Declarative candidate spaces**: each kernel family registers a
  :class:`KernelFamily` describing its search shapes, its candidate
  configurations (block sizes, grid layouts, variant flags — with
  family-owned pruning, e.g. a VMEM-budget bound), how to build a
  runnable kernel for a (shape, config) pair, and the XLA-composite
  baseline it must beat.
- **Mandatory parity pre-filter**: every candidate runs in CPU
  interpret mode against the composite BEFORE it is ever timed — a
  config that cannot reproduce the math is rejected, never measured
  (``search/rejects``), so a fast-but-wrong tiling cannot win.
- **The timing discipline**: candidates are timed with
  ``autotune._time_compiled`` — two compiled fori_loops of different
  lengths with a REAL data dependence, difference-divided so the
  ~70-95 ms tunnel sync cancels (CLAUDE.md timing rules).
- **One persisted tune table** (``kernel_tune.json`` next to this
  module): per-family namespaces, device + commit provenance on every
  row, fcntl-locked read-modify-write with atomic tmp/rename
  (``utils/measurements.py`` discipline — the old ``flash_tune.json``
  writer could tear under concurrent hwbench/autotune writers).
  Legacy ``flash_tune.json`` entries are migrated in through a
  one-shot loader fallback (:func:`load_table` merges them under the
  ``flash`` namespace).
- **Engagement = measured-faster-than-composite only**: a kernel
  engages for a shape exactly when a HARDWARE row at that exact key
  says ratio > 1.0 (CPU/interpret rows never engage — their
  wall-clock is meaningless). No row → the caller's default path.

Monitor contract: this module carries a ``_monitor`` None-slot
(``pallas/engaged``, ``pallas/fallback_composite``, ``search/*`` —
``monitor.INSTRUMENTED_MODULES``); when monitoring is off no monitor
callable is ever invoked.
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
import time
from typing import Any, Dict, Iterable, List, Optional

__all__ = [
    "KernelFamily", "register_family", "FAMILIES",
    "table_path", "load_table", "save_table", "update_table",
    "family_entries", "lookup", "best_config", "engaged", "decide",
    "search_family", "search_shape",
]

_ENV_PATH = "PT_KERNEL_TUNE_PATH"

# telemetry slot (paddle_tpu.monitor None-slot contract): None unless
# PT_MONITOR wired it
_monitor = None

FAMILIES: Dict[str, "KernelFamily"] = {}


def register_family(family: "KernelFamily") -> "KernelFamily":
    """Register a kernel family under ``family.name`` (idempotent by
    name: re-import replaces)."""
    FAMILIES[family.name] = family
    return family


class KernelFamily:
    """One searchable kernel family. Subclasses declare the candidate
    space and how to build/verify/compare; the harness owns enumeration,
    the parity pre-filter, timing, and persistence."""

    #: tune-table namespace + monitor label
    name = "family"
    #: time fwd+bwd (training kernels) rather than fwd only (decode)
    grad = False
    #: interpret-mode parity tolerance vs the composite (fp32 inputs)
    parity_atol = 2e-5

    def shapes(self) -> List[Any]:
        """The standard search shapes (hardware run)."""
        return []

    def smoke_shapes(self) -> List[Any]:
        """Tiny shapes for the CPU interpret-mode smoke pipeline."""
        return self.shapes()

    def key(self, shape) -> str:
        """Tune-table key for ``shape`` — exact-match engagement rides
        on it, so it must encode every engagement-relevant parameter."""
        raise NotImplementedError

    def shape_info(self, shape) -> Dict[str, Any]:
        """Human-readable shape fields for the persisted row."""
        return {"shape": list(shape) if isinstance(shape, tuple)
                else shape}

    def candidates(self, shape) -> Iterable[Dict[str, Any]]:
        """Candidate configurations for ``shape`` (already pruned by
        family-owned feasibility rules, e.g. VMEM budget)."""
        raise NotImplementedError

    def make_inputs(self, shape):
        """Deterministic input arrays for parity + timing."""
        raise NotImplementedError

    def build(self, shape, config, interpret: bool):
        """A callable ``fn(*make_inputs(shape))`` running the kernel at
        ``config``."""
        raise NotImplementedError

    def build_composite(self, shape):
        """The XLA-composite baseline ``fn(*make_inputs(shape))`` the
        family must measure faster than to engage."""
        raise NotImplementedError


# -- unified tune table -------------------------------------------------------

_table_cache: Optional[Dict[str, Any]] = None


def table_path() -> str:
    override = os.environ.get(_ENV_PATH)
    if override:
        return override
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "kernel_tune.json")


def _store_lock(path: str):
    """The fcntl sidecar lock from utils/measurements.py — one
    discipline for every persisted measurement artifact."""
    from ...utils.measurements import _StoreLock

    return _StoreLock(path)


def _read_disk(path: str) -> Dict[str, Any]:
    try:
        with open(path) as f:
            data = json.load(f)
        if isinstance(data, dict) and isinstance(data.get("families"),
                                                 dict):
            return data
    except (OSError, ValueError):
        pass
    return {"families": {}}


def _atomic_write(path: str, data: Dict[str, Any]) -> None:
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".",
                               prefix=".kernel_tune_", suffix=".json")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(data, f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _migrate_flash(data: Dict[str, Any]) -> Dict[str, Any]:
    """One-shot loader fallback: legacy ``flash_tune.json`` rows appear
    under the ``flash`` namespace (unified rows win on key collision).
    Purely additive and in-memory — the merged view persists the next
    time the table is saved."""
    try:
        from . import autotune

        legacy = autotune.load_cache().get("entries", {})
    except Exception:  # noqa: BLE001 — a broken legacy cache must not
        return data  # poison the unified table
    if not legacy:
        return data
    fam = data.setdefault("families", {}).setdefault(
        "flash", {"entries": {}})
    for key, e in legacy.items():
        if key in fam["entries"]:
            continue
        row = dict(e)
        row.setdefault("migrated_from", "flash_tune.json")
        if "ratio_fwd_bwd" in row:
            row.setdefault("ratio", row["ratio_fwd_bwd"])
        if "block_q" in row:
            row.setdefault("config", {"block_q": row["block_q"],
                                      "block_k": row.get("block_k")})
        fam["entries"][key] = row
    return data


def load_table(refresh: bool = False) -> Dict[str, Any]:
    global _table_cache
    if _table_cache is None or refresh:
        _table_cache = _migrate_flash(_read_disk(table_path()))
    return _table_cache


def save_table(data: Dict[str, Any]) -> None:
    """Full-table write (locked + atomic). Prefer :func:`update_table`
    for read-modify-write — it re-reads under the lock so concurrent
    writers cannot drop each other's rows."""
    global _table_cache
    path = table_path()
    with _store_lock(path):
        _atomic_write(path, data)
    _table_cache = data


def update_table(mutator) -> Dict[str, Any]:
    """Locked read-modify-write: reload from disk under the fcntl lock,
    apply ``mutator(data)``, write atomically. The ONLY safe way to add
    rows when hwbench and a manual search can run concurrently."""
    global _table_cache
    path = table_path()
    with _store_lock(path):
        data = _migrate_flash(_read_disk(path))
        mutator(data)
        _atomic_write(path, data)
    _table_cache = data
    return data


def _device_kind() -> Optional[str]:
    try:
        import jax

        return getattr(jax.devices()[0], "device_kind", None)
    except Exception:  # noqa: BLE001 — no backend, no filtering
        return None


def family_entries(family: str) -> Dict[str, Any]:
    """Rows for ``family`` measured on the RUNNING device generation
    (same rule as ``autotune._device_entries``: a v5e row must not
    drive decisions on v6e)."""
    entries = load_table().get("families", {}).get(
        family, {}).get("entries", {})
    kind = _device_kind()
    if kind is None:
        return entries
    return {k: e for k, e in entries.items()
            if e.get("device") in (None, kind)}


def lookup(family: str, key: str) -> Optional[Dict[str, Any]]:
    """Exact-key row or None — engagement never transfers across shapes
    (the flash crossover lesson: the win/lose verdict flips with shape;
    see autotune.kernel_beats_composite)."""
    return family_entries(family).get(key)


def best_config(family: str, key: str) -> Optional[Dict[str, Any]]:
    e = lookup(family, key)
    return e.get("config") if e else None


def engaged(family: str, key: str) -> Optional[bool]:
    """Measured engagement verdict; None when no measurement applies.

    A row only counts when it was measured on real hardware (CPU /
    interpret rows carry meaningless wall-clock and never engage) and
    carries a kernel-vs-composite ratio. True iff measured faster.
    """
    e = lookup(family, key)
    if e is None or "ratio" not in e:
        return None
    if e.get("backend") in (None, "cpu") or e.get("interpret"):
        return None
    return e["ratio"] > 1.0


def note_engaged(family: str) -> None:
    m = _monitor
    if m is not None:
        m.on_pallas_engaged(family)


def note_fallback(family: str) -> None:
    m = _monitor
    if m is not None:
        m.on_pallas_fallback(family)


def decide(family: str, key: str) -> bool:
    """The runtime entry: engagement verdict + monitor accounting.
    Returns True only on a measured-faster hardware row."""
    v = bool(engaged(family, key))
    if v:
        note_engaged(family)
    else:
        note_fallback(family)
    return v


def engagement_report() -> Dict[str, bool]:
    """``{family: any-shape-engaged}`` for EVERY registered family on
    the current device — the sub-object benches embed (``kernels``) so
    the perf guard's engagement-regression gate can compare runs. A
    family with no hardware rows reports False, NOT absent: the
    deleted-row / regenerated-table regression must read as a lost
    engagement against a True baseline (absent means only "this bench
    didn't embed the map at all" — the guard's wildcard)."""
    out: Dict[str, bool] = {}
    for name in sorted(FAMILIES):
        hw = [e for e in family_entries(name).values()
              if e.get("backend") not in (None, "cpu")
              and not e.get("interpret") and "ratio" in e]
        out[name] = any(e["ratio"] > 1.0 for e in hw)
    return out


# -- the search ---------------------------------------------------------------

def _parity_check(fam: KernelFamily, shape, config, args, ref_out):
    """Interpret-mode parity vs the composite — the mandatory
    pre-filter. Returns (ok, max_abs_err)."""
    import numpy as np

    try:
        out = fam.build(shape, config, interpret=True)(*args)
    except Exception:  # noqa: BLE001 — a config that cannot run is a reject
        return False, float("inf")
    outs = out if isinstance(out, (tuple, list)) else (out,)
    refs = ref_out if isinstance(ref_out, (tuple, list)) else (ref_out,)
    err = 0.0
    for o, r in zip(outs, refs):
        err = max(err, float(np.max(np.abs(
            np.asarray(o, dtype=np.float64)
            - np.asarray(r, dtype=np.float64)))))
    return err <= fam.parity_atol, err


def search_shape(fam: KernelFamily, shape, iters: int = 20,
                 verbose: bool = True,
                 interpret: Optional[bool] = None) -> Dict[str, Any]:
    """Run the full pipeline for one shape: enumerate -> interpret-mode
    parity filter -> time survivors + composite -> persist the best row
    (device/commit provenance). Returns the persisted entry."""
    import jax

    from . import autotune
    from ...utils import measurements as _meas

    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    key = fam.key(shape)
    args = fam.make_inputs(shape)
    # parity runs on dedicated (fp32) inputs when the family provides
    # them: the filter must see math errors, not bf16 rounding noise
    pargs = getattr(fam, "make_parity_inputs", fam.make_inputs)(shape)
    composite = fam.build_composite(shape)
    ref_out = composite(*pargs)
    cands = list(fam.candidates(shape))
    if not cands:
        raise RuntimeError(f"{fam.name}: empty candidate space for "
                           f"{key}")
    m = _monitor
    survivors = []
    rejects = 0
    for cand in cands:
        ok, err = _parity_check(fam, shape, cand, pargs, ref_out)
        if ok:
            survivors.append((cand, err))
        else:
            rejects += 1
            if m is not None:
                m.on_search_reject(fam.name)
            if verbose:
                print(f"  {fam.name}[{key}] reject {cand}: "
                      f"parity err {err:g} > {fam.parity_atol:g}",
                      flush=True)
    if not survivors:
        raise RuntimeError(
            f"{fam.name}: every candidate failed interpret-mode parity "
            f"for {key} — the kernel is wrong, not slow")

    def timefn(f):
        return autotune._gradify(f) if fam.grad else f

    try:
        t_comp = autotune._time_compiled(timefn(composite), args, iters)
    except Exception as e:  # noqa: BLE001 — composite OOM: no ratio
        if verbose:
            print(f"  {fam.name}[{key}] composite failed "
                  f"({type(e).__name__}); no engagement ratio",
                  flush=True)
        t_comp = None

    results = []
    hint: Dict[str, Any] = {}  # shared fori-loop calibration per shape
    for cand, perr in survivors:
        fn = fam.build(shape, cand, interpret=interpret)
        try:
            t = autotune._time_compiled(timefn(fn), args, iters,
                                        n_hint=hint)
        except Exception as e:  # noqa: BLE001 — a bad config skips
            rejects += 1
            if m is not None:
                m.on_search_reject(fam.name)
            if verbose:
                print(f"  {fam.name}[{key}] {cand}: failed "
                      f"{type(e).__name__}", flush=True)
            continue
        if m is not None:
            m.on_search_timed(fam.name)
        results.append((t, cand, perr))
        if verbose:
            print(f"  {fam.name}[{key}] {cand}: "
                  f"{t * 1e3:.3f} ms"
                  + (f"  (composite {t_comp * 1e3:.3f} ms)"
                     if t_comp is not None else ""), flush=True)
    if not results:
        raise RuntimeError(f"{fam.name}: no candidate survived timing "
                           f"for {key}")
    results.sort(key=lambda r: r[0])
    t_best, best_cand, best_err = results[0]
    entry: Dict[str, Any] = {
        "family": fam.name, "key": key,
        "config": best_cand,
        "t_kernel_ms": round(t_best * 1e3, 4),
        "parity_max_err": best_err,
        "candidates": len(cands),
        "candidates_timed": len(results),
        "rejects": rejects,
        "grad": fam.grad,
        "device": _device_kind(),
        "backend": jax.default_backend(),
        "interpret": bool(interpret),
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    entry.update(fam.shape_info(shape))
    entry.update(_meas._git_commit())
    if t_comp is not None:
        entry["t_composite_ms"] = round(t_comp * 1e3, 4)
        entry["ratio"] = round(t_comp / max(t_best, 1e-12), 4)
        if m is not None:
            m.on_search_best_ratio(fam.name, entry["ratio"])

    def put(data):
        data.setdefault("families", {}).setdefault(
            fam.name, {"entries": {}}).setdefault(
            "entries", {})[key] = entry

    update_table(put)
    on_persist = getattr(fam, "on_persist", None)
    if on_persist is not None:
        on_persist(shape, entry)
    return entry


def search_family(fam_or_name, shapes=None, iters: int = 20,
                  verbose: bool = True,
                  interpret: Optional[bool] = None,
                  smoke: bool = False) -> List[Dict[str, Any]]:
    """Search every shape of a family; returns the persisted entries.
    ``smoke`` selects the family's tiny CPU shapes."""
    fam = FAMILIES[fam_or_name] if isinstance(fam_or_name, str) \
        else fam_or_name
    if shapes is None:
        shapes = fam.smoke_shapes() if smoke else fam.shapes()
    out = []
    for shape in shapes:
        if verbose:
            print(f"searching {fam.name}[{fam.key(shape)}] "
                  f"({len(list(fam.candidates(shape)))} candidate(s))",
                  flush=True)
        out.append(search_shape(fam, shape, iters=iters, verbose=verbose,
                                interpret=interpret))
    return out


from ...monitor import _register as _monitor_register  # noqa: E402

_monitor_register(sys.modules[__name__])
