"""Flash-attention block-size autotuner with a persisted cache.

The reference carries an Ansor-like kernel tuner
(`paddle/cinn/auto_schedule/auto_tuner.h`) and a GPU autotune cache
(`paddle/phi/kernels/autotune/cache.h`); this is that component at Pallas
scale: per-shape search over (block_q, block_k) for the flash kernels,
measured on the real chip with an amortized in-program loop (host sync
through the tunnel costs ~170 ms, so per-dispatch timing is meaningless —
PERF.md round 3), persisted to ``flash_tune.json`` next to this module
with device/commit provenance.

The cache ALSO re-derives the engagement heuristic: each entry stores the
kernel-vs-XLA-composite fwd+bwd ratio, so `flash_attention_kernel` engages
the Pallas kernel exactly where it measured faster, replacing the
hand-edited thresholds (VERDICT r3 weak #6).
"""
from __future__ import annotations

import json
import math
import os
import tempfile
import time
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

_CACHE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "flash_tune.json")
_cache: Optional[Dict[str, Any]] = None


def _key(sq: int, sk: int, d: int, causal: bool,
         dropout: float = 0.0) -> str:
    base = f"s{sq}x{sk}_d{d}_{'c' if causal else 'f'}"
    if dropout > 0.0:
        base += f"_p{dropout:g}"
    return base


def load_cache() -> Dict[str, Any]:
    global _cache
    if _cache is None:
        try:
            with open(_CACHE_PATH) as f:
                _cache = json.load(f)
        except (OSError, ValueError):
            _cache = {"entries": {}}
    return _cache


def _atomic_write(path: str, data: Dict[str, Any]) -> None:
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".",
                               prefix=".flash_tune_", suffix=".json")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(data, f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def save_cache(cache: Dict[str, Any]) -> None:
    """Full-cache write — fcntl-locked + atomic tmp/rename
    (utils/measurements.py discipline; the old bare ``open(..., "w")``
    could tear under concurrent hwbench/autotune writers). Prefer
    :func:`update_cache` for read-modify-write."""
    global _cache
    _cache = cache
    from ...utils.measurements import _StoreLock

    with _StoreLock(_CACHE_PATH):
        _atomic_write(_CACHE_PATH, cache)


def update_cache(mutator) -> Dict[str, Any]:
    """Locked read-modify-write: reload from disk under the lock, apply
    ``mutator(cache)``, write atomically — concurrent tuners (hwbench's
    flashtune stage + a manual run) cannot drop each other's rows."""
    global _cache
    from ...utils.measurements import _StoreLock

    with _StoreLock(_CACHE_PATH):
        try:
            with open(_CACHE_PATH) as f:
                data = json.load(f)
            if not (isinstance(data, dict)
                    and isinstance(data.get("entries"), dict)):
                data = {"entries": {}}
        except (OSError, ValueError):
            data = {"entries": {}}
        mutator(data)
        _atomic_write(_CACHE_PATH, data)
    _cache = data
    return data


def _device_kind() -> Optional[str]:
    try:
        return getattr(jax.devices()[0], "device_kind", None)
    except Exception:  # noqa: BLE001 — no backend, no filtering
        return None


def _device_entries() -> Dict[str, Any]:
    """Cache entries measured on the RUNNING device generation only — a
    cache tuned on v5e must not drive decisions on v6e."""
    entries = load_cache().get("entries", {})
    kind = _device_kind()
    if kind is None:
        return entries
    return {k: e for k, e in entries.items()
            if e.get("device") in (None, kind)}


def lookup(sq: int, sk: int, d: int, causal: bool, *,
           exact: bool = False) -> Optional[Dict[str, Any]]:
    """Exact-shape cache entry, or (unless ``exact``) the nearest
    same-d/causal seq within one octave per dimension (block choices
    transfer well between close sequence lengths)."""
    entries = _device_entries()
    hit = entries.get(_key(sq, sk, d, causal))
    if hit is not None or exact:
        return hit
    best, best_dist = None, None
    for e in entries.values():
        if e["d"] != d or e["causal"] != causal:
            continue
        dq = abs(math.log2(max(e["sq"], 1) / max(sq, 1)))
        dk = abs(math.log2(max(e["sk"], 1) / max(sk, 1)))
        if dq > 1.0 or dk > 1.0:  # transfer at most one octave per dim
            continue
        if best_dist is None or dq + dk < best_dist:
            best, best_dist = e, dq + dk
    return best


def best_blocks(sq: int, sk: int, d: int, causal: bool
                ) -> Tuple[Optional[int], Optional[int]]:
    e = lookup(sq, sk, d, causal)
    if e is None:
        return None, None
    bq, bk = e["block_q"], e["block_k"]
    # a transferred entry must still tile the actual shape
    if sq % bq or sk % bk:
        return None, None
    return bq, bk


def kernel_beats_composite(sq: int, sk: int, d: int, causal: bool,
                           margin: float = 1.0,
                           dropout: float = 0.0) -> Optional[bool]:
    """Measured engagement decision; None when no measurement applies.

    Exact-shape hits only: the win/lose ratio flips across the measured
    seq crossover (round-4 DCE-free timing: composite wins at s=512,
    kernel from s=1024 — 3.4-6.1x, growing with seq), so transferring
    the verdict one octave would invert it exactly at the crossover.
    Block sizes transfer (see `best_blocks`); the binary verdict does not.
    ``margin > 1`` demands measured headroom — used when the caller adds
    unmeasured work on top of the measured configuration (in-kernel
    dropout adds hash+select VPU time the no-dropout rows don't carry).
    ``dropout``: a measured VARIANT row (tune_shape(dropout=...)) wins
    over the margin heuristic when one exists at this exact shape.
    """
    if dropout > 0.0:
        ev = _device_entries().get(_key(sq, sk, d, causal, dropout))
        if ev is not None and "ratio_fwd_bwd" in ev:
            return ev["ratio_fwd_bwd"] > 1.0
    e = lookup(sq, sk, d, causal, exact=True)
    if e is None or "ratio_fwd_bwd" not in e:
        return None
    return e["ratio_fwd_bwd"] > margin


def _candidates(seq: int):
    out = []
    for b in (128, 256, 512, 1024):
        if b <= seq and seq % b == 0:
            out.append(b)
    return out or [seq]


_sync_overhead: Dict[str, float] = {}


def _time_compiled(fn, args, iters=20, n_hint=None) -> float:
    """Amortized per-iteration seconds.

    Two tunnel realities shape this method (both produced plausible-looking
    0.01 ms "measurements" for s=4096 attention — 30x past chip peak —
    before they were fixed):

    - the sync is a device->host transfer (`float(out[0, ...])`) — the
      only fence that is strong on every backend: through the tunnel,
      block_until_ready acks enqueue rather than completion (see
      utils/timing.py), and a transfer costs ~70-95 ms.
    - that per-sync overhead dwarfs sub-ms kernels and jitters by ~±15 ms.
      So time TWO compiled loops (n and 4*n dependent applications) and
      divide the DIFFERENCE by 3*n: the constant sync + dispatch overhead
      cancels, and n is sized so the difference carries ~600 ms of kernel
      time.

    The loop body feeds the output back as the next query — a true data
    dependence (`q + 0.0 * r.mean()` gets algebraically simplified away
    and the kernel DCE'd).
    """

    def make(n):
        @jax.jit
        def loop(*a):
            def body(_, q):
                r = fn(q, *a[1:])
                if r.shape == q.shape:
                    return r.astype(q.dtype)
                return q + r.astype(q.dtype).sum() * 1e-12

            return jax.lax.fori_loop(0, n, body, a[0])

        return loop

    def run(loop):
        t0 = time.perf_counter()
        out = loop(*args)
        float(out[(0,) * out.ndim])  # full sync (transfer-backed)
        return time.perf_counter() - t0

    if iters < 16 and jax.default_backend() == "cpu":
        # smoke mode (interpret-mode CPU tests): one short loop, no
        # calibration — accuracy is irrelevant, wall-clock is not.
        # CPU-only: on a real backend small --iters still calibrates, so
        # a hardware tune can never persist uncalibrated numbers.
        loop = make(iters)
        run(loop)  # compile + warm
        return max(run(loop), 1e-9) / iters

    # constant dispatch+sync overhead (~70-95 ms through the tunnel,
    # ~1 ms on an attached chip): a property of the harness, not of fn —
    # measure once per backend and memoize
    overhead = _sync_overhead.get(jax.default_backend())
    if overhead is None:
        empty = make(0)
        run(empty)
        overhead = min(run(empty) for _ in range(2))
        _sync_overhead[jax.default_backend()] = overhead
    # calibrate: size n so the long-short difference carries ~600 ms of
    # kernel time — well above the measured ~±15 ms sync jitter.
    # Candidates of one shape/direction run within a small factor of each
    # other, so callers may share a calibration via n_hint (a mutable
    # dict) instead of paying the ~3 calibration runs per candidate.
    if n_hint and "n" in n_hint:
        n = n_hint["n"]
    else:
        cal_n = max(iters, 128)
        cal = make(cal_n)
        run(cal)  # compile + warm
        t_cal = min(run(cal) for _ in range(2))
        t_est = max((t_cal - overhead) / cal_n, 2e-7)
        n = int(min(max(0.6 / (3 * t_est), 8), 20000))
        if n_hint is not None:
            n_hint["n"] = n

    short, long_ = make(n), make(4 * n)
    run(short), run(long_)  # compile + warm both
    deltas = sorted(run(long_) - run(short) for _ in range(3))
    return max(deltas[1], 1e-9) / (3 * n)


def tune_shape(bh: int, sq: int, sk: int, d: int, causal: bool,
               dtype=jnp.bfloat16, iters: int = 20,
               verbose: bool = True) -> Dict[str, Any]:
    """Search (block_q, block_k) for one shape on the LIVE backend; also
    measure the XLA composite for the engagement ratio. Returns the cache
    entry (already persisted)."""
    from .flash_attention import _flash_bhsd

    scale = 1.0 / math.sqrt(d)
    q = jax.random.normal(jax.random.PRNGKey(0), (bh, sq, d), dtype)
    k = jax.random.normal(jax.random.PRNGKey(1), (bh, sk, d), dtype)
    v = jax.random.normal(jax.random.PRNGKey(2), (bh, sk, d), dtype)

    composite = _composite_sdpa(sq, sk, causal, scale)
    gradify = _gradify

    # the composite baseline may OOM at long-context shapes (it
    # materializes the [sq, sk] score matrix the flash kernel exists to
    # avoid) — tune the kernel anyway, just without an engagement ratio
    try:
        t_comp_fwd = _time_compiled(composite, (q, k, v), iters)
        t_comp_fb = _time_compiled(gradify(composite), (q, k, v), iters)
    except Exception as e:  # noqa: BLE001 — baseline OOM must not stop tuning
        if verbose:
            print(f"  composite baseline failed ({type(e).__name__}); "
                  f"tuning kernel without a ratio", flush=True)
        t_comp_fwd = t_comp_fb = None

    results = []
    hint_fwd, hint_fb = {}, {}  # one calibration per direction, shared
    for bq in _candidates(sq):
        for bk in _candidates(sk):
            def run(q, k, v, _bq=bq, _bk=bk):
                return _flash_bhsd(q, k, v, causal, scale, False, _bq, _bk)

            try:
                t_fwd = _time_compiled(run, (q, k, v), iters,
                                       n_hint=hint_fwd)
                t_fb = _time_compiled(gradify(run), (q, k, v), iters,
                                      n_hint=hint_fb)
            except Exception as e:  # noqa: BLE001 — a bad tiling skips
                if verbose:
                    print(f"  ({bq},{bk}): failed {type(e).__name__}",
                          flush=True)
                continue
            results.append((t_fb, t_fwd, bq, bk))
            if verbose:
                print(f"  ({bq},{bk}): fwd {t_fwd * 1e3:.2f}ms "
                      f"fwd+bwd {t_fb * 1e3:.2f}ms", flush=True)
    if not results:
        raise RuntimeError(f"no viable block sizes for {sq}x{sk} d{d}")
    results.sort()
    t_fb, t_fwd, bq, bk = results[0]
    dev = jax.devices()[0]
    entry = {
        "sq": sq, "sk": sk, "d": d, "causal": causal, "bh": bh,
        "block_q": bq, "block_k": bk,
        "t_fwd_ms": round(t_fwd * 1e3, 4),
        "t_fwd_bwd_ms": round(t_fb * 1e3, 4),
        "device": getattr(dev, "device_kind", str(dev)),
        "backend": jax.default_backend(),
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    if t_comp_fwd is not None:
        entry.update({
            "t_xla_fwd_ms": round(t_comp_fwd * 1e3, 4),
            "t_xla_fwd_bwd_ms": round(t_comp_fb * 1e3, 4),
            "ratio_fwd": round(t_comp_fwd / t_fwd, 4),
            "ratio_fwd_bwd": round(t_comp_fb / t_fb, 4),
        })
    update_cache(lambda c: c.setdefault("entries", {}).update(
        {_key(sq, sk, d, causal): entry}))
    return entry


# the bench-relevant shapes: headline Llama (s1024 d128), BERT (s512
# d64), long-context legs
def _gradify(f):
    """fwd+bwd timing wrapper with every grad folded into the result —
    returning dq alone lets XLA DCE the dk/dv computation (measured:
    "bwd" adding only 0.2 ms on a 2.5x-fwd-FLOPs pass). Cross-length
    grads fold via a seq-reduced broadcast."""

    def g(q, k, v):
        dq, dk, dv = jax.grad(
            lambda *a: f(*a).astype(jnp.float32).sum(),
            argnums=(0, 1, 2))(q, k, v)
        r = dq
        for dother in (dk, dv):
            if dother.shape == r.shape:
                r = r + dother
            else:
                r = r + dother.sum(axis=-2, keepdims=True) * 1e-6
        return r

    return g


def _composite_sdpa(sq, sk, causal, scale, dropout=0.0):
    """The XLA-composite attention baseline. With dropout, the bernoulli
    key is derived FROM the query data: a fixed key would be
    loop-invariant inside _time_compiled's fori_loop and XLA would
    hoist the mask generation out of the timed loop, biasing the ratio
    (the kernel regenerates its mask every iteration)."""

    def composite(q, k, v):
        s_ = (q.astype(jnp.float32) * scale) @ jnp.swapaxes(
            k.astype(jnp.float32), -1, -2)
        if causal:
            mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
            s_ = jnp.where(mask, s_, -1e30)
        p = jax.nn.softmax(s_, axis=-1)
        if dropout > 0.0:
            salt = jax.lax.bitcast_convert_type(
                q[(0,) * q.ndim].astype(jnp.float32), jnp.int32)
            key = jax.random.fold_in(jax.random.PRNGKey(5), salt)
            keep = jax.random.bernoulli(key, 1.0 - dropout, p.shape)
            p = jnp.where(keep, p / (1.0 - dropout), 0.0)
        return p @ v.astype(jnp.float32)

    return composite


def tune_variant_ratio(bh: int, sq: int, sk: int, d: int, causal: bool,
                       dropout: float, dtype=jnp.bfloat16,
                       iters: int = 20, verbose: bool = True
                       ) -> Dict[str, Any]:
    """Kernel-vs-composite fwd+bwd ratio for the in-kernel DROPOUT
    variant at this shape, run at the base entry's tuned blocks (no
    block re-search: only the engagement RATIO is variant-dependent).
    Persists a variant cache row consulted by
    `kernel_beats_composite(dropout=...)` — replacing the interim 1.2x
    demand-headroom margin with a measurement."""
    from .flash_attention import _flash_bhsd_drop

    scale = 1.0 / math.sqrt(d)
    q = jax.random.normal(jax.random.PRNGKey(0), (bh, sq, d), dtype)
    k = jax.random.normal(jax.random.PRNGKey(1), (bh, sk, d), dtype)
    v = jax.random.normal(jax.random.PRNGKey(2), (bh, sk, d), dtype)
    seed = jnp.asarray([7, 9], jnp.int32)
    bq, bk = best_blocks(sq, sk, d, causal)
    if bq is None and jax.default_backend() != "cpu":
        # a ratio at un-tuned default blocks would misstate the
        # kernel's best case; tune the base row first
        raise RuntimeError(
            f"no tuned base row for s{sq}x{sk} d{d} causal={causal}; "
            "run the standard tune before the variant")

    def kern(q, k, v):
        return _flash_bhsd_drop(q, k, v, seed, causal, scale, False,
                                bq, bk, 0, dropout)

    composite = _composite_sdpa(sq, sk, causal, scale, dropout)

    t_k = _time_compiled(_gradify(kern), (q, k, v), iters)
    try:
        t_c = _time_compiled(_gradify(composite), (q, k, v), iters)
    except Exception as e:  # noqa: BLE001 — composite OOM: no ratio
        if verbose:
            print(f"  variant composite failed ({type(e).__name__})",
                  flush=True)
        t_c = None
    entry: Dict[str, Any] = {
        "sq": sq, "sk": sk, "d": d, "causal": causal, "bh": bh,
        "dropout": dropout, "block_q": bq, "block_k": bk,
        "t_kernel_fwd_bwd_s": t_k,
        "device": _device_kind(),
        "backend": jax.default_backend(),
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    if t_c is not None:
        entry["t_composite_fwd_bwd_s"] = t_c
        entry["ratio_fwd_bwd"] = t_c / max(t_k, 1e-12)
    if verbose:
        r = entry.get("ratio_fwd_bwd")
        print(f"  dropout={dropout} ratio_fwd_bwd="
              f"{r if r is None else round(r, 3)}", flush=True)
    update_cache(lambda c: c.setdefault("entries", {}).update(
        {_key(sq, sk, d, causal, dropout): entry}))
    return entry


# dropout-variant rows (BERT/ERNIE honest configs): ratio-only
# measurements at the base rows' tuned blocks
VARIANT_SHAPES = [
    (768, 512, 512, 64, False, 0.1),
    (48, 1024, 1024, 64, True, 0.1),
    (48, 1024, 1024, 128, True, 0.1),
]

STANDARD_SHAPES = [
    (48, 1024, 1024, 64, True),
    (48, 1024, 1024, 128, True),
    (32, 512, 512, 64, True),
    (24, 2048, 2048, 128, True),
    (12, 4096, 4096, 128, True),
    # long-context legs (composite may OOM-skip; kernel still tunes)
    (8, 8192, 8192, 128, True),
    (4, 16384, 16384, 128, True),
    # non-causal (encoder / BERT-shape) engagement rows
    (768, 512, 512, 64, False),
    (48, 1024, 1024, 64, False),
    (48, 1024, 1024, 128, False),
]


def tune_standard(iters: int = 20, verbose: bool = True):
    out = []
    for bh, sq, sk, d, causal in STANDARD_SHAPES:
        if verbose:
            print(f"tuning bh={bh} s={sq}x{sk} d={d} causal={causal}",
                  flush=True)
        out.append(tune_shape(bh, sq, sk, d, causal, iters=iters,
                              verbose=verbose))
    return out


# -- search-harness family (ops/pallas/search.py) -----------------------------

from . import search as _search  # noqa: E402 — no cycle: search imports
#                                  this module lazily, inside functions


class FlashFamily(_search.KernelFamily):
    """The original (block_q, block_k) flash search, expressed as a
    harness family. Rows persisted through the harness are mirrored
    into the legacy ``flash_tune.json`` (``on_persist``) so
    `flash_attention_kernel`'s `best_blocks`/`kernel_beats_composite`
    lookups see them — one engagement source, two writers."""

    name = "flash"
    grad = True
    parity_atol = 2e-5

    def shapes(self):
        return list(STANDARD_SHAPES)

    def smoke_shapes(self):
        return [(2, 128, 128, 8, True)]

    def key(self, shape):
        bh, sq, sk, d, causal = shape
        return _key(sq, sk, d, causal)

    def shape_info(self, shape):
        bh, sq, sk, d, causal = shape
        return {"bh": bh, "sq": sq, "sk": sk, "d": d, "causal": causal}

    def candidates(self, shape):
        bh, sq, sk, d, causal = shape
        return [{"block_q": bq, "block_k": bk}
                for bq in _candidates(sq) for bk in _candidates(sk)]

    def _inputs(self, shape, dtype):
        bh, sq, sk, d, causal = shape
        q = jax.random.normal(jax.random.PRNGKey(0), (bh, sq, d), dtype)
        k = jax.random.normal(jax.random.PRNGKey(1), (bh, sk, d), dtype)
        v = jax.random.normal(jax.random.PRNGKey(2), (bh, sk, d), dtype)
        return q, k, v

    def make_inputs(self, shape):
        return self._inputs(shape, jnp.bfloat16)

    def make_parity_inputs(self, shape):
        return self._inputs(shape, jnp.float32)

    def build(self, shape, config, interpret):
        from .flash_attention import _flash_bhsd

        bh, sq, sk, d, causal = shape
        scale = 1.0 / math.sqrt(d)

        def run(q, k, v):
            return _flash_bhsd(q, k, v, causal, scale, interpret,
                               config.get("block_q"),
                               config.get("block_k"))

        return run

    def build_composite(self, shape):
        bh, sq, sk, d, causal = shape
        return _composite_sdpa(sq, sk, causal, 1.0 / math.sqrt(d))

    def on_persist(self, shape, entry):
        """Mirror the harness row into the legacy cache in the exact
        schema `best_blocks`/`kernel_beats_composite` read."""
        bh, sq, sk, d, causal = shape
        legacy: Dict[str, Any] = {
            "sq": sq, "sk": sk, "d": d, "causal": causal, "bh": bh,
            "block_q": entry["config"]["block_q"],
            "block_k": entry["config"]["block_k"],
            "t_fwd_bwd_ms": entry["t_kernel_ms"],
            "device": entry.get("device"),
            "backend": entry.get("backend"),
            "timestamp": entry.get("timestamp"),
            "via": "kernel_search",
        }
        if "ratio" in entry:
            legacy["t_xla_fwd_bwd_ms"] = entry["t_composite_ms"]
            legacy["ratio_fwd_bwd"] = entry["ratio"]
        # interpret/CPU rows carry meaningless wall-clock: never mirror
        # them into the engagement cache (the smoke CLI runs on CPU)
        if entry.get("backend") == "cpu" or entry.get("interpret"):
            return
        update_cache(lambda c: c.setdefault("entries", {}).update(
            {_key(sq, sk, d, causal): legacy}))


_search.register_family(FlashFamily())
