"""FlashAttention forward/backward as Pallas TPU kernels.

Reference parity: the reference binds the external FlashAttention CUDA
library as a PHI kernel (`paddle/phi/kernels/gpu/flash_attn_kernel.cu`,
`cmake/external/flashattn.cmake`). Here the same role is played by a
tiled streaming-softmax kernel pair written in Pallas (SURVEY §5.7:
"implement splash/flash attention in Pallas").

Algorithm: FlashAttention-2. Forward streams K/V blocks through VMEM with a
running (max, sum) softmax, never materializing the [sq, sk] score matrix in
HBM; saves per-row logsumexp for backward. Backward recomputes scores per
block (dq kernel over q-rows, dkv kernel over k-columns), also O(block²)
VMEM only. Layout: [batch, seq, heads, head_dim] — paddle's flash-attn
layout — processed as one (batch·head) per grid row.

Registered as the 'flash_attention' kernel override for platform 'tpu', so
`paddle.nn.functional.scaled_dot_product_attention` transparently uses it on
TPU (mask / dropout calls fall back to the XLA composite implementation).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .. import registry

NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, causal, scale,
                block_k, seq_k):
    # q_ref: [block_q, d]; k_ref/v_ref: [seq_k, d] (whole K/V row in VMEM)
    q_idx = pl.program_id(1)
    block_q = q_ref.shape[1]
    d = q_ref.shape[2]
    q = q_ref[0].astype(jnp.float32) * scale

    m = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((block_q, 1), jnp.float32)
    acc = jnp.zeros((block_q, d), jnp.float32)

    n_kb = seq_k // block_k
    # causal: only stream K blocks up to (and including) the diagonal
    if causal:
        q_end = (q_idx + 1) * block_q  # rows cover [q_idx*bq, q_end)
        n_kb_eff = pl.cdiv(q_end, block_k)
    else:
        n_kb_eff = n_kb

    def body(kb, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [bq, bk]
        if causal:
            rows = q_idx * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=1, keepdims=True)
        acc_new = alpha * acc + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, n_kb_eff, body, (m, l, acc))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l_safe).astype(o_ref.dtype)
    lse_ref[0] = (m + jnp.log(l_safe))[:, 0]


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   *, causal, scale, block_k, seq_k):
    q_idx = pl.program_id(1)
    block_q = q_ref.shape[1]
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0][:, None]
    delta = delta_ref[0][:, None]
    dq = jnp.zeros_like(q)

    if causal:
        n_kb_eff = pl.cdiv((q_idx + 1) * block_q, block_k)
    else:
        n_kb_eff = seq_k // block_k

    def body(kb, dq):
        k = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if causal:
            rows = q_idx * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse)                       # [bq, bk]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        return dq + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, n_kb_eff, body, dq)
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, causal, scale, block_q, seq_q):
    k_idx = pl.program_id(1)
    block_k = k_ref.shape[1]
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    dk = jnp.zeros_like(k)
    dv = jnp.zeros_like(v)

    n_qb = seq_q // block_q
    if causal:
        qb_start = (k_idx * block_k) // block_q  # first q block on/after diag
    else:
        qb_start = 0

    def body(qb, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(qb * block_q, block_q)][:, None]
        delta = delta_ref[0, pl.ds(qb * block_q, block_q)][:, None]
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [bq, bk]
        if causal:
            rows = qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = k_idx * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse)
        dv_new = dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk_new = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk_new, dv_new

    dk, dv = jax.lax.fori_loop(qb_start, n_qb, body, (dk, dv))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _pick_block(seq, target=512):
    b = min(seq, target)
    while seq % b:
        b //= 2
    return max(b, 1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_bhsd(q, k, v, causal, scale, interpret):
    out, _ = _flash_fwd(q, k, v, causal, scale, interpret)
    return out


def _flash_fwd(q, k, v, causal, scale, interpret):
    """q,k,v: [bh, s, d] -> (out [bh, s, d], lse [bh, s])."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    block_q = _pick_block(sq)
    block_k = _pick_block(sk)
    grid = (bh, sq // block_q)
    kernel = functools.partial(_fwd_kernel, causal=causal, scale=scale,
                               block_k=block_k, seq_k=sk)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q), lambda b, i: (b, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq), jnp.float32),
        ],
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=int(4 * bh * sq * sk * d * (0.5 if causal else 1.0)),
            bytes_accessed=int(q.size * 2 + k.size * 2 + v.size * 2),
            transcendentals=int(bh * sq * sk),
        ),
    )(q, k, v)
    return out, lse


def _flash_fwd_rule(q, k, v, causal, scale, interpret):
    out, lse = _flash_fwd(q, k, v, causal, scale, interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd_rule(causal, scale, interpret, res, g):
    q, k, v, out, lse = res
    bh, sq, d = q.shape
    sk = k.shape[1]
    block_q = _pick_block(sq)
    block_k = _pick_block(sk)
    g = g.astype(q.dtype)
    # delta_i = sum_d(do * o) per row (FlashAttention-2 eq. for ds)
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)  # [bh, sq]

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, causal=causal, scale=scale,
                          block_k=block_k, seq_k=sk),
        grid=(bh, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q), lambda b, i: (b, i)),
            pl.BlockSpec((1, block_q), lambda b, i: (b, i)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        interpret=interpret,
    )(q, k, v, g, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, causal=causal, scale=scale,
                          block_q=block_q, seq_q=sq),
        grid=(bh, sk // block_k),
        in_specs=[
            pl.BlockSpec((1, sq, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, sq, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, sq), lambda b, j: (b, 0)),
            pl.BlockSpec((1, sq), lambda b, j: (b, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        interpret=interpret,
    )(q, k, v, g, lse, delta)
    return dq, dk, dv


_flash_bhsd.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention_kernel(q, k, v, *rest, causal=False, dropout=0.0,
                           interpret=False):
    """Kernel-registry entry: [b, s, h, d] inputs, same signature as the
    default XLA implementation in nn/functional/attention.py. Falls back to
    the composite path for masks/dropout/odd shapes."""
    if rest or dropout > 0.0:
        from ...nn.functional.attention import _sdpa_reference

        return _sdpa_reference(q, k, v, *rest, causal=causal, dropout=dropout)
    b, sq, h, d = q.shape
    sk = k.shape[1]
    if sq < 16 or sk < 16 or d % 128 or k.shape[2] != h:
        from ...nn.functional.attention import _sdpa_reference

        return _sdpa_reference(q, k, v, causal=causal, dropout=0.0)
    scale = 1.0 / math.sqrt(d)
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    out = _flash_bhsd(qt, kt, vt, causal, scale, interpret)
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)


def register(platform="tpu", interpret=False):
    fn = functools.partial(flash_attention_kernel, interpret=interpret)
    registry.register_kernel("flash_attention", platform)(fn)
    return fn
