"""FlashAttention forward/backward as Pallas TPU kernels.

Reference parity: the reference binds the external FlashAttention CUDA
library as a PHI kernel (`paddle/phi/kernels/gpu/flash_attn_kernel.cu`,
`cmake/external/flashattn.cmake`). Here the same role is played by a
tiled streaming-softmax kernel pair written in Pallas (SURVEY §5.7:
"implement splash/flash attention in Pallas").

Algorithm: FlashAttention-2. The grid iterates over BOTH q-blocks and
k-blocks — the (max, sum, acc) streaming-softmax state lives in VMEM
scratch and is carried across the k-minor grid dimension, so VMEM usage is
O(block_q·block_k + block_q·d) regardless of sequence length (the whole
point of flash attention; round-1 kept full K/V rows in VMEM which capped
seq at a few K). Backward recomputes scores per block pair (dq kernel with
k-minor grid, dkv kernel with q-minor grid), also block-local VMEM only.

Causal masking is bottom-right aligned (rows of the score matrix count
back from the last key), matching flash-attn >= 2.1 and `_sdpa_reference`
in nn/functional/attention.py (`jnp.tril(..., k=sk-sq)`).

Layout: [batch, seq, heads, head_dim] — paddle's flash-attn layout —
processed as one (batch·head) per grid row.

Registered as the 'flash_attention' kernel override for platform 'tpu', so
`paddle.nn.functional.scaled_dot_product_attention` transparently uses it
on TPU (mask / dropout calls fall back to the XLA composite
implementation, with the caller's dropout PRNG key preserved).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .. import registry

NEG_INF = -1e30
# lane width for the m/l scratch rows and the lse/delta side outputs.
# Mosaic requires the last block dim to be 128-divisible (or equal to the
# array dim), so per-row scalars are carried lane-broadcast — the same
# layout the splash/flash kernels in jax.experimental.pallas.ops.tpu use
# (fp32 VMEM tiles are (8, 128)).
_LANES = 128


def _keep_mask(seed_ref, head, q_idx, k_idx, block_q, block_k, rate):
    """Per-element dropout keep-mask for one [block_q, block_k] tile.

    Counter-based hash PRNG (murmur3 fmix32 avalanche over global
    (head, row, col) + two seed words) in plain uint32 VPU ops rather
    than `pltpu.prng_random_bits`: the bits are a pure function of the
    GLOBAL element coordinates, so the forward and both backward kernels
    reproduce the identical mask with no per-tile seeding protocol (and
    with any block shape), and the CPU interpret-mode tests see the same
    numbers the hardware does (the TPU-interpret PRNG stub returns
    zeros). Reference parity: in-kernel dropout of
    `phi/kernels/gpu/flash_attn_kernel.cu` (philox counter PRNG).
    """
    rows = (q_idx * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)).astype(jnp.uint32)
    cols = (k_idx * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)).astype(jnp.uint32)
    s0 = seed_ref[0].astype(jnp.uint32)
    s1 = seed_ref[1].astype(jnp.uint32)
    h = (s0 * jnp.uint32(0x9E3779B9)
         + (head + 1).astype(jnp.uint32) * jnp.uint32(0x85EBCA6B) + s1)
    x = rows * jnp.uint32(0x27D4EB2F) + cols * jnp.uint32(0x165667B1) + h
    x ^= x >> 16
    x *= jnp.uint32(0x85EBCA6B)
    x ^= x >> 13
    x *= jnp.uint32(0xC2B2AE35)
    x ^= x >> 16
    threshold = jnp.uint32(min(int(rate * 4294967296.0), 4294967295))
    return x >= threshold


def _causal_mask(s, q_idx, k_idx, block_q, block_k, offset, window=0):
    """Bottom-right-aligned causal mask for one [block_q, block_k] tile.

    Global query row r may attend key col c iff  r + offset >= c,
    where offset = seq_k - seq_q. ``window > 0`` additionally bounds the
    lookback (sliding-window / Mistral-style local attention): c must
    also satisfy  c > r + offset - window.
    """
    rows = q_idx * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    cols = k_idx * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    keep = rows + offset >= cols
    if window > 0:
        keep &= cols > rows + offset - window
    return jnp.where(keep, s, NEG_INF)


def _tile_live(q_idx, k_idx, block_q, block_k, offset, window):
    """Whether a [block_q, block_k] tile intersects the (causal, window)
    band at all — fully-masked tiles skip their MXU work."""
    below_diag = k_idx * block_k < (q_idx + 1) * block_q + offset
    if window <= 0:
        return below_diag
    in_window = (k_idx + 1) * block_k > q_idx * block_q + offset - window + 1
    return below_diag & in_window


def _fwd_kernel(*refs, causal, scale, offset, n_kb, window=0, dropout=0.0):
    if dropout > 0.0:
        (seed_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
         acc_ref, m_ref, l_ref) = refs
    else:
        seed_ref = None
        (q_ref, k_ref, v_ref, o_ref, lse_ref,
         acc_ref, m_ref, l_ref) = refs
    b_idx = pl.program_id(0)
    q_idx = pl.program_id(1)
    k_idx = pl.program_id(2)
    block_q, d = q_ref.shape[1], q_ref.shape[2]
    block_k = k_ref.shape[1]

    @pl.when(k_idx == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def _step():
        q = q_ref[0].astype(jnp.float32) * scale
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [bq, bk]
        if causal:
            s = _causal_mask(s, q_idx, k_idx, block_q, block_k, offset,
                             window)
        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)
        if dropout > 0.0:
            # dropout acts on the POST-softmax probs: the denominator l
            # keeps the undropped sum, only the value-accumulator sees
            # the masked + 1/(1-rate)-rescaled probs
            keep = _keep_mask(seed_ref, b_idx, q_idx, k_idx,
                              block_q, block_k, dropout)
            p_acc = jnp.where(keep, p * (1.0 / (1.0 - dropout)), 0.0)
        else:
            p_acc = p
        acc_ref[...] = alpha * acc_ref[...] + jax.lax.dot_general(
            p_acc, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        # tiles fully outside the (causal, window) band are entirely
        # masked — skip their compute (their HBM fetch still happens;
        # the win is MXU time, which is the bottleneck here).
        pl.when(_tile_live(q_idx, k_idx, block_q, block_k, offset,
                           window))(_step)
    else:
        _step()

    @pl.when(k_idx == n_kb - 1)
    def _fini():
        m = m_ref[:, :1]
        l_safe = jnp.maximum(l_ref[:, :1], 1e-30)
        # rows with no valid key (bottom-right causal with sq > sk) output
        # exactly 0 — flash-attn >= 2.1 semantics, matched by the composite
        # fallback; m stays at NEG_INF iff every score was masked/skipped
        valid = m > NEG_INF * 0.5
        o_ref[0] = jnp.where(
            valid, acc_ref[...] / l_safe, 0.0).astype(o_ref.dtype)
        lse_ref[0] = jnp.broadcast_to(m + jnp.log(l_safe),
                                      lse_ref.shape[1:])


def _bwd_dq_kernel(*refs, causal, scale, offset, n_kb, window=0,
                   dropout=0.0):
    if dropout > 0.0:
        (seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dq_ref, dq_acc_ref) = refs
    else:
        seed_ref = None
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dq_ref, dq_acc_ref) = refs
    b_idx = pl.program_id(0)
    q_idx = pl.program_id(1)
    k_idx = pl.program_id(2)
    block_q = q_ref.shape[1]
    block_k = k_ref.shape[1]

    @pl.when(k_idx == 0)
    def _init():
        dq_acc_ref[...] = jnp.zeros_like(dq_acc_ref)

    def _step():
        q = q_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, :1]
        delta = delta_ref[0][:, :1]
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if causal:
            s = _causal_mask(s, q_idx, k_idx, block_q, block_k, offset,
                             window)
        # no-valid-key rows have lse ~ NEG_INF; exp(s - lse) would blow up
        p = jnp.where(lse > NEG_INF * 0.5, jnp.exp(s - lse), 0.0)  # [bq, bk]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if dropout > 0.0:
            # ds_ij = P_ij (D_ij dp_ij - delta_i) with D the keep/(1-r)
            # mask; delta already carries the dropped-out forward
            keep = _keep_mask(seed_ref, b_idx, q_idx, k_idx,
                              block_q, block_k, dropout)
            dp = jnp.where(keep, dp * (1.0 / (1.0 - dropout)), 0.0)
        ds = p * (dp - delta) * scale
        dq_acc_ref[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        pl.when(_tile_live(q_idx, k_idx, block_q, block_k, offset,
                           window))(_step)
    else:
        _step()

    @pl.when(k_idx == n_kb - 1)
    def _fini():
        dq_ref[0] = dq_acc_ref[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(*refs, causal, scale, offset, n_qb, n_iters, window=0,
                    dropout=0.0):
    """dk/dv accumulate over the q-minor grid dim, which iterates
    group × q-blocks under GQA (the same KV block serves every q head of
    its group; q_idx below is the position within one head's q blocks)."""
    if dropout > 0.0:
        (seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dk_acc_ref, dv_acc_ref) = refs
    else:
        seed_ref = None
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dk_acc_ref, dv_acc_ref) = refs
    b_idx = pl.program_id(0)
    k_idx = pl.program_id(1)
    q_iter = pl.program_id(2)
    q_idx = q_iter % n_qb
    block_k = k_ref.shape[1]
    block_q = q_ref.shape[1]

    @pl.when(q_iter == 0)
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    def _step():
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        q = q_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, :1]
        delta = delta_ref[0][:, :1]
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [bq, bk]
        if causal:
            s = _causal_mask(s, q_idx, k_idx, block_q, block_k, offset,
                             window)
        p = jnp.where(lse > NEG_INF * 0.5, jnp.exp(s - lse), 0.0)
        if dropout > 0.0:
            # GQA: the mask was drawn per QUERY head in the forward
            head = b_idx * (n_iters // n_qb) + q_iter // n_qb
            keep = _keep_mask(seed_ref, head, q_idx, k_idx,
                              block_q, block_k, dropout)
            dmask = jnp.where(keep, 1.0 / (1.0 - dropout), 0.0)
            pd = p * dmask
        else:
            dmask = None
            pd = p
        dv_acc_ref[...] += jax.lax.dot_general(
            pd, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if dropout > 0.0:
            dp = dp * dmask
        ds = p * (dp - delta) * scale
        dk_acc_ref[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        pl.when(_tile_live(q_idx, k_idx, block_q, block_k, offset,
                           window))(_step)
    else:
        _step()

    @pl.when(q_iter == n_iters - 1)
    def _fini():
        dk_ref[0] = dk_acc_ref[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc_ref[...].astype(dv_ref.dtype)


def _pick_block(seq, target=512):
    b = min(seq, target)
    while seq % b:
        b //= 2
    return max(b, 1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash_bhsd(q, k, v, causal, scale, interpret, block_q=None,
                block_k=None, window=0):
    out, _ = _flash_fwd(q, k, v, causal, scale, interpret, block_q,
                        block_k, window)
    return out


def _flash_fwd(q, k, v, causal, scale, interpret, block_q=None,
               block_k=None, window=0, seed=None, dropout=0.0):
    """q: [bh, s, d], k/v: [bh_kv, s, d] with bh % bh_kv == 0 (GQA: each
    group of bh//bh_kv query heads shares one KV head — the K/V BlockSpec
    index maps divide the bh program index, so grouped heads stream the
    same KV blocks without materializing repeated KV, matching the
    reference flash_attn kernel's num_heads_k support).

    Returns (out [bh, s, d], lse [bh, s, _LANES]) — lse lane-broadcast so
    its BlockSpec satisfies Mosaic's lane-divisibility rule; consumers
    read [..., :1].
    """
    bh, sq, d = q.shape
    sk = k.shape[1]
    group = bh // k.shape[0]
    block_q = block_q or _pick_block(sq)
    block_k = block_k or _pick_block(sk)
    n_kb = sk // block_k
    grid = (bh, sq // block_q, n_kb)
    kernel = functools.partial(_fwd_kernel, causal=causal, scale=scale,
                               offset=sk - sq, n_kb=n_kb, window=window,
                               dropout=dropout)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, d),
                     lambda b, i, j: (b // group, j, 0)),
        pl.BlockSpec((1, block_k, d),
                     lambda b, i, j: (b // group, j, 0)),
    ]
    args = (q, k, v)
    if dropout > 0.0:
        in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)] + in_specs
        args = (seed,) + args
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=(pltpu.PARALLEL, pltpu.PARALLEL, pltpu.ARBITRARY)),
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=int(4 * bh * sq * sk * d * (0.5 if causal else 1.0)),
            bytes_accessed=int(q.size * 2 + k.size * 2 + v.size * 2),
            transcendentals=int(bh * sq * sk),
        ),
    )(*args)
    return out, lse


def _flash_fwd_rule(q, k, v, causal, scale, interpret, block_q=None,
                    block_k=None, window=0):
    out, lse = _flash_fwd(q, k, v, causal, scale, interpret, block_q,
                          block_k, window)
    return out, (q, k, v, out, lse)


def _flash_bwd_rule(causal, scale, interpret, block_q, block_k, window,
                    res, g):
    q, k, v, out, lse = res
    return _flash_bwd_impl(q, k, v, out, lse, g, causal, scale, interpret,
                           block_q, block_k, window, None, 0.0)


def _flash_bwd_impl(q, k, v, out, lse, g, causal, scale, interpret,
                    block_q, block_k, window, seed, dropout):
    bh, sq, d = q.shape
    sk = k.shape[1]
    bh_kv = k.shape[0]
    group = bh // bh_kv
    block_q = block_q or _pick_block(sq)
    block_k = block_k or _pick_block(sk)
    n_qb = sq // block_q
    n_kb = sk // block_k
    offset = sk - sq
    g = g.astype(q.dtype)
    # delta_i = sum_d(do * o) per row (FlashAttention-2 eq. for ds),
    # lane-broadcast to match the lse layout (see _flash_fwd docstring)
    delta = jnp.broadcast_to(
        jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                axis=-1, keepdims=True),
        (bh, sq, _LANES))

    dq_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, d),
                     lambda b, i, j: (b // group, j, 0)),
        pl.BlockSpec((1, block_k, d),
                     lambda b, i, j: (b // group, j, 0)),
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_q, _LANES), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_q, _LANES), lambda b, i, j: (b, i, 0)),
    ]
    dq_args = (q, k, v, g, lse, delta)
    if dropout > 0.0:
        dq_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)] + dq_specs
        dq_args = (seed,) + dq_args
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, causal=causal, scale=scale,
                          offset=offset, n_kb=n_kb, window=window,
                          dropout=dropout),
        grid=(bh, n_qb, n_kb),
        in_specs=dq_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=(pltpu.PARALLEL, pltpu.PARALLEL, pltpu.ARBITRARY)),
        interpret=interpret,
    )(*dq_args)

    # dkv grid runs per KV head; the minor dim sweeps group × q-blocks so
    # grouped q heads accumulate into one dk/dv block (GQA)
    dkv_specs = [
        pl.BlockSpec((1, block_q, d),
                     lambda b, j, i: (b * group + i // n_qb,
                                      i % n_qb, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        pl.BlockSpec((1, block_q, d),
                     lambda b, j, i: (b * group + i // n_qb,
                                      i % n_qb, 0)),
        pl.BlockSpec((1, block_q, _LANES),
                     lambda b, j, i: (b * group + i // n_qb,
                                      i % n_qb, 0)),
        pl.BlockSpec((1, block_q, _LANES),
                     lambda b, j, i: (b * group + i // n_qb,
                                      i % n_qb, 0)),
    ]
    dkv_args = (q, k, v, g, lse, delta)
    if dropout > 0.0:
        dkv_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)] + dkv_specs
        dkv_args = (seed,) + dkv_args
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, causal=causal, scale=scale,
                          offset=offset, n_qb=n_qb,
                          n_iters=group * n_qb, window=window,
                          dropout=dropout),
        grid=(bh_kv, n_kb, group * n_qb),
        in_specs=dkv_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh_kv, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh_kv, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=(pltpu.PARALLEL, pltpu.PARALLEL, pltpu.ARBITRARY)),
        interpret=interpret,
    )(*dkv_args)
    return dq, dk, dv


_flash_bhsd.defvjp(_flash_fwd_rule, _flash_bwd_rule)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9, 10))
def _flash_bhsd_drop(q, k, v, seed, causal, scale, interpret,
                     block_q=None, block_k=None, window=0, dropout=0.0):
    """Dropout variant: `seed` is an int32[2] array (derived from the
    caller's dropout PRNG key) feeding the counter-hash mask — the same
    mask is regenerated in the backward kernels (see _keep_mask)."""
    out, _ = _flash_fwd(q, k, v, causal, scale, interpret, block_q,
                        block_k, window, seed=seed, dropout=dropout)
    return out


def _flash_fwd_rule_drop(q, k, v, seed, causal, scale, interpret,
                         block_q=None, block_k=None, window=0,
                         dropout=0.0):
    out, lse = _flash_fwd(q, k, v, causal, scale, interpret, block_q,
                          block_k, window, seed=seed, dropout=dropout)
    return out, (q, k, v, seed, out, lse)


def _flash_bwd_rule_drop(causal, scale, interpret, block_q, block_k,
                         window, dropout, res, g):
    q, k, v, seed, out, lse = res
    dq, dk, dv = _flash_bwd_impl(q, k, v, out, lse, g, causal, scale,
                                 interpret, block_q, block_k, window,
                                 seed, dropout)
    return dq, dk, dv, None


_flash_bhsd_drop.defvjp(_flash_fwd_rule_drop, _flash_bwd_rule_drop)


def flash_attention_kernel(q, k, v, *rest, causal=False, dropout=0.0,
                           has_key=False, default_fn=None,
                           interpret=False):
    """Kernel-registry entry: [b, s, h, d] inputs, same signature as the
    default XLA implementation in nn/functional/attention.py. When
    ``has_key`` the trailing operand is the dropout PRNG key's raw
    uint32 data; dropout then runs IN-KERNEL (reference
    flash_attn_kernel.cu supports in-kernel dropout — the round-4 gap
    that forced every dropout>0 call onto the composite). Falls back to
    ``default_fn`` for masks/odd shapes."""
    dkey = None
    if has_key and rest:
        *head_rest, dkey = rest
        rest = tuple(head_rest)

    def fallback(dp):
        arrs = (q, k, v) + rest + ((dkey,) if dkey is not None else ())
        if default_fn is not None:
            return default_fn(*arrs, causal=causal, dropout=dp,
                              has_key=dkey is not None)
        from ...nn.functional.attention import _sdpa_reference

        key_arr = (jax.random.wrap_key_data(dkey)
                   if dkey is not None else None)
        return _sdpa_reference(q, k, v, *rest, causal=causal, dropout=dp,
                               dropout_key=key_arr)

    if rest or (dropout > 0.0 and dkey is None):
        return fallback(dropout)
    b, sq, h, d = q.shape
    sk = k.shape[1]
    h_kv = k.shape[2]
    # d is never blocked, so any 8-multiple head_dim lowers (block dim ==
    # array dim); d=64 (BERT-base) engages the kernel, matching the
    # reference flash_attn kernel's head_dim support. GQA/MQA (h_kv < h)
    # streams shared KV blocks via index-map division. The seq blocks
    # must be sublane-aligned when they tile the sequence.
    bq, bk = _pick_block(sq), _pick_block(sk)
    ok_blocks = (bq == sq or bq % 8 == 0) and (bk == sk or bk % 8 == 0)
    if (sq < 16 or sk < 16 or d % 8 or h % h_kv or v.shape[2] != h_kv
            or not ok_blocks):
        return fallback(dropout)
    # engagement is measurement-driven: the autotune cache stores the
    # kernel-vs-composite fwd+bwd ratio per shape (tools/flash_autotune.py
    # on hardware). Where no measurement applies, fall back to the round-4
    # measured crossover (PERF.md, TPU v5e, DCE-free differential timing):
    # the kernel wins from seq >= 1024 at every measured head_dim (3.4-5.2x);
    # the composite wins below (0.37x at s=512 d=64).
    from . import autotune as _tune

    bq_t = bk_t = None
    if not interpret:
        # dropout variants have no dedicated tune rows yet: demand 20%
        # measured headroom over the composite before engaging the
        # dropout kernel on a no-dropout measurement (the mask adds
        # VPU hash+select work). The >=1024 heuristic rows measured
        # 3.4-6.1x, far above the margin.
        margin = 1.2 if dropout > 0.0 else 1.0
        beats = _tune.kernel_beats_composite(sq, sk, d, causal,
                                             margin=margin)
        if beats is False:
            return fallback(dropout)
        if beats is None and (max(sq, sk) < 1024 or not causal):
            # the >=1024 crossover is extrapolated from CAUSAL
            # measurements only (flash_tune.json has no non-causal
            # >=1024 rows yet); unmeasured non-causal shapes stay on
            # the composite until tools/flash_autotune.py measures them.
            # (dropout inherits the no-dropout engagement decision: the
            # mask adds only VPU integer work.)
            return fallback(dropout)
        bq_t, bk_t = _tune.best_blocks(sq, sk, d, causal)
    scale = 1.0 / math.sqrt(d)
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * h_kv, sk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * h_kv, sk, d)
    if dropout > 0.0:
        seed = jax.lax.bitcast_convert_type(
            jnp.asarray(dkey).reshape(2), jnp.int32)
        out = _flash_bhsd_drop(qt, kt, vt, seed, causal, scale, interpret,
                               bq_t, bk_t, 0, dropout)
    else:
        out = _flash_bhsd(qt, kt, vt, causal, scale, interpret, bq_t,
                          bk_t)
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)


def check_lowering():
    """Mosaic-lower fwd+bwd for platform 'tpu' at the kernel's contract
    shapes (BERT-base d=64, Llama d=128, cross-length) — runs on any host
    via jax.export, no chip needed."""
    shapes = [(8, 1024, 1024, 64), (8, 1024, 1024, 128), (4, 512, 1024, 128)]
    for bh, sq, sk, d in shapes:
        q = jnp.zeros((bh, sq, d), jnp.bfloat16)
        kv = jnp.zeros((bh, sk, d), jnp.bfloat16)
        scale = 1.0 / math.sqrt(d)

        def fwd(q, k, v, _s=scale):
            return _flash_bhsd(q, k, v, True, _s, False)

        def bwd(q, k, v, _s=scale):
            return jax.grad(
                lambda *a: fwd(*a, _s=_s).astype(jnp.float32).sum(),
                argnums=(0, 1, 2))(q, k, v)

        jax.export.export(jax.jit(fwd), platforms=["tpu"])(q, kv, kv)
        jax.export.export(jax.jit(bwd), platforms=["tpu"])(q, kv, kv)

    # sliding-window variant (window bands engage the tile-skip path)
    q = jnp.zeros((8, 1024, 128), jnp.bfloat16)
    kv = jnp.zeros((8, 1024, 128), jnp.bfloat16)

    def swa(q, k, v):
        return _flash_bhsd(q, k, v, True, 1.0 / math.sqrt(128.0), False,
                           None, None, 256)

    def swa_bwd(q, k, v):
        return jax.grad(
            lambda *a: swa(*a).astype(jnp.float32).sum(),
            argnums=(0, 1, 2))(q, k, v)

    jax.export.export(jax.jit(swa), platforms=["tpu"])(q, kv, kv)
    jax.export.export(jax.jit(swa_bwd), platforms=["tpu"])(q, kv, kv)

    # in-kernel dropout variant (counter-hash mask; uint32 VPU ops)
    seed = jnp.zeros((2,), jnp.int32)

    def drop(q, k, v, seed):
        return _flash_bhsd_drop(q, k, v, seed, True,
                                1.0 / math.sqrt(128.0), False, None, None,
                                0, 0.1)

    def drop_bwd(q, k, v, seed):
        return jax.grad(
            lambda *a: drop(*a, seed).astype(jnp.float32).sum(),
            argnums=(0, 1, 2))(q, k, v)

    jax.export.export(jax.jit(drop), platforms=["tpu"])(q, kv, kv, seed)
    jax.export.export(jax.jit(drop_bwd), platforms=["tpu"])(q, kv, kv,
                                                            seed)


def register(platform="tpu", interpret=False):
    fn = functools.partial(flash_attention_kernel, interpret=interpret)
    # ask dispatch to pass the caller's composite closure as default_fn so
    # fallback paths keep caller state (the live dropout PRNG key).
    fn.wants_default = True
    # the lowering self-check travels with the kernel so the pre-flight
    # (ops.pallas.check_tpu_lowering) covers every registered kernel
    fn.check_lowering = check_lowering
    registry.register_kernel("flash_attention", platform)(fn)
    return fn
