"""FlashAttention forward/backward as Pallas TPU kernels.

Reference parity: the reference binds the external FlashAttention CUDA
library as a PHI kernel (`paddle/phi/kernels/gpu/flash_attn_kernel.cu`,
`cmake/external/flashattn.cmake`). Here the same role is played by a
tiled streaming-softmax kernel pair written in Pallas (SURVEY §5.7:
"implement splash/flash attention in Pallas").

Algorithm: FlashAttention-2. The grid iterates over BOTH q-blocks and
k-blocks — the (max, sum, acc) streaming-softmax state lives in VMEM
scratch and is carried across the k-minor grid dimension, so VMEM usage is
O(block_q·block_k + block_q·d) regardless of sequence length (the whole
point of flash attention; round-1 kept full K/V rows in VMEM which capped
seq at a few K). Backward recomputes scores per block pair (dq kernel with
k-minor grid, dkv kernel with q-minor grid), also block-local VMEM only.

Causal masking is bottom-right aligned (rows of the score matrix count
back from the last key), matching flash-attn >= 2.1 and `_sdpa_reference`
in nn/functional/attention.py (`jnp.tril(..., k=sk-sq)`).

Layout: [batch, seq, heads, head_dim] — paddle's flash-attn layout —
processed as one (batch·head) per grid row.

Registered as the 'flash_attention' kernel override for platform 'tpu', so
`paddle.nn.functional.scaled_dot_product_attention` transparently uses it
on TPU. Dropout runs IN-KERNEL (counter-hash mask), and key-PADDING
masks ([b, 1, 1, sk] bool-keep or additive — the BERT/ERNIE pattern)
run in-kernel as an additive row; row-varying masks fall back to the
XLA composite with the caller's dropout PRNG key preserved.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...framework.jax_compat import export as _jax_export, tpu_compiler_params

from .. import registry

NEG_INF = -1e30
# lane width for the m/l scratch rows and the lse/delta side outputs.
# Mosaic requires the last block dim to be 128-divisible (or equal to the
# array dim), so per-row scalars are carried lane-broadcast — the same
# layout the splash/flash kernels in jax.experimental.pallas.ops.tpu use
# (fp32 VMEM tiles are (8, 128)).
_LANES = 128


def _keep_mask(seed_ref, head, q_idx, k_idx, block_q, block_k, rate):
    """Per-element dropout keep-mask for one [block_q, block_k] tile.

    Counter-based hash PRNG (murmur3 fmix32 avalanche over global
    (head, row, col) + two seed words) in plain uint32 VPU ops rather
    than `pltpu.prng_random_bits`: the bits are a pure function of the
    GLOBAL element coordinates, so the forward and both backward kernels
    reproduce the identical mask with no per-tile seeding protocol (and
    with any block shape), and the CPU interpret-mode tests see the same
    numbers the hardware does (the TPU-interpret PRNG stub returns
    zeros). Reference parity: in-kernel dropout of
    `phi/kernels/gpu/flash_attn_kernel.cu` (philox counter PRNG).
    """
    rows = (q_idx * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)).astype(jnp.uint32)
    cols = (k_idx * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)).astype(jnp.uint32)
    s0 = seed_ref[0].astype(jnp.uint32)
    s1 = seed_ref[1].astype(jnp.uint32)
    h = (s0 * jnp.uint32(0x9E3779B9)
         + (head + 1).astype(jnp.uint32) * jnp.uint32(0x85EBCA6B) + s1)
    x = rows * jnp.uint32(0x27D4EB2F) + cols * jnp.uint32(0x165667B1) + h
    x ^= x >> 16
    x *= jnp.uint32(0x85EBCA6B)
    x ^= x >> 13
    x *= jnp.uint32(0xC2B2AE35)
    x ^= x >> 16
    threshold = jnp.uint32(min(int(rate * 4294967296.0), 4294967295))
    return x >= threshold


def _causal_mask(s, q_idx, k_idx, block_q, block_k, offset, window=0):
    """Bottom-right-aligned causal mask for one [block_q, block_k] tile.

    Global query row r may attend key col c iff  r + offset >= c,
    where offset = seq_k - seq_q. ``window > 0`` additionally bounds the
    lookback (sliding-window / Mistral-style local attention): c must
    also satisfy  c > r + offset - window.
    """
    rows = q_idx * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    cols = k_idx * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    keep = rows + offset >= cols
    if window > 0:
        keep &= cols > rows + offset - window
    return jnp.where(keep, s, NEG_INF)


def _tile_live(q_idx, k_idx, block_q, block_k, offset, window):
    """Whether a [block_q, block_k] tile intersects the (causal, window)
    band at all — fully-masked tiles skip their MXU work."""
    below_diag = k_idx * block_k < (q_idx + 1) * block_q + offset
    if window <= 0:
        return below_diag
    in_window = (k_idx + 1) * block_k > q_idx * block_q + offset - window + 1
    return below_diag & in_window


def _unpack(refs, dropout, has_kmask, n_main):
    """refs = [seed?] + main inputs + [kmask?] + outputs/scratch."""
    i = 0
    seed_ref = None
    if dropout > 0.0:
        seed_ref = refs[0]
        i = 1
    main = refs[i:i + n_main]
    i += n_main
    km_ref = None
    if has_kmask:
        km_ref = refs[i]
        i += 1
    return (seed_ref, km_ref) + tuple(main) + tuple(refs[i:])


def _fwd_kernel(*refs, causal, scale, offset, n_kb, window=0, dropout=0.0,
                has_kmask=False):
    (seed_ref, km_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
     acc_ref, m_ref, l_ref) = _unpack(refs, dropout, has_kmask, 3)
    b_idx = pl.program_id(0)
    q_idx = pl.program_id(1)
    k_idx = pl.program_id(2)
    block_q, d = q_ref.shape[1], q_ref.shape[2]
    block_k = k_ref.shape[1]

    @pl.when(k_idx == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def _step():
        q = q_ref[0].astype(jnp.float32) * scale
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [bq, bk]
        if causal:
            s = _causal_mask(s, q_idx, k_idx, block_q, block_k, offset,
                             window)
        if has_kmask:
            s = s + km_ref[0]  # [1, bk] additive key mask, row-broadcast
        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)
        if dropout > 0.0:
            # dropout acts on the POST-softmax probs: the denominator l
            # keeps the undropped sum, only the value-accumulator sees
            # the masked + 1/(1-rate)-rescaled probs
            keep = _keep_mask(seed_ref, b_idx, q_idx, k_idx,
                              block_q, block_k, dropout)
            p_acc = jnp.where(keep, p * (1.0 / (1.0 - dropout)), 0.0)
        else:
            p_acc = p
        acc_ref[...] = alpha * acc_ref[...] + jax.lax.dot_general(
            p_acc, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        # tiles fully outside the (causal, window) band are entirely
        # masked — skip their compute (their HBM fetch still happens;
        # the win is MXU time, which is the bottleneck here).
        pl.when(_tile_live(q_idx, k_idx, block_q, block_k, offset,
                           window))(_step)
    else:
        _step()

    @pl.when(k_idx == n_kb - 1)
    def _fini():
        m = m_ref[:, :1]
        l_safe = jnp.maximum(l_ref[:, :1], 1e-30)
        # rows with no valid key (bottom-right causal with sq > sk) output
        # exactly 0 — flash-attn >= 2.1 semantics, matched by the composite
        # fallback; m stays at NEG_INF iff every score was masked/skipped
        valid = m > NEG_INF * 0.5
        o_ref[0] = jnp.where(
            valid, acc_ref[...] / l_safe, 0.0).astype(o_ref.dtype)
        lse_ref[0] = jnp.broadcast_to(m + jnp.log(l_safe),
                                      lse_ref.shape[1:])


def _bwd_dq_kernel(*refs, causal, scale, offset, n_kb, window=0,
                   dropout=0.0, has_kmask=False):
    (seed_ref, km_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
     dq_ref, dq_acc_ref) = _unpack(refs, dropout, has_kmask, 6)
    b_idx = pl.program_id(0)
    q_idx = pl.program_id(1)
    k_idx = pl.program_id(2)
    block_q = q_ref.shape[1]
    block_k = k_ref.shape[1]

    @pl.when(k_idx == 0)
    def _init():
        dq_acc_ref[...] = jnp.zeros_like(dq_acc_ref)

    def _step():
        q = q_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, :1]
        delta = delta_ref[0][:, :1]
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if causal:
            s = _causal_mask(s, q_idx, k_idx, block_q, block_k, offset,
                             window)
        if has_kmask:
            s = s + km_ref[0]
        # no-valid-key rows have lse ~ NEG_INF; exp(s - lse) would blow up
        p = jnp.where(lse > NEG_INF * 0.5, jnp.exp(s - lse), 0.0)  # [bq, bk]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if dropout > 0.0:
            # ds_ij = P_ij (D_ij dp_ij - delta_i) with D the keep/(1-r)
            # mask; delta already carries the dropped-out forward
            keep = _keep_mask(seed_ref, b_idx, q_idx, k_idx,
                              block_q, block_k, dropout)
            dp = jnp.where(keep, dp * (1.0 / (1.0 - dropout)), 0.0)
        ds = p * (dp - delta) * scale
        dq_acc_ref[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        pl.when(_tile_live(q_idx, k_idx, block_q, block_k, offset,
                           window))(_step)
    else:
        _step()

    @pl.when(k_idx == n_kb - 1)
    def _fini():
        dq_ref[0] = dq_acc_ref[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(*refs, causal, scale, offset, n_qb, n_iters, window=0,
                    dropout=0.0, has_kmask=False):
    """dk/dv accumulate over the q-minor grid dim, which iterates
    group × q-blocks under GQA (the same KV block serves every q head of
    its group; q_idx below is the position within one head's q blocks)."""
    if has_kmask:
        (seed_ref, km_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
         delta_ref, dk_ref, dv_ref, dm_ref, dk_acc_ref, dv_acc_ref,
         dm_acc_ref) = _unpack(refs, dropout, True, 6)
    else:
        (seed_ref, km_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
         delta_ref, dk_ref, dv_ref, dk_acc_ref, dv_acc_ref) = _unpack(
            refs, dropout, False, 6)
        dm_ref = dm_acc_ref = None
    b_idx = pl.program_id(0)
    k_idx = pl.program_id(1)
    q_iter = pl.program_id(2)
    q_idx = q_iter % n_qb
    block_k = k_ref.shape[1]
    block_q = q_ref.shape[1]

    @pl.when(q_iter == 0)
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    if has_kmask:
        # the mask cotangent accumulates PER Q HEAD (the mask rides per
        # query head): reset at each head's first q-block, write at its
        # last — q_iter sweeps group x q-blocks head-major
        @pl.when(q_idx == 0)
        def _dm_init():
            dm_acc_ref[...] = jnp.zeros_like(dm_acc_ref)

    def _step():
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        q = q_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, :1]
        delta = delta_ref[0][:, :1]
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [bq, bk]
        if causal:
            s = _causal_mask(s, q_idx, k_idx, block_q, block_k, offset,
                             window)
        if has_kmask:
            s = s + km_ref[0]
        p = jnp.where(lse > NEG_INF * 0.5, jnp.exp(s - lse), 0.0)
        if dropout > 0.0:
            # GQA: the mask was drawn per QUERY head in the forward
            head = b_idx * (n_iters // n_qb) + q_iter // n_qb
            keep = _keep_mask(seed_ref, head, q_idx, k_idx,
                              block_q, block_k, dropout)
            dmask = jnp.where(keep, 1.0 / (1.0 - dropout), 0.0)
            pd = p * dmask
        else:
            dmask = None
            pd = p
        dv_acc_ref[...] += jax.lax.dot_general(
            pd, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if dropout > 0.0:
            dp = dp * dmask
        ds = p * (dp - delta) * scale
        dk_acc_ref[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        if has_kmask:
            # d(mask_j) = sum_i ds_ij / scale (the mask adds to s AFTER
            # the scale multiply, and ds above carries one scale factor
            # from d(s_pre_mask)/dq path — the additive-bias cotangent
            # is sum_i dL/ds_ij = sum_i p*(dp - delta))
            dm_acc_ref[0:1, :] += jnp.sum(ds / scale, axis=0,
                                          keepdims=True)

    if causal:
        pl.when(_tile_live(q_idx, k_idx, block_q, block_k, offset,
                           window))(_step)
    else:
        _step()

    @pl.when(q_iter == n_iters - 1)
    def _fini():
        dk_ref[0] = dk_acc_ref[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc_ref[...].astype(dv_ref.dtype)

    if has_kmask:
        @pl.when(q_idx == n_qb - 1)
        def _dm_fini():
            dm_ref[0] = dm_acc_ref[0:1, :].astype(dm_ref.dtype)


def _pick_block(seq, target=512):
    b = min(seq, target)
    while seq % b:
        b //= 2
    return max(b, 1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10, 11))
def _flash_call(q, k, v, seed, kmask, causal, scale, interpret,
                block_q=None, block_k=None, window=0, dropout=0.0):
    """The one differentiable entry all variants route through.
    ``seed`` (int32[2] or None) enables in-kernel dropout; ``kmask``
    ([bh, 1, sk] additive fp32 or None) enables the in-kernel key
    mask."""
    out, _ = _flash_fwd(q, k, v, causal, scale, interpret, block_q,
                        block_k, window, seed=seed, dropout=dropout,
                        kmask=kmask)
    return out


def _flash_call_fwd_rule(q, k, v, seed, kmask, causal, scale, interpret,
                         block_q=None, block_k=None, window=0,
                         dropout=0.0):
    out, lse = _flash_fwd(q, k, v, causal, scale, interpret, block_q,
                          block_k, window, seed=seed, dropout=dropout,
                          kmask=kmask)
    return out, (q, k, v, seed, kmask, out, lse)


def _flash_call_bwd_rule(causal, scale, interpret, block_q, block_k,
                         window, dropout, res, g):
    q, k, v, seed, kmask, out, lse = res
    dq, dk, dv, dmask = _flash_bwd_impl(q, k, v, out, lse, g, causal,
                                        scale, interpret, block_q,
                                        block_k, window, seed, dropout,
                                        kmask=kmask)
    return dq, dk, dv, None, dmask


_flash_call.defvjp(_flash_call_fwd_rule, _flash_call_bwd_rule)


def _flash_bhsd(q, k, v, causal, scale, interpret, block_q=None,
                block_k=None, window=0):
    return _flash_call(q, k, v, None, None, causal, scale, interpret,
                       block_q, block_k, window, 0.0)


def _flash_fwd(q, k, v, causal, scale, interpret, block_q=None,
               block_k=None, window=0, seed=None, dropout=0.0,
               kmask=None):
    """q: [bh, s, d], k/v: [bh_kv, s, d] with bh % bh_kv == 0 (GQA: each
    group of bh//bh_kv query heads shares one KV head — the K/V BlockSpec
    index maps divide the bh program index, so grouped heads stream the
    same KV blocks without materializing repeated KV, matching the
    reference flash_attn kernel's num_heads_k support).

    Returns (out [bh, s, d], lse [bh, s, _LANES]) — lse lane-broadcast so
    its BlockSpec satisfies Mosaic's lane-divisibility rule; consumers
    read [..., :1].
    """
    bh, sq, d = q.shape
    sk = k.shape[1]
    group = bh // k.shape[0]
    block_q = block_q or _pick_block(sq)
    block_k = block_k or _pick_block(sk)
    n_kb = sk // block_k
    grid = (bh, sq // block_q, n_kb)
    kernel = functools.partial(_fwd_kernel, causal=causal, scale=scale,
                               offset=sk - sq, n_kb=n_kb, window=window,
                               dropout=dropout,
                               has_kmask=kmask is not None)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, d),
                     lambda b, i, j: (b // group, j, 0)),
        pl.BlockSpec((1, block_k, d),
                     lambda b, i, j: (b // group, j, 0)),
    ]
    args = (q, k, v)
    if kmask is not None:
        # additive key mask [bh, 1, sk]: middle singleton keeps the
        # block 3-D so Mosaic's last-two-dims rule is satisfied
        in_specs = in_specs + [
            pl.BlockSpec((1, 1, block_k), lambda b, i, j: (b, 0, j))]
        args = args + (kmask,)
    if dropout > 0.0:
        in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)] + in_specs
        args = (seed,) + args
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=int(4 * bh * sq * sk * d * (0.5 if causal else 1.0)),
            bytes_accessed=int(q.size * 2 + k.size * 2 + v.size * 2),
            transcendentals=int(bh * sq * sk),
        ),
    )(*args)
    return out, lse


def _flash_bwd_impl(q, k, v, out, lse, g, causal, scale, interpret,
                    block_q, block_k, window, seed, dropout, kmask=None):
    bh, sq, d = q.shape
    sk = k.shape[1]
    bh_kv = k.shape[0]
    group = bh // bh_kv
    block_q = block_q or _pick_block(sq)
    block_k = block_k or _pick_block(sk)
    n_qb = sq // block_q
    n_kb = sk // block_k
    offset = sk - sq
    g = g.astype(q.dtype)
    # delta_i = sum_d(do * o) per row (FlashAttention-2 eq. for ds),
    # lane-broadcast to match the lse layout (see _flash_fwd docstring)
    delta = jnp.broadcast_to(
        jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                axis=-1, keepdims=True),
        (bh, sq, _LANES))

    dq_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, d),
                     lambda b, i, j: (b // group, j, 0)),
        pl.BlockSpec((1, block_k, d),
                     lambda b, i, j: (b // group, j, 0)),
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_q, _LANES), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_q, _LANES), lambda b, i, j: (b, i, 0)),
    ]
    dq_args = (q, k, v, g, lse, delta)
    if kmask is not None:
        dq_specs = dq_specs + [
            pl.BlockSpec((1, 1, block_k), lambda b, i, j: (b, 0, j))]
        dq_args = dq_args + (kmask,)
    if dropout > 0.0:
        dq_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)] + dq_specs
        dq_args = (seed,) + dq_args
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, causal=causal, scale=scale,
                          offset=offset, n_kb=n_kb, window=window,
                          dropout=dropout,
                          has_kmask=kmask is not None),
        grid=(bh, n_qb, n_kb),
        in_specs=dq_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*dq_args)

    # dkv grid runs per KV head; the minor dim sweeps group × q-blocks so
    # grouped q heads accumulate into one dk/dv block (GQA)
    dkv_specs = [
        pl.BlockSpec((1, block_q, d),
                     lambda b, j, i: (b * group + i // n_qb,
                                      i % n_qb, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        pl.BlockSpec((1, block_q, d),
                     lambda b, j, i: (b * group + i // n_qb,
                                      i % n_qb, 0)),
        pl.BlockSpec((1, block_q, _LANES),
                     lambda b, j, i: (b * group + i // n_qb,
                                      i % n_qb, 0)),
        pl.BlockSpec((1, block_q, _LANES),
                     lambda b, j, i: (b * group + i // n_qb,
                                      i % n_qb, 0)),
    ]
    dkv_args = (q, k, v, g, lse, delta)
    dkv_out_specs = [
        pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
    ]
    dkv_out_shape = [
        jax.ShapeDtypeStruct((bh_kv, sk, d), k.dtype),
        jax.ShapeDtypeStruct((bh_kv, sk, d), v.dtype),
    ]
    dkv_scratch = [
        pltpu.VMEM((block_k, d), jnp.float32),
        pltpu.VMEM((block_k, d), jnp.float32),
    ]
    if kmask is not None:
        dkv_specs = dkv_specs + [
            pl.BlockSpec((1, 1, block_k),
                         lambda b, j, i: (b * group + i // n_qb, 0, j))]
        dkv_args = dkv_args + (kmask,)
        # third output: the mask cotangent, accumulated per q head
        dkv_out_specs = dkv_out_specs + [
            pl.BlockSpec((1, 1, block_k),
                         lambda b, j, i: (b * group + i // n_qb, 0, j))]
        dkv_out_shape = dkv_out_shape + [
            jax.ShapeDtypeStruct((bh, 1, sk), jnp.float32)]
        dkv_scratch = dkv_scratch + [
            pltpu.VMEM((8, block_k), jnp.float32)]
    if dropout > 0.0:
        dkv_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)] + dkv_specs
        dkv_args = (seed,) + dkv_args
    outs = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, causal=causal, scale=scale,
                          offset=offset, n_qb=n_qb,
                          n_iters=group * n_qb, window=window,
                          dropout=dropout,
                          has_kmask=kmask is not None),
        grid=(bh_kv, n_kb, group * n_qb),
        in_specs=dkv_specs,
        out_specs=dkv_out_specs,
        out_shape=dkv_out_shape,
        scratch_shapes=dkv_scratch,
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*dkv_args)
    if kmask is not None:
        dk, dv, dmask = outs
        return dq, dk, dv, dmask
    dk, dv = outs
    return dq, dk, dv, None


def _flash_bhsd_drop(q, k, v, seed, causal, scale, interpret,
                     block_q=None, block_k=None, window=0, dropout=0.0):
    """Dropout variant: `seed` is an int32[2] array (derived from the
    caller's dropout PRNG key) feeding the counter-hash mask — the same
    mask is regenerated in the backward kernels (see _keep_mask)."""
    return _flash_call(q, k, v, seed, None, causal, scale, interpret,
                       block_q, block_k, window, dropout)


def flash_attention_kernel(q, k, v, *rest, causal=False, dropout=0.0,
                           has_key=False, default_fn=None,
                           interpret=False):
    """Kernel-registry entry: [b, s, h, d] inputs, same signature as the
    default XLA implementation in nn/functional/attention.py. When
    ``has_key`` the trailing operand is the dropout PRNG key's raw
    uint32 data; dropout then runs IN-KERNEL (reference
    flash_attn_kernel.cu supports in-kernel dropout — the round-4 gap
    that forced every dropout>0 call onto the composite). Key-padding
    masks run in-kernel too (_key_padding_additive); row-varying masks
    and odd shapes fall back to ``default_fn``."""
    dkey = None
    if has_key and rest:
        *head_rest, dkey = rest
        rest = tuple(head_rest)

    from . import search as _search

    def fallback(dp):
        _search.note_fallback("flash")
        arrs = (q, k, v) + rest + ((dkey,) if dkey is not None else ())
        if default_fn is not None:
            return default_fn(*arrs, causal=causal, dropout=dp,
                              has_key=dkey is not None)
        from ...nn.functional.attention import _sdpa_reference

        key_arr = (jax.random.wrap_key_data(dkey)
                   if dkey is not None else None)
        return _sdpa_reference(q, k, v, *rest, causal=causal, dropout=dp,
                               dropout_key=key_arr)

    kadd = None
    if rest:
        # key-PADDING masks ([b, 1, 1, sk], bool keep or additive float
        # — the BERT/ERNIE right-pad pattern) run IN-KERNEL as an
        # additive row; anything row-varying ([.., sq, sk]) falls back
        if len(rest) == 1:
            kadd = _key_padding_additive(rest[0], q.shape, k.shape)
        if kadd is None:
            return fallback(dropout)
    if dropout > 0.0 and dkey is None:
        return fallback(dropout)
    b, sq, h, d = q.shape
    sk = k.shape[1]
    h_kv = k.shape[2]
    # d is never blocked, so any 8-multiple head_dim lowers (block dim ==
    # array dim); d=64 (BERT-base) engages the kernel, matching the
    # reference flash_attn kernel's head_dim support. GQA/MQA (h_kv < h)
    # streams shared KV blocks via index-map division. The seq blocks
    # must be sublane-aligned when they tile the sequence.
    bq, bk = _pick_block(sq), _pick_block(sk)
    ok_blocks = (bq == sq or bq % 8 == 0) and (bk == sk or bk % 8 == 0)
    if (sq < 16 or sk < 16 or d % 8 or h % h_kv or v.shape[2] != h_kv
            or not ok_blocks):
        return fallback(dropout)
    # engagement is measurement-driven: the autotune cache stores the
    # kernel-vs-composite fwd+bwd ratio per shape (tools/flash_autotune.py
    # on hardware). Where no measurement applies, fall back to the round-4
    # measured crossover (PERF.md, TPU v5e, DCE-free differential timing):
    # the kernel wins from seq >= 1024 at every measured head_dim (3.4-5.2x);
    # the composite wins below (0.37x at s=512 d=64).
    from . import autotune as _tune

    scale = 1.0 / math.sqrt(d)
    if not interpret:
        # head-BATCHED variant (head_flash.py — no transpose pair):
        # exact-key measured engagement only, from the search harness's
        # flash_headbatch rows; the variant key markers keep dropout /
        # mask calls disengaged until their own rows exist
        from . import head_flash as _hb

        hb_key = _hb.shape_key(b, sq, sk, h, h_kv, d, causal,
                               dropout > 0.0, kadd is not None)
        if _search.engaged("flash_headbatch", hb_key):
            cfg = _search.best_config("flash_headbatch", hb_key) or {}
            hb_seed = None
            if dropout > 0.0:
                hb_seed = jax.lax.bitcast_convert_type(
                    jnp.asarray(dkey).reshape(2), jnp.int32)
            _search.note_engaged("flash_headbatch")
            return _hb.hb_flash(q, k, v, hb_seed, kadd, causal, scale,
                                False, cfg.get("block_q"),
                                cfg.get("block_k"), 0, dropout)

    bq_t = bk_t = None
    if not interpret:
        # dropout/mask variants have no dedicated tune rows yet: demand
        # 20% measured headroom over the composite before engaging them
        # on an unmasked no-dropout measurement (dropout adds VPU
        # hash+select work; the mask adds an HBM operand per tile). The
        # >=1024 heuristic rows measured 3.4-6.1x, far above the margin.
        margin = 1.2 if (dropout > 0.0 or kadd is not None) else 1.0
        # the dropout-variant row was measured WITHOUT a mask operand:
        # it may replace the margin only when no mask rides along
        beats = _tune.kernel_beats_composite(
            sq, sk, d, causal, margin=margin,
            dropout=0.0 if kadd is not None else dropout)
        if beats is False:
            return fallback(dropout)
        if beats is None and (max(sq, sk) < 1024 or not causal):
            # the >=1024 crossover is extrapolated from CAUSAL
            # measurements only (flash_tune.json has no non-causal
            # >=1024 rows yet); unmeasured non-causal shapes stay on
            # the composite until tools/flash_autotune.py measures them.
            # (dropout inherits the no-dropout engagement decision: the
            # mask adds only VPU integer work.)
            return fallback(dropout)
        bq_t, bk_t = _tune.best_blocks(sq, sk, d, causal)
    _search.note_engaged("flash")
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * h_kv, sk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * h_kv, sk, d)
    seed = None
    if dropout > 0.0:
        seed = jax.lax.bitcast_convert_type(
            jnp.asarray(dkey).reshape(2), jnp.int32)
    kmask = None
    if kadd is not None:
        # [b, 1, sk] -> per-query-head rows [bh, 1, sk]
        kmask = jnp.broadcast_to(kadd[:, None],
                                 (b, h, 1, sk)).reshape(b * h, 1, sk)
    out = _flash_call(qt, kt, vt, seed, kmask, causal, scale, interpret,
                      bq_t, bk_t, 0, dropout)
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)


def _key_padding_additive(mask, q_shape, k_shape):
    """[b, 1, 1, sk] (or [b, 1, sk] / [b, sk]) key-padding mask ->
    additive fp32 [b, 1, sk], or None when the mask is row-varying /
    head-varying (those fall back to the composite). Bool means KEEP;
    floats are additive and clamped to NEG_INF so a fully-masked row
    cannot produce inf - inf in the streaming softmax."""
    b = q_shape[0]
    sk = k_shape[1]
    # ONLY [b, 1, 1, sk]: the composite's `logits + mask` broadcast
    # gives 3-D/2-D shapes different (head-bound) semantics, so
    # accepting them here would make semantics depend on which path
    # engages
    if tuple(mask.shape) != (b, 1, 1, sk):
        return None
    m = mask.reshape(b, 1, sk)
    if m.dtype == jnp.bool_:
        return jnp.where(m, 0.0, NEG_INF).astype(jnp.float32)
    if not jnp.issubdtype(m.dtype, jnp.floating):
        return None
    return jnp.maximum(m.astype(jnp.float32), NEG_INF)


def check_lowering():
    """Mosaic-lower fwd+bwd for platform 'tpu' at the kernel's contract
    shapes (BERT-base d=64, Llama d=128, cross-length) — runs on any host
    via jax.export, no chip needed."""
    shapes = [(8, 1024, 1024, 64), (8, 1024, 1024, 128), (4, 512, 1024, 128)]
    for bh, sq, sk, d in shapes:
        q = jnp.zeros((bh, sq, d), jnp.bfloat16)
        kv = jnp.zeros((bh, sk, d), jnp.bfloat16)
        scale = 1.0 / math.sqrt(d)

        def fwd(q, k, v, _s=scale):
            return _flash_bhsd(q, k, v, True, _s, False)

        def bwd(q, k, v, _s=scale):
            return jax.grad(
                lambda *a: fwd(*a, _s=_s).astype(jnp.float32).sum(),
                argnums=(0, 1, 2))(q, k, v)

        _jax_export.export(jax.jit(fwd), platforms=["tpu"])(q, kv, kv)
        _jax_export.export(jax.jit(bwd), platforms=["tpu"])(q, kv, kv)

    # sliding-window variant (window bands engage the tile-skip path)
    q = jnp.zeros((8, 1024, 128), jnp.bfloat16)
    kv = jnp.zeros((8, 1024, 128), jnp.bfloat16)

    def swa(q, k, v):
        return _flash_bhsd(q, k, v, True, 1.0 / math.sqrt(128.0), False,
                           None, None, 256)

    def swa_bwd(q, k, v):
        return jax.grad(
            lambda *a: swa(*a).astype(jnp.float32).sum(),
            argnums=(0, 1, 2))(q, k, v)

    _jax_export.export(jax.jit(swa), platforms=["tpu"])(q, kv, kv)
    _jax_export.export(jax.jit(swa_bwd), platforms=["tpu"])(q, kv, kv)

    # in-kernel key-padding mask variant
    q = jnp.zeros((8, 1024, 128), jnp.bfloat16)
    kv = jnp.zeros((8, 1024, 128), jnp.bfloat16)
    km = jnp.zeros((8, 1, 1024), jnp.float32)

    def masked(q, k, v, km):
        return _flash_call(q, k, v, None, km, False,
                           1.0 / math.sqrt(128.0), False, None, None, 0,
                           0.0)

    def masked_bwd(q, k, v, km):
        return jax.grad(
            lambda *a: masked(*a, km).astype(jnp.float32).sum(),
            argnums=(0, 1, 2))(q, k, v)

    _jax_export.export(jax.jit(masked), platforms=["tpu"])(q, kv, kv, km)
    _jax_export.export(jax.jit(masked_bwd), platforms=["tpu"])(q, kv, kv,
                                                              km)

    # in-kernel dropout variant (counter-hash mask; uint32 VPU ops)
    seed = jnp.zeros((2,), jnp.int32)

    def drop(q, k, v, seed):
        return _flash_bhsd_drop(q, k, v, seed, True,
                                1.0 / math.sqrt(128.0), False, None, None,
                                0, 0.1)

    def drop_bwd(q, k, v, seed):
        return jax.grad(
            lambda *a: drop(*a, seed).astype(jnp.float32).sum(),
            argnums=(0, 1, 2))(q, k, v)

    _jax_export.export(jax.jit(drop), platforms=["tpu"])(q, kv, kv, seed)
    _jax_export.export(jax.jit(drop_bwd), platforms=["tpu"])(q, kv, kv,
                                                            seed)


def register(platform="tpu", interpret=False):
    fn = functools.partial(flash_attention_kernel, interpret=interpret)
    # ask dispatch to pass the caller's composite closure as default_fn so
    # fallback paths keep caller state (the live dropout PRNG key).
    fn.wants_default = True
    # the lowering self-check travels with the kernel so the pre-flight
    # (ops.pallas.check_tpu_lowering) covers every registered kernel
    fn.check_lowering = check_lowering
    registry.register_kernel("flash_attention", platform)(fn)
    return fn
