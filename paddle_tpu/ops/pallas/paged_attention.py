"""Paged-attention decode kernel: gather KV straight from the block pool.

The serving engine's decode step (`serving/engine.py`) currently
materializes each lane's KV with a dense gather —
``kpool[tables].reshape(L, M*B, ...)`` — and attends over ALL ``M*B``
slots with a mask. Every decode round therefore reads each lane's WHOLE
table worth of KV from HBM, live or not; the serving bench's
``hbm_util`` gap quantifies the waste (decode is bandwidth-bound —
PERF.md). This kernel is the PagedAttention read path done TPU-style:
one grid row per (lane, table-slot), the K/V BlockSpec index maps
resolve through the lane's block table (scalar-prefetch — the table and
the per-lane lengths arrive before the body runs), and iterations past
the lane's live prefix REPEAT the previous block index, which the
Pallas pipeline recognizes as "block unchanged" and elides the DMA — so
HBM traffic is ``pool_len`` live tokens per lane, not ``M·B``.

The math mirrors ``serving/engine.py:_attend_lanes`` (fp32 grouped-GQA
dots, 1/sqrt(d), -1e30 masking) as a streaming softmax over table
slots; masked slots carry exactly-zero weight, so engine outputs stay
token-identical to ``generate()`` (tests/test_serving.py extends the
token-identity proof to this path).

Ships **disengaged by default**: the engine's auto mode consults the
search harness's ``paged_attention`` tune-table row for this geometry
(``ops/pallas/search.py``; engagement = measured-faster-than-the-dense-
gather only) and the tunnel is down, so the first hardware row lands
via ``tools/hwbench.py``'s ``kernel_search`` stage next chip-up.
``PT_SERVE_PAGED=1/0`` forces it on/off (docs/SERVING.md).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...framework.jax_compat import export as _jax_export, tpu_compiler_params
from .. import registry
from . import search

__all__ = ["paged_attend", "paged_attend_int8", "family_key",
           "check_lowering", "check_lowering_int8", "register"]

NEG_INF = -1e30
_LANES = 128


def _paged_kernel(tab_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
                  acc_ref, m_ref, l_ref, *, block_size, n_blocks,
                  nkv, g, window=0):
    """One (lane, table-slot) grid step of the streaming softmax.
    ``tab_ref``/``pos_ref`` are scalar-prefetch refs (also consumed by
    the K/V index maps); state lives in VMEM scratch across the
    slot-minor grid dim."""
    l_idx = pl.program_id(0)
    m_idx = pl.program_id(1)
    p = pos_ref[l_idx]
    nh = nkv * g
    B = block_size
    nb = p // B + 1  # live blocks: slots 0..p are visible

    @pl.when(m_idx == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(m_idx < nb)
    def _step():
        # per-KV-head loop of 2-D dots (Mosaic lowers only 2-D dots;
        # a [nkv, g, B]-batched formulation does not) — the g grouped
        # query heads of each KV head are a CONTIGUOUS static row slice
        # of q, so GQA costs no relayout
        slots = m_idx * B + jax.lax.broadcasted_iota(jnp.int32, (g, B),
                                                     1)
        vis = slots <= p
        if window > 0:
            vis &= slots > p - window
        for j in range(nkv):
            q = q_ref[0, j * g:(j + 1) * g, :].astype(jnp.float32)
            k = k_ref[0, :, j, :].astype(jnp.float32)   # [B, d]
            v = v_ref[0, :, j, :].astype(jnp.float32)
            d = q.shape[-1]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)  # [g, B]
            s = jnp.where(vis, s * (1.0 / math.sqrt(d)), NEG_INF)
            rows = slice(j * g, (j + 1) * g)
            m_prev = m_ref[rows, :1]
            l_prev = l_ref[rows, :1]
            m_new = jnp.maximum(m_prev,
                                jnp.max(s, axis=1, keepdims=True))
            pexp = jnp.exp(s - m_new)
            alpha = jnp.exp(m_prev - m_new)
            l_new = alpha * l_prev + jnp.sum(pexp, axis=1,
                                             keepdims=True)
            m_ref[rows] = jnp.broadcast_to(m_new, (g, m_ref.shape[1]))
            l_ref[rows] = jnp.broadcast_to(l_new, (g, l_ref.shape[1]))
            acc_ref[rows] = alpha * acc_ref[rows] + jax.lax.dot_general(
                pexp, v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

    @pl.when(m_idx == n_blocks - 1)
    def _fini():
        l_safe = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)


def paged_attend(q, kpool, vpool, tables, pos, *, window=0,
                 dead="clamp", interpret=False):
    """Decode-phase paged attention.

    q: ``[L, nh, d]`` — each lane's single pending-token query (already
    RoPE'd); kpool/vpool: ``[num_blocks, B, nkv, d]`` — ONE layer's
    block pool; tables: ``[L, M]`` int32 block tables; pos: ``[L]``
    int32 — the pending token's absolute position (slot ``l`` is
    visible iff ``l <= pos``, matching `_attend_lanes`). Returns
    ``[L, nh, d]``.

    ``dead`` picks the dead-iteration indexing strategy (the family's
    candidate axis): ``"clamp"`` repeats the lane's last LIVE block
    index so every dead iteration elides its DMA entirely; ``"null"``
    redirects dead iterations to null block 0 (one extra block fetch,
    then elided). Both are compute-skipped by ``pl.when``.
    """
    L, nh, d = q.shape
    B, nkv = kpool.shape[1], kpool.shape[2]
    M = tables.shape[1]
    g = nh // nkv
    if dead == "clamp":
        def kv_index(l, m, tab, pos):  # noqa: ANN001 — pallas index map
            return (tab[l, jnp.minimum(m, pos[l] // B)], 0, 0, 0)
    elif dead == "null":
        def kv_index(l, m, tab, pos):  # noqa: ANN001
            return (jnp.where(m <= pos[l] // B, tab[l, m], 0), 0, 0, 0)
    else:
        raise ValueError(f"unknown dead-iteration strategy {dead!r}")
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(L, M),
        in_specs=[
            pl.BlockSpec((1, nh, d), lambda l, m, tab, pos: (l, 0, 0)),
            pl.BlockSpec((1, B, nkv, d), kv_index),
            pl.BlockSpec((1, B, nkv, d), kv_index),
        ],
        out_specs=pl.BlockSpec((1, nh, d),
                               lambda l, m, tab, pos: (l, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((nh, d), jnp.float32),
            pltpu.VMEM((nh, _LANES), jnp.float32),
            pltpu.VMEM((nh, _LANES), jnp.float32),
        ],
    )
    return pl.pallas_call(
        functools.partial(_paged_kernel, block_size=B, n_blocks=M,
                          nkv=nkv, g=g, window=window),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((L, nh, d), q.dtype),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(tables, pos, q, kpool, vpool)


# -- int8 quantized-gather variant (PT_SERVE_KV_INT8 engines) -----------------

def _paged_kernel_int8(tab_ref, pos_ref, q_ref, k_ref, v_ref, ks_ref,
                       vs_ref, o_ref, acc_ref, m_ref, l_ref, *,
                       block_size, n_blocks, nkv, g, window=0):
    """:func:`_paged_kernel` over an int8 block pool: the K/V tiles
    arrive quantized with their per-position fp32 scale tiles (same
    scalar-prefetched block-table index maps, so dead-iteration DMA
    elision is unchanged) and dequantize in-register — the fp32
    ``int8 * scale`` product feeds the same streaming-softmax math, so
    outputs match the engine's dense dequant-then-attend read
    bit-for-bit at fp32 (`quantization.dequantize_kv` is the same two
    ops)."""
    l_idx = pl.program_id(0)
    m_idx = pl.program_id(1)
    p = pos_ref[l_idx]
    B = block_size
    nb = p // B + 1  # live blocks: slots 0..p are visible

    @pl.when(m_idx == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(m_idx < nb)
    def _step():
        slots = m_idx * B + jax.lax.broadcasted_iota(jnp.int32, (g, B),
                                                     1)
        vis = slots <= p
        if window > 0:
            vis &= slots > p - window
        for j in range(nkv):
            q = q_ref[0, j * g:(j + 1) * g, :].astype(jnp.float32)
            # in-tile dequant: [B, d] int8 * [B, 1] fp32 scale
            k = k_ref[0, :, j, :].astype(jnp.float32) \
                * ks_ref[0, :, j:j + 1]
            v = v_ref[0, :, j, :].astype(jnp.float32) \
                * vs_ref[0, :, j:j + 1]
            d = q.shape[-1]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)  # [g, B]
            s = jnp.where(vis, s * (1.0 / math.sqrt(d)), NEG_INF)
            rows = slice(j * g, (j + 1) * g)
            m_prev = m_ref[rows, :1]
            l_prev = l_ref[rows, :1]
            m_new = jnp.maximum(m_prev,
                                jnp.max(s, axis=1, keepdims=True))
            pexp = jnp.exp(s - m_new)
            alpha = jnp.exp(m_prev - m_new)
            l_new = alpha * l_prev + jnp.sum(pexp, axis=1,
                                             keepdims=True)
            m_ref[rows] = jnp.broadcast_to(m_new, (g, m_ref.shape[1]))
            l_ref[rows] = jnp.broadcast_to(l_new, (g, l_ref.shape[1]))
            acc_ref[rows] = alpha * acc_ref[rows] + jax.lax.dot_general(
                pexp, v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

    @pl.when(m_idx == n_blocks - 1)
    def _fini():
        l_safe = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)


def paged_attend_int8(q, kpool, vpool, kscale, vscale, tables, pos, *,
                      window=0, dead="clamp", interpret=False):
    """:func:`paged_attend` for an int8 block pool: kpool/vpool are
    ``[num_blocks, B, nkv, d]`` int8, kscale/vscale their paired
    ``[num_blocks, B, nkv]`` fp32 amax scales (one per position per KV
    head — `quantization.quantize_kv`). Scale tiles gather through the
    SAME block-table index maps as their K/V tiles (one 3-D BlockSpec
    per scale pool) and dequantize in-tile; everything else — masking,
    dead-iteration strategies, streaming softmax — is the bf16 kernel
    unchanged. Returns ``[L, nh, d]`` in ``q.dtype``."""
    L, nh, d = q.shape
    B, nkv = kpool.shape[1], kpool.shape[2]
    M = tables.shape[1]
    g = nh // nkv
    if dead == "clamp":
        def kv_index(l, m, tab, pos):  # noqa: ANN001 — pallas index map
            return (tab[l, jnp.minimum(m, pos[l] // B)], 0, 0, 0)

        def sc_index(l, m, tab, pos):  # noqa: ANN001
            return (tab[l, jnp.minimum(m, pos[l] // B)], 0, 0)
    elif dead == "null":
        def kv_index(l, m, tab, pos):  # noqa: ANN001
            return (jnp.where(m <= pos[l] // B, tab[l, m], 0), 0, 0, 0)

        def sc_index(l, m, tab, pos):  # noqa: ANN001
            return (jnp.where(m <= pos[l] // B, tab[l, m], 0), 0, 0)
    else:
        raise ValueError(f"unknown dead-iteration strategy {dead!r}")
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(L, M),
        in_specs=[
            pl.BlockSpec((1, nh, d), lambda l, m, tab, pos: (l, 0, 0)),
            pl.BlockSpec((1, B, nkv, d), kv_index),
            pl.BlockSpec((1, B, nkv, d), kv_index),
            pl.BlockSpec((1, B, nkv), sc_index),
            pl.BlockSpec((1, B, nkv), sc_index),
        ],
        out_specs=pl.BlockSpec((1, nh, d),
                               lambda l, m, tab, pos: (l, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((nh, d), jnp.float32),
            pltpu.VMEM((nh, _LANES), jnp.float32),
            pltpu.VMEM((nh, _LANES), jnp.float32),
        ],
    )
    return pl.pallas_call(
        functools.partial(_paged_kernel_int8, block_size=B, n_blocks=M,
                          nkv=nkv, g=g, window=window),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((L, nh, d), q.dtype),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(tables, pos, q, kpool, vpool, kscale, vscale)


# -- search-harness family ----------------------------------------------------

def family_key(block_size, nkv, g, d, window=0) -> str:
    """Engagement key: the per-lane compute shape. Lane count and table
    length are deliberately OUT — per-lane work is O(live tokens)
    whatever M is, and the lane grid dim is embarrassingly parallel, so
    one measured geometry row serves any (lanes, max_seq_len) engine.
    A sliding window IS in (``_w<n>``): the windowed variant masks
    differently and its dead-DMA profile differs, so a window=0 row
    must not engage it (same variant-marker rule as
    `head_flash.shape_key`)."""
    key = f"B{block_size}_kv{nkv}_g{g}_d{d}"
    if window > 0:
        key += f"_w{window}"
    return key


class PagedAttentionFamily(search.KernelFamily):
    """Candidate axis: the dead-iteration strategy (see
    :func:`paged_attend`). Decode-phase kernel — fwd-only timing."""

    name = "paged_attention"
    grad = False
    parity_atol = 2e-5

    def shapes(self):
        # (L, M, B, nkv, g, d): the serving bench's non-smoke geometry
        # (0.44B-class decode model: 12 heads, d=128, PT_SERVE_BLOCK=16,
        # max_position_embeddings=2048 -> M=128)
        return [(8, 128, 16, 12, 1, 128)]

    def smoke_shapes(self):
        return [(3, 4, 8, 2, 2, 16)]

    def key(self, shape):
        L, M, B, nkv, g, d = shape
        return family_key(B, nkv, g, d)

    def shape_info(self, shape):
        L, M, B, nkv, g, d = shape
        return {"lanes": L, "blocks_per_lane": M, "block_size": B,
                "nkv": nkv, "group": g, "d": d}

    def candidates(self, shape):
        return [{"dead": "clamp"}, {"dead": "null"}]

    def _inputs(self, shape, dtype):
        L, M, B, nkv, g, d = shape
        nh = nkv * g
        nb = L * M + 1
        kq, kk, kv_, kp = jax.random.split(jax.random.PRNGKey(0), 4)
        q = jax.random.normal(kq, (L, nh, d), dtype)
        kpool = jax.random.normal(kk, (nb, B, nkv, d), dtype)
        vpool = jax.random.normal(kv_, (nb, B, nkv, d), dtype)
        # each lane owns a contiguous run of blocks; live lengths vary
        # across lanes so both dead strategies face real dead tails
        tables = (jnp.arange(L * M, dtype=jnp.int32).reshape(L, M) + 1)
        pos = (jax.random.randint(kp, (L,), 0, M * B)).astype(jnp.int32)
        return q, kpool, vpool, tables, pos

    def make_inputs(self, shape):
        return self._inputs(shape, jnp.bfloat16)

    def make_parity_inputs(self, shape):
        return self._inputs(shape, jnp.float32)

    def build(self, shape, config, interpret):
        def run(q, kpool, vpool, tables, pos):
            return paged_attend(q, kpool, vpool, tables, pos,
                                dead=config.get("dead", "clamp"),
                                interpret=interpret)

        return run

    def build_composite(self, shape):
        """The dense gathered read this kernel replaces — the engine's
        real `_attend_lanes` on `kpool[tables]` (serving/engine.py), so
        the composite cannot drift from production."""
        L, M, B, nkv, g, d = shape
        nh = nkv * g

        def composite(q, kpool, vpool, tables, pos):
            from ...serving.engine import _attend_lanes

            kc = kpool[tables].reshape(L, M * B, nkv, d)
            vc = vpool[tables].reshape(L, M * B, nkv, d)
            return _attend_lanes(q[:, None], kc, vc, pos[:, None], nh,
                                 nkv)[:, 0]

        return composite


search.register_family(PagedAttentionFamily())


class PagedAttentionInt8Family(PagedAttentionFamily):
    """The quantized-gather variant (`paged_attend_int8`) for int8
    block pools (``PT_SERVE_KV_INT8`` engines): int8 K/V blocks + fp32
    scale blocks gather through the same block tables and dequantize
    in-tile. Same candidate axis (dead-iteration strategy), same
    geometry keys — but its OWN tune-table family, so an int8 engine
    never engages on a bf16 measurement or vice versa. Ships
    disengaged until hwbench's ``kernel_search`` row lands hardware
    rows (docs/KERNELS.md)."""

    name = "paged_attention_int8"

    def _inputs(self, shape, dtype):
        from ...quantization import quantize_kv

        q, kpool, vpool, tables, pos = super()._inputs(shape, dtype)
        # quantize through THE shared helper — the tiles the kernel
        # dequantizes are exactly what the engine's write path produces
        kq, ks = quantize_kv(kpool)
        vq, vs = quantize_kv(vpool)
        return q, kq, vq, ks, vs, tables, pos

    def build(self, shape, config, interpret):
        def run(q, kpool, vpool, kscale, vscale, tables, pos):
            return paged_attend_int8(q, kpool, vpool, kscale, vscale,
                                     tables, pos,
                                     dead=config.get("dead", "clamp"),
                                     interpret=interpret)

        return run

    def build_composite(self, shape):
        """The engine's int8 dense read (`serving/engine.py:
        _pool_forward` with ``kv_int8``): gather int8 blocks + scales,
        `quantization.dequantize_kv`, then `_attend_lanes` — the
        production fallback this kernel replaces."""
        L, M, B, nkv, g, d = shape
        nh = nkv * g

        def composite(q, kpool, vpool, kscale, vscale, tables, pos):
            from ...quantization import dequantize_kv
            from ...serving.engine import _attend_lanes

            kc = dequantize_kv(
                kpool[tables].reshape(L, M * B, nkv, d),
                kscale[tables].reshape(L, M * B, nkv), q.dtype)
            vc = dequantize_kv(
                vpool[tables].reshape(L, M * B, nkv, d),
                vscale[tables].reshape(L, M * B, nkv), q.dtype)
            return _attend_lanes(q[:, None], kc, vc, pos[:, None], nh,
                                 nkv)[:, 0]

        return composite


search.register_family(PagedAttentionInt8Family())


# -- lowering self-check + registry hookup ------------------------------------

def check_lowering():
    """Mosaic-lower the decode kernel for platform 'tpu' at the serving
    geometries (engine default B=16 and a lane-tile-friendly B=128,
    GQA, both dead-iteration strategies) — any host, no chip."""
    for (L, M, B, nkv, g, d), dead in (
            ((8, 32, 16, 12, 1, 128), "clamp"),
            ((8, 32, 16, 12, 1, 128), "null"),
            ((4, 8, 128, 4, 2, 128), "clamp")):
        nh = nkv * g
        q = jnp.zeros((L, nh, d), jnp.bfloat16)
        pool = jnp.zeros((L * M + 1, B, nkv, d), jnp.bfloat16)
        tables = jnp.zeros((L, M), jnp.int32)
        pos = jnp.zeros((L,), jnp.int32)

        def run(q, kpool, vpool, tables, pos, _dead=dead):
            return paged_attend(q, kpool, vpool, tables, pos,
                                dead=_dead)

        _jax_export.export(jax.jit(run), platforms=["tpu"])(
            q, pool, pool, tables, pos)


def check_lowering_int8():
    """Mosaic-lower the quantized-gather kernel for platform 'tpu' at
    the serving geometries (same sweep as :func:`check_lowering` — both
    dead-iteration strategies, GQA, engine-default and lane-tile block
    sizes) — any host, no chip."""
    for (L, M, B, nkv, g, d), dead in (
            ((8, 32, 16, 12, 1, 128), "clamp"),
            ((8, 32, 16, 12, 1, 128), "null"),
            ((4, 8, 128, 4, 2, 128), "clamp")):
        nh = nkv * g
        q = jnp.zeros((L, nh, d), jnp.bfloat16)
        pool = jnp.zeros((L * M + 1, B, nkv, d), jnp.int8)
        scale = jnp.zeros((L * M + 1, B, nkv), jnp.float32)
        tables = jnp.zeros((L, M), jnp.int32)
        pos = jnp.zeros((L,), jnp.int32)

        def run(q, kpool, vpool, kscale, vscale, tables, pos,
                _dead=dead):
            return paged_attend_int8(q, kpool, vpool, kscale, vscale,
                                     tables, pos, dead=_dead)

        _jax_export.export(jax.jit(run), platforms=["tpu"])(
            q, pool, pool, scale, scale, tables, pos)


def register(platform="tpu"):
    """Registry entries exist for the lowering pre-flight only: the
    serving engine calls :func:`paged_attend` /
    :func:`paged_attend_int8` directly behind its measured-engagement
    gate, never by op-name dispatch."""
    fn = paged_attend
    fn.check_lowering = check_lowering
    registry.register_kernel("paged_attention", platform)(fn)
    fn8 = paged_attend_int8
    fn8.check_lowering = check_lowering_int8
    registry.register_kernel("paged_attention_int8", platform)(fn8)
    return fn
