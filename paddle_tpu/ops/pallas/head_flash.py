"""Head-BATCHED flash attention: native ``[b, s, h, d]`` Pallas kernels.

The round-5 negative result (PERF.md "native [b,s,h,d] flash blocks
don't lower") established that a per-head singleton BlockSpec
``(1, block_q, 1, d)`` violates Mosaic's last-two-dims tiling rule, so
the bhsd kernels in ``flash_attention.py`` require a structural
``[b,s,h,d] -> [b·h,s,d]`` transpose pair around every attention call —
part of the profiled 8.4% data-movement slice. This module implements
the remaining idea from that write-up: a head-batched kernel whose grid
drops the head dimension entirely. Blocks carry ALL heads
(``(1, block_q, h, d)`` — the last two dims equal the array dims, which
Mosaic accepts), and every head's streaming-softmax state lives in VMEM
scratch at once. Heads are sliced STATICALLY inside the kernel (an
unrolled per-head loop of strided sublane reads and 2-D dots): the
``[h, bq, d] × [h, bk, d]`` batched-dot formulation PERF.md sketched
needs an in-kernel major-dim transpose, and Mosaic (jax 0.4.37) lowers
only 2-D transposes — the same physical-layout constraint class as the
original negative result, dodged rather than fought. The HBM-level
transposes disappear; the price is strided per-head VMEM access and an
h-times-larger VMEM footprint — exactly the trade only a hardware
measurement can judge, so the kernel ships **disengaged by default**
and flips on only via a persisted ``flash_headbatch`` row in the search
harness's tune table (``ops/pallas/search.py``; engagement =
measured-faster-than-the-best-current-path only).

Feature parity with the bhsd kernels: causal (bottom-right aligned),
sliding window, GQA (grouped in-tile — no KV repeat materialization),
in-kernel dropout (the SAME counter-hash mask bits as
``flash_attention._keep_mask``, so the two kernels drop identical
elements for one seed), and the additive key-padding mask (``[b,1,sk]``
— per batch row here; its cotangent reduces over heads in-kernel).
Parity is proven in interpret mode against the XLA composite
(tests/test_head_flash.py) and the dropout variant against the bhsd
kernel's identical mask.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...framework.jax_compat import export as _jax_export, tpu_compiler_params
from .. import registry
from . import search
from .flash_attention import (
    NEG_INF, _LANES, _causal_mask, _keep_mask, _pick_block, _tile_live,
    _unpack,
)

__all__ = ["hb_flash", "shape_key", "check_lowering", "register"]


def _hb_fwd_kernel(*refs, causal, scale, offset, n_kb, h, h_kv, window=0,
                   dropout=0.0, has_kmask=False):
    (seed_ref, km_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
     acc_ref, m_ref, l_ref) = _unpack(refs, dropout, has_kmask, 3)
    b_idx = pl.program_id(0)
    q_idx = pl.program_id(1)
    k_idx = pl.program_id(2)
    block_q, d = q_ref.shape[1], q_ref.shape[3]
    block_k = k_ref.shape[1]
    g = h // h_kv

    @pl.when(k_idx == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def _step():
        # heads are sliced STATICALLY from the all-heads block (strided
        # sublane reads — Mosaic lowers these; in-kernel major-dim
        # transposes to an [h, bq, d]-batched-dot layout do NOT (only
        # 2-D transposes have a lowering rule), the same physical-layout
        # constraint class as the round-5 negative result). The loop is
        # unrolled at trace time; every head's state stays resident.
        for i in range(h):
            q = q_ref[0, :, i, :].astype(jnp.float32) * scale  # [bq, d]
            k = k_ref[0, :, i // g, :].astype(jnp.float32)     # [bk, d]
            v = v_ref[0, :, i // g, :].astype(jnp.float32)
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)  # [bq, bk]
            if causal:
                s = _causal_mask(s, q_idx, k_idx, block_q, block_k,
                                 offset, window)
            if has_kmask:
                s = s + km_ref[0]  # [1, bk] additive row
            m_prev = m_ref[i, :, :1]
            l_prev = l_ref[i, :, :1]
            m_new = jnp.maximum(m_prev,
                                jnp.max(s, axis=1, keepdims=True))
            p = jnp.exp(s - m_new)
            alpha = jnp.exp(m_prev - m_new)
            l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
            m_ref[i] = jnp.broadcast_to(m_new, m_ref.shape[1:])
            l_ref[i] = jnp.broadcast_to(l_new, l_ref.shape[1:])
            if dropout > 0.0:
                # the bhsd kernel's grid row is the flattened b·h + i
                # head index; feeding the same index reproduces its
                # exact mask bits (pure function of global coords)
                keep = _keep_mask(seed_ref, b_idx * h + i, q_idx, k_idx,
                                  block_q, block_k, dropout)
                p_acc = jnp.where(keep, p * (1.0 / (1.0 - dropout)),
                                  0.0)
            else:
                p_acc = p
            acc_ref[i] = alpha * acc_ref[i] + jax.lax.dot_general(
                p_acc, v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

    if causal:
        pl.when(_tile_live(q_idx, k_idx, block_q, block_k, offset,
                           window))(_step)
    else:
        _step()

    @pl.when(k_idx == n_kb - 1)
    def _fini():
        for i in range(h):
            m = m_ref[i, :, :1]
            l_safe = jnp.maximum(l_ref[i, :, :1], 1e-30)
            valid = m > NEG_INF * 0.5
            o_ref[0, :, i, :] = jnp.where(
                valid, acc_ref[i] / l_safe, 0.0).astype(o_ref.dtype)
            lse_ref[0, :, i, :] = jnp.broadcast_to(
                m + jnp.log(l_safe), (block_q, _LANES))


def _hb_dq_kernel(*refs, causal, scale, offset, n_kb, h, h_kv, window=0,
                  dropout=0.0, has_kmask=False):
    (seed_ref, km_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
     dq_ref, dq_acc_ref) = _unpack(refs, dropout, has_kmask, 6)
    b_idx = pl.program_id(0)
    q_idx = pl.program_id(1)
    k_idx = pl.program_id(2)
    block_q, d = q_ref.shape[1], q_ref.shape[3]
    block_k = k_ref.shape[1]
    g = h // h_kv

    @pl.when(k_idx == 0)
    def _init():
        dq_acc_ref[...] = jnp.zeros_like(dq_acc_ref)

    def _step():
        for i in range(h):
            q = q_ref[0, :, i, :].astype(jnp.float32)
            k = k_ref[0, :, i // g, :].astype(jnp.float32)
            v = v_ref[0, :, i // g, :].astype(jnp.float32)
            do = do_ref[0, :, i, :].astype(jnp.float32)
            lse = lse_ref[0, :, i, :1]
            delta = delta_ref[0, :, i, :1]
            s = scale * jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            if causal:
                s = _causal_mask(s, q_idx, k_idx, block_q, block_k,
                                 offset, window)
            if has_kmask:
                s = s + km_ref[0]
            p = jnp.where(lse > NEG_INF * 0.5, jnp.exp(s - lse), 0.0)
            dp = jax.lax.dot_general(
                do, v, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            if dropout > 0.0:
                keep = _keep_mask(seed_ref, b_idx * h + i, q_idx, k_idx,
                                  block_q, block_k, dropout)
                dp = jnp.where(keep, dp * (1.0 / (1.0 - dropout)), 0.0)
            ds = p * (dp - delta) * scale
            dq_acc_ref[i] += jax.lax.dot_general(
                ds, k, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

    if causal:
        pl.when(_tile_live(q_idx, k_idx, block_q, block_k, offset,
                           window))(_step)
    else:
        _step()

    @pl.when(k_idx == n_kb - 1)
    def _fini():
        for i in range(h):
            dq_ref[0, :, i, :] = dq_acc_ref[i].astype(dq_ref.dtype)


def _hb_dkv_kernel(*refs, causal, scale, offset, n_qb, h, h_kv, window=0,
                   dropout=0.0, has_kmask=False):
    """dk/dv accumulate over the q-minor grid dim; GQA reduces in-tile
    (all g query heads of a KV head sit in the same block). The kmask
    cotangent additionally reduces over heads — the mask is per BATCH
    row here, unlike the bhsd kernel's per-query-head broadcast."""
    if has_kmask:
        (seed_ref, km_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
         delta_ref, dk_ref, dv_ref, dm_ref, dk_acc_ref, dv_acc_ref,
         dm_acc_ref) = _unpack(refs, dropout, True, 6)
    else:
        (seed_ref, km_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
         delta_ref, dk_ref, dv_ref, dk_acc_ref, dv_acc_ref) = _unpack(
            refs, dropout, False, 6)
        dm_ref = dm_acc_ref = None
    b_idx = pl.program_id(0)
    k_idx = pl.program_id(1)
    q_idx = pl.program_id(2)
    block_q, d = q_ref.shape[1], q_ref.shape[3]
    block_k = k_ref.shape[1]
    g = h // h_kv

    @pl.when(q_idx == 0)
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)
        if has_kmask:
            dm_acc_ref[...] = jnp.zeros_like(dm_acc_ref)

    def _step():
        for i in range(h):
            q = q_ref[0, :, i, :].astype(jnp.float32)
            k = k_ref[0, :, i // g, :].astype(jnp.float32)
            v = v_ref[0, :, i // g, :].astype(jnp.float32)
            do = do_ref[0, :, i, :].astype(jnp.float32)
            lse = lse_ref[0, :, i, :1]
            delta = delta_ref[0, :, i, :1]
            s = scale * jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            if causal:
                s = _causal_mask(s, q_idx, k_idx, block_q, block_k,
                                 offset, window)
            if has_kmask:
                s = s + km_ref[0]
            p = jnp.where(lse > NEG_INF * 0.5, jnp.exp(s - lse), 0.0)
            if dropout > 0.0:
                keep = _keep_mask(seed_ref, b_idx * h + i, q_idx, k_idx,
                                  block_q, block_k, dropout)
                dmask = jnp.where(keep, 1.0 / (1.0 - dropout), 0.0)
                pd = p * dmask
            else:
                dmask = None
                pd = p
            # GQA reduces in-tile: the g query heads of kv head i//g
            # accumulate into the same scratch slice
            dv_acc_ref[i // g] += jax.lax.dot_general(
                pd, do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(
                do, v, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            if dropout > 0.0:
                dp = dp * dmask
            ds = p * (dp - delta) * scale
            dk_acc_ref[i // g] += jax.lax.dot_general(
                ds, q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            if has_kmask:
                # additive-bias cotangent summed over heads AND rows
                # (the mask rides per BATCH row here):
                # d(mask_j) = sum_{h,i} ds_hij / scale
                dm_acc_ref[0:1, :] += jnp.sum(ds / scale, axis=0,
                                              keepdims=True)

    if causal:
        pl.when(_tile_live(q_idx, k_idx, block_q, block_k, offset,
                           window))(_step)
    else:
        _step()

    @pl.when(q_idx == n_qb - 1)
    def _fini():
        for j in range(h_kv):
            dk_ref[0, :, j, :] = dk_acc_ref[j].astype(dk_ref.dtype)
            dv_ref[0, :, j, :] = dv_acc_ref[j].astype(dv_ref.dtype)
        if has_kmask:
            dm_ref[0] = dm_acc_ref[0:1, :].astype(dm_ref.dtype)


# -- pallas_call plumbing -----------------------------------------------------

def _hb_fwd(q, k, v, causal, scale, interpret, block_q=None,
            block_k=None, window=0, seed=None, dropout=0.0, kmask=None):
    """q: [b, sq, h, d]; k/v: [b, sk, h_kv, d] with h % h_kv == 0.
    Returns (out [b, sq, h, d], lse [b, sq, h, _LANES])."""
    b, sq, h, d = q.shape
    sk, h_kv = k.shape[1], k.shape[2]
    block_q = block_q or _pick_block(sq, 256)
    block_k = block_k or _pick_block(sk, 256)
    n_kb = sk // block_k
    grid = (b, sq // block_q, n_kb)
    kernel = functools.partial(
        _hb_fwd_kernel, causal=causal, scale=scale, offset=sk - sq,
        n_kb=n_kb, h=h, h_kv=h_kv, window=window, dropout=dropout,
        has_kmask=kmask is not None)
    in_specs = [
        pl.BlockSpec((1, block_q, h, d), lambda bb, i, j: (bb, i, 0, 0)),
        pl.BlockSpec((1, block_k, h_kv, d),
                     lambda bb, i, j: (bb, j, 0, 0)),
        pl.BlockSpec((1, block_k, h_kv, d),
                     lambda bb, i, j: (bb, j, 0, 0)),
    ]
    args = (q, k, v)
    if kmask is not None:
        in_specs = in_specs + [
            pl.BlockSpec((1, 1, block_k), lambda bb, i, j: (bb, 0, j))]
        args = args + (kmask,)
    if dropout > 0.0:
        in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)] + in_specs
        args = (seed,) + args
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, h, d),
                         lambda bb, i, j: (bb, i, 0, 0)),
            pl.BlockSpec((1, block_q, h, _LANES),
                         lambda bb, i, j: (bb, i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, sq, h, d), q.dtype),
            jax.ShapeDtypeStruct((b, sq, h, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((h, block_q, d), jnp.float32),
            pltpu.VMEM((h, block_q, _LANES), jnp.float32),
            pltpu.VMEM((h, block_q, _LANES), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=int(4 * b * h * sq * sk * d * (0.5 if causal else 1.0)),
            bytes_accessed=int(q.size * 2 + k.size * 2 + v.size * 2),
            transcendentals=int(b * h * sq * sk),
        ),
    )(*args)
    return out, lse


def _hb_bwd_impl(q, k, v, out, lse, g_out, causal, scale, interpret,
                 block_q, block_k, window, seed, dropout, kmask=None):
    b, sq, h, d = q.shape
    sk, h_kv = k.shape[1], k.shape[2]
    block_q = block_q or _pick_block(sq, 256)
    block_k = block_k or _pick_block(sk, 256)
    n_qb = sq // block_q
    n_kb = sk // block_k
    offset = sk - sq
    g_out = g_out.astype(q.dtype)
    delta = jnp.broadcast_to(
        jnp.sum(g_out.astype(jnp.float32) * out.astype(jnp.float32),
                axis=-1, keepdims=True),
        (b, sq, h, _LANES))

    q_spec = pl.BlockSpec((1, block_q, h, d),
                          lambda bb, i, j: (bb, i, 0, 0))
    kv_spec = pl.BlockSpec((1, block_k, h_kv, d),
                           lambda bb, i, j: (bb, j, 0, 0))
    row_spec = pl.BlockSpec((1, block_q, h, _LANES),
                            lambda bb, i, j: (bb, i, 0, 0))
    dq_specs = [q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec]
    dq_args = (q, k, v, g_out, lse, delta)
    if kmask is not None:
        km_spec = pl.BlockSpec((1, 1, block_k),
                               lambda bb, i, j: (bb, 0, j))
        dq_specs = dq_specs + [km_spec]
        dq_args = dq_args + (kmask,)
    if dropout > 0.0:
        dq_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)] + dq_specs
        dq_args = (seed,) + dq_args
    dq = pl.pallas_call(
        functools.partial(_hb_dq_kernel, causal=causal, scale=scale,
                          offset=offset, n_kb=n_kb, h=h, h_kv=h_kv,
                          window=window, dropout=dropout,
                          has_kmask=kmask is not None),
        grid=(b, n_qb, n_kb),
        in_specs=dq_specs,
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((b, sq, h, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((h, block_q, d), jnp.float32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*dq_args)

    # dkv grid: (b, k-blocks, q-minor); q heads reduce in-tile
    q_spec_t = pl.BlockSpec((1, block_q, h, d),
                            lambda bb, j, i: (bb, i, 0, 0))
    kv_spec_t = pl.BlockSpec((1, block_k, h_kv, d),
                             lambda bb, j, i: (bb, j, 0, 0))
    row_spec_t = pl.BlockSpec((1, block_q, h, _LANES),
                              lambda bb, j, i: (bb, i, 0, 0))
    dkv_specs = [q_spec_t, kv_spec_t, kv_spec_t, q_spec_t, row_spec_t,
                 row_spec_t]
    dkv_args = (q, k, v, g_out, lse, delta)
    dkv_out_specs = [kv_spec_t, kv_spec_t]
    dkv_out_shape = [
        jax.ShapeDtypeStruct((b, sk, h_kv, d), k.dtype),
        jax.ShapeDtypeStruct((b, sk, h_kv, d), v.dtype),
    ]
    dkv_scratch = [
        pltpu.VMEM((h_kv, block_k, d), jnp.float32),
        pltpu.VMEM((h_kv, block_k, d), jnp.float32),
    ]
    if kmask is not None:
        km_spec_t = pl.BlockSpec((1, 1, block_k),
                                 lambda bb, j, i: (bb, 0, j))
        dkv_specs = dkv_specs + [km_spec_t]
        dkv_args = dkv_args + (kmask,)
        dkv_out_specs = dkv_out_specs + [km_spec_t]
        dkv_out_shape = dkv_out_shape + [
            jax.ShapeDtypeStruct((b, 1, sk), jnp.float32)]
        dkv_scratch = dkv_scratch + [
            pltpu.VMEM((8, block_k), jnp.float32)]
    if dropout > 0.0:
        dkv_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)] + dkv_specs
        dkv_args = (seed,) + dkv_args
    outs = pl.pallas_call(
        functools.partial(_hb_dkv_kernel, causal=causal, scale=scale,
                          offset=offset, n_qb=n_qb, h=h, h_kv=h_kv,
                          window=window, dropout=dropout,
                          has_kmask=kmask is not None),
        grid=(b, n_kb, n_qb),
        in_specs=dkv_specs,
        out_specs=dkv_out_specs,
        out_shape=dkv_out_shape,
        scratch_shapes=dkv_scratch,
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*dkv_args)
    if kmask is not None:
        dk, dv, dmask = outs
        return dq, dk, dv, dmask
    dk, dv = outs
    return dq, dk, dv, None


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10, 11))
def _hb_call(q, k, v, seed, kmask, causal, scale, interpret,
             block_q=None, block_k=None, window=0, dropout=0.0):
    out, _ = _hb_fwd(q, k, v, causal, scale, interpret, block_q, block_k,
                     window, seed=seed, dropout=dropout, kmask=kmask)
    return out


def _hb_call_fwd_rule(q, k, v, seed, kmask, causal, scale, interpret,
                      block_q=None, block_k=None, window=0, dropout=0.0):
    out, lse = _hb_fwd(q, k, v, causal, scale, interpret, block_q,
                       block_k, window, seed=seed, dropout=dropout,
                       kmask=kmask)
    return out, (q, k, v, seed, kmask, out, lse)


def _hb_call_bwd_rule(causal, scale, interpret, block_q, block_k, window,
                      dropout, res, g_out):
    q, k, v, seed, kmask, out, lse = res
    dq, dk, dv, dmask = _hb_bwd_impl(q, k, v, out, lse, g_out, causal,
                                     scale, interpret, block_q, block_k,
                                     window, seed, dropout, kmask=kmask)
    return dq, dk, dv, None, dmask


_hb_call.defvjp(_hb_call_fwd_rule, _hb_call_bwd_rule)


def hb_flash(q, k, v, seed=None, kmask=None, causal=False, scale=None,
             interpret=False, block_q=None, block_k=None, window=0,
             dropout=0.0):
    """The head-batched flash entry: q [b, sq, h, d], k/v
    [b, sk, h_kv, d], additive ``kmask`` [b, 1, sk] or None, ``seed``
    int32[2] or None (in-kernel dropout). Returns [b, sq, h, d] — no
    layout transposes anywhere."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    return _hb_call(q, k, v, seed, kmask, causal, scale, interpret,
                    block_q, block_k, window, dropout)


# -- search-harness family ----------------------------------------------------

def shape_key(b, sq, sk, h, h_kv, d, causal, dropout=False,
              kmask=False) -> str:
    """Exact engagement key. Variant markers (dropout / key mask) are
    part of the key: a base-shape measurement says nothing about the
    variant's extra VPU/HBM work, so variants stay disengaged until
    their own rows exist (measurement-first, like the flash dropout
    variant rows)."""
    key = f"b{b}_s{sq}x{sk}_h{h}"
    if h_kv != h:
        key += f"kv{h_kv}"
    key += f"_d{d}_{'c' if causal else 'f'}"
    if dropout:
        key += "_drop"
    if kmask:
        key += "_km"
    return key


def vmem_bytes(shape, config, dtype_bytes=2) -> int:
    """Forward-pass VMEM footprint estimate for a candidate: all heads'
    streaming state + double-buffered operand tiles. The candidate
    pruner's feasibility bound (the whole reason small block_q exists in
    this family's space — PERF.md round-5 conclusion (b))."""
    b, sq, sk, h, h_kv, d, causal = shape
    bq, bk = config["block_q"], config["block_k"]
    scratch = h * bq * (d + 2 * _LANES) * 4
    tiles = (bq * h * d + 2 * bk * h_kv * d) * dtype_bytes * 2  # dbl-buf
    outs = bq * h * d * dtype_bytes + bq * h * _LANES * 4
    return scratch + tiles + outs


class HeadBatchFlashFamily(search.KernelFamily):
    """Search space: (block_q, block_k) under a VMEM-budget prune —
    with every head's state resident, feasibility (not preference)
    bounds block_q."""

    name = "flash_headbatch"
    grad = True
    parity_atol = 2e-5
    vmem_budget = 12 * 2 ** 20  # leave headroom of the ~16 MB VMEM

    def shapes(self):
        # (b, sq, sk, h, h_kv, d, causal): the bench-relevant geometries
        # — headline 0.44B Llama, 7B-geometry legs, BERT-base encoder
        return [
            (8, 1024, 1024, 12, 12, 128, True),
            (4, 1024, 1024, 32, 32, 128, True),
            (64, 512, 512, 12, 12, 64, False),
        ]

    def smoke_shapes(self):
        return [(2, 64, 64, 4, 2, 32, True)]

    def key(self, shape):
        b, sq, sk, h, h_kv, d, causal = shape
        return shape_key(b, sq, sk, h, h_kv, d, causal)

    def shape_info(self, shape):
        b, sq, sk, h, h_kv, d, causal = shape
        return {"b": b, "sq": sq, "sk": sk, "h": h, "h_kv": h_kv,
                "d": d, "causal": causal}

    def candidates(self, shape):
        b, sq, sk, h, h_kv, d, causal = shape
        out = []
        for bq in (64, 128, 256, 512):
            if bq > sq or sq % bq:
                continue
            for bk in (64, 128, 256, 512):
                if bk > sk or sk % bk:
                    continue
                cand = {"block_q": bq, "block_k": bk}
                if vmem_bytes(shape, cand) <= self.vmem_budget:
                    out.append(cand)
        if not out:
            out.append({"block_q": min(sq, 64), "block_k": min(sk, 64)})
        return out

    def _inputs(self, shape, dtype):
        b, sq, sk, h, h_kv, d, causal = shape
        q = jax.random.normal(jax.random.PRNGKey(0), (b, sq, h, d),
                              dtype)
        k = jax.random.normal(jax.random.PRNGKey(1), (b, sk, h_kv, d),
                              dtype)
        v = jax.random.normal(jax.random.PRNGKey(2), (b, sk, h_kv, d),
                              dtype)
        return q, k, v

    def make_inputs(self, shape):
        return self._inputs(shape, jnp.bfloat16)

    def make_parity_inputs(self, shape):
        # fp32 parity: the filter must see math errors, not bf16
        # quantization noise
        return self._inputs(shape, jnp.float32)

    def build(self, shape, config, interpret):
        b, sq, sk, h, h_kv, d, causal = shape
        scale = 1.0 / math.sqrt(d)

        def run(q, k, v):
            return _hb_call(q, k, v, None, None, causal, scale,
                            interpret, config.get("block_q"),
                            config.get("block_k"), 0, 0.0)

        return run

    def build_composite(self, shape):
        """The path head-batching actually replaces at this shape — the
        CURRENT production route through `flash_attention_kernel`:
        where the bhsd kernel has a measured win, that's transpose ->
        tuned bhsd flash -> transpose (the structural data movement
        this family exists to kill); elsewhere it's the XLA composite
        on the native layout. Beating this (not just the XLA fallback)
        is the engagement bar, so a head-batch row can never engage a
        slower-than-bhsd path."""
        b, sq, sk, h, h_kv, d, causal = shape
        g = h // h_kv
        scale = 1.0 / math.sqrt(d)
        from . import autotune as _tune
        from .flash_attention import _flash_bhsd

        if _tune.kernel_beats_composite(sq, sk, d, causal):
            bq, bk = _tune.best_blocks(sq, sk, d, causal)
            interpret = jax.default_backend() == "cpu"

            def composite(q, k, v):
                qt = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
                kt = k.transpose(0, 2, 1, 3).reshape(b * h_kv, sk, d)
                vt = v.transpose(0, 2, 1, 3).reshape(b * h_kv, sk, d)
                out = _flash_bhsd(qt, kt, vt, causal, scale, interpret,
                                  bq, bk)
                return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)

            return composite

        def composite(q, k, v):
            qg = q.astype(jnp.float32).reshape(b, sq, h_kv, g, d)
            s = jnp.einsum("bskgd,btkd->bkgst", qg,
                           k.astype(jnp.float32)) * scale
            if causal:
                mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
                s = jnp.where(mask, s, NEG_INF)
            p = jax.nn.softmax(s, axis=-1)
            out = jnp.einsum("bkgst,btkd->bskgd", p,
                             v.astype(jnp.float32))
            return out.reshape(b, sq, h, d).astype(q.dtype)

        return composite


search.register_family(HeadBatchFlashFamily())


# -- lowering self-check + registry hookup ------------------------------------

def check_lowering():
    """Mosaic-lower fwd+bwd for platform 'tpu' at the contract shapes
    (head-batched blocks: MHA d=128, GQA, BERT-shape d=64, and the
    dropout + key-mask variants) — runs on any host via jax.export, no
    chip needed. The round-5 negative result was exactly a lowering
    failure this check exists to catch before a hardware run."""
    shapes = [
        (2, 512, 512, 8, 8, 128, True),
        (2, 512, 512, 8, 4, 128, True),   # GQA in-tile grouping
        (2, 512, 512, 12, 12, 64, False),  # BERT-base head_dim
    ]
    for b, sq, sk, h, h_kv, d, causal in shapes:
        q = jnp.zeros((b, sq, h, d), jnp.bfloat16)
        kv = jnp.zeros((b, sk, h_kv, d), jnp.bfloat16)
        scale = 1.0 / math.sqrt(d)

        def fwd(q, k, v, _c=causal, _s=scale):
            return hb_flash(q, k, v, causal=_c, scale=_s)

        def bwd(q, k, v, _c=causal, _s=scale):
            return jax.grad(
                lambda *a: hb_flash(*a, causal=_c, scale=_s).astype(
                    jnp.float32).sum(),
                argnums=(0, 1, 2))(q, k, v)

        _jax_export.export(jax.jit(fwd), platforms=["tpu"])(q, kv, kv)
        _jax_export.export(jax.jit(bwd), platforms=["tpu"])(q, kv, kv)

    # key-padding mask + in-kernel dropout variants
    q = jnp.zeros((2, 512, 8, 128), jnp.bfloat16)
    kv = jnp.zeros((2, 512, 8, 128), jnp.bfloat16)
    km = jnp.zeros((2, 1, 512), jnp.float32)
    seed = jnp.zeros((2,), jnp.int32)
    scale = 1.0 / math.sqrt(128.0)

    def masked_bwd(q, k, v, km):
        return jax.grad(
            lambda *a: hb_flash(*a, kmask=km, causal=False,
                                scale=scale).astype(jnp.float32).sum(),
            argnums=(0, 1, 2))(q, k, v)

    def drop_bwd(q, k, v, seed):
        return jax.grad(
            lambda *a: hb_flash(*a, seed, causal=True, scale=scale,
                                dropout=0.1).astype(jnp.float32).sum(),
            argnums=(0, 1, 2))(q, k, v)

    _jax_export.export(jax.jit(masked_bwd), platforms=["tpu"])(q, kv, kv,
                                                               km)
    _jax_export.export(jax.jit(drop_bwd), platforms=["tpu"])(q, kv, kv,
                                                             seed)


def register(platform="tpu"):
    """Registry entry exists for the lowering pre-flight only: the
    head-batched kernel is dispatched from `flash_attention_kernel`
    (behind its `flash_headbatch` engagement row), never looked up by
    op name."""
    fn = hb_flash
    fn.check_lowering = check_lowering
    registry.register_kernel("flash_attention_headbatch", platform)(fn)
    return fn
