"""Pallas TPU kernel overrides (the reference's hand-written CUDA/CUTLASS
kernel layer — `phi/kernels/fusion/`, external flashattn — reimagined as
Mosaic kernels). Importing this package registers every kernel for platform
'tpu'; the registry only selects them when running on TPU."""
from . import flash_attention as _fa

_fa.register(platform="tpu")

flash_attention_kernel = _fa.flash_attention_kernel
register_flash_attention = _fa.register
